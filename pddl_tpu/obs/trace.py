"""Per-request tracing for the serving stack (Dapper-style spans).

`ServeMetrics` answers "how is the fleet doing" in aggregate; it cannot
answer "what happened to THIS request" — which queue wait it paid,
whether its prefix matched, how many prefill chunks it cost, which tick
each token came from, whether it was retried, replayed, or rode through
a degraded window. Dapper (Sigelman et al., 2010) is the model: one
trace per request, one root span from submit to finish, and every
lifecycle transition recorded as a timestamped span EVENT, so the whole
timeline — queue → admission → prefix match → prefill chunks → decode
ticks → retries/replays → finish — reconstructs from the span record
alone. Orca's (OSDI '22) iteration-level decisions are exactly what the
engine-level events capture: faults, retries, replays, and degraded
transitions carry the same ``(step, site)`` coordinates the fault plan
(`serve/faults.py`) injects at, so a chaos test can match injections to
observations one-for-one.

Cost discipline (the reason this file owns no clever machinery):

- **Disabled is free.** The engine's default tracer is
  :data:`NULL_TRACER`, whose every hook is a no-op method — no
  per-tick allocation, no branch beyond the call itself, and the test
  suite pins "zero allocations attributed to this module" with
  ``tracemalloc``. Enabling tracing swaps ONE object on the engine.
- **Never a device sync.** Hooks receive host-side scalars the engine
  already computed (wall times from ``perf_counter`` around the async
  dispatch, token ids already fetched by the streaming path); no hook
  may touch a device array.

Export: finished span records go to an optional ``sink`` (anything
with a ``write(record: dict)`` — `obs/export.py`'s
:class:`~pddl_tpu.obs.export.JsonlEventLog` — or a plain callable) and
are retained on :attr:`RequestTracer.finished` for in-process readers;
engine-level events (faults, retries, degraded flips) are emitted as
``kind="engine_event"`` records and retained on
:attr:`RequestTracer.engine_events`.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

SCHEMA_VERSION = 1


class Span:
    """One request's timeline: trace/span ids, monotonic start/end, and
    an ordered list of timestamped events. Events past
    ``max_events`` are counted (``events_dropped``) instead of stored,
    so one million-token stream cannot balloon the tracer."""

    __slots__ = ("trace_id", "span_id", "name", "request_id", "start_s",
                 "end_s", "finish_reason", "attrs", "events",
                 "events_dropped", "_max_events", "last_requeue_s",
                 "decode_events")

    def __init__(self, trace_id: str, span_id: str, name: str,
                 request_id: int, start_s: float,
                 max_events: int = 4096):
        self.trace_id = trace_id
        self.span_id = span_id
        self.name = name
        self.request_id = request_id
        self.start_s = start_s
        self.end_s: Optional[float] = None
        self.finish_reason: Optional[str] = None
        self.attrs: Dict[str, object] = {}
        self.events: List[Dict[str, object]] = []
        self.events_dropped = 0
        self._max_events = max_events
        # Stamped by each replay requeue so the NEXT admission's
        # queue_wait_s measures time since the requeue, not since the
        # original submit (which would read as scheduler backlog).
        self.last_requeue_s: Optional[float] = None
        # High-frequency decode events get their OWN budget (tracked by
        # the tracer) so a long stream can never crowd the rare
        # lifecycle events (replay, re-admission, deadline_shed) out of
        # the overall cap.
        self.decode_events = 0

    def event(self, t_s: float, name: str, **attrs) -> None:
        if len(self.events) >= self._max_events:
            self.events_dropped += 1
            return
        ev: Dict[str, object] = {"t_s": t_s, "name": name}
        if attrs:
            ev.update(attrs)
        self.events.append(ev)

    def finish(self, t_s: float, reason: str) -> None:
        self.end_s = t_s
        self.finish_reason = reason

    def to_record(self) -> Dict[str, object]:
        """The schema-versioned JSONL line (`obs/export.py`)."""
        return {
            "schema": SCHEMA_VERSION,
            "kind": "span",
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "name": self.name,
            "request_id": self.request_id,
            "start_s": self.start_s,
            "end_s": self.end_s,
            "duration_s": (None if self.end_s is None
                           else self.end_s - self.start_s),
            "finish_reason": self.finish_reason,
            "attrs": dict(self.attrs),
            "events": list(self.events),
            "events_dropped": self.events_dropped,
        }


class NullTracer:
    """The engine's default tracer: every hook is a no-op.

    The hook surface below IS the tracing contract — `engine.py` calls
    exactly these methods at exactly these lifecycle points, and any
    real tracer implements the same names. Keeping the disabled path a
    plain method call (no ``if tracer:`` branches scattered through the
    engine) is what makes "tracing off" indistinguishable from the
    pre-observability engine: no allocation, no conditional state, and
    the test suite pins zero ``tracemalloc`` blocks from this module
    across a full engine run.
    """

    enabled = False

    def on_submit(self, handle, queue_depth: int) -> None:
        """Request accepted into the queue."""

    def on_admit(self, handle, slot: int, replay: bool) -> None:
        """Popped from the queue into a slot (admission starts)."""

    def on_prefix_match(self, handle, blocks_hit: int,
                        tokens_saved: int) -> None:
        """Prefix-cache lookup result for this admission."""

    def on_prefill_chunk(self, handle, site: str, start: int, width: int,
                         wall_s: float) -> None:
        """One admission device dispatch (gather / chunk prefill)."""

    def on_first_token(self, handle, ttft_s: float) -> None:
        """First token sampled (TTFT settles)."""

    def on_token(self, handle, step: int) -> None:
        """One decode-tick token appended to the stream."""

    def on_tick(self, step: int, queue_depth: int, live_slots: int,
                new_tokens: int, wall_s: float) -> None:
        """One engine step completed (engine-level, not per-request)."""

    def on_retry(self, step: int, site: str, attempt: int) -> None:
        """A transient device failure is being retried."""

    def on_fault_injected(self, step: int, site: str, kind: str) -> None:
        """The fault plan fired (wired via ``FaultPlan.on_inject``)."""

    def on_replay(self, handle, step: int, requeued: bool) -> None:
        """Slot KV lost; request requeued for rebuild (or failed)."""

    def on_degraded_entry(self, step: int) -> None:
        """OOM flipped the engine degraded."""

    def on_degraded_exit(self, step: int, duration_s: float) -> None:
        """Degraded window closed (cache re-armed)."""

    def on_deadline_shed(self, handle) -> None:
        """Queued request shed at pop time (deadline expired)."""

    def on_preempt(self, handle, step: int) -> None:
        """Running best_effort slot parked for queued interactive
        work; the stream resumes later via replay admission."""

    def on_finish(self, handle, reason: str) -> None:
        """Request reached a terminal state."""

    def on_drain(self, step: int, n_requests: int) -> None:
        """Engine drained (snapshot taken)."""

    def on_fleet_event(self, name: str, **attrs) -> None:
        """A fleet-router lifecycle event (`serve/fleet/router.py`):
        replica_up/replica_down, circuit transitions, migration, shed,
        heartbeat_missed, orphaned, probe_failed. One generic hook —
        the event vocabulary belongs to the router, the transport (and
        the no-op discipline) to the tracer."""

    # --------------------------------------- distributed-tracing hooks
    # The fleet propagation layer (`obs/propagate.py`) and the flight
    # recorder (`obs/flightrec.py`) report through the same surface —
    # all no-ops here, so tracing-off stays exactly free (the
    # tracemalloc pin covers these too).

    def on_trace_context(self, request_id: int, trace_id: str,
                         parent_span_id: Optional[str]) -> None:
        """The router's wire context arrived for an in-flight request:
        restamp its span into the fleet trace."""

    def on_restored(self, handle, n_tokens: int) -> None:
        """A drained/migrated/hand-off stream resumed in THIS engine
        with ``n_tokens`` already emitted elsewhere."""

    def on_chain_export(self, n_blocks: int, wall_s: float) -> None:
        """A prefix chain left this engine over the chain wire."""

    def on_chain_import(self, n_blocks: int, wall_s: float) -> None:
        """A prefix chain landed in this engine's host tier."""

    def on_span_shipped(self, n: int, dropped: int) -> None:
        """A span batch left the worker for the router (``dropped`` is
        the shipper's cumulative overflow counter)."""

    def on_flight_rotate(self, segments: int,
                         bytes_written: int) -> None:
        """The flight recorder sealed a segment."""

    # ------------------------------------------------- training hooks
    # The Trainer's guarded boundary (`train/loop.py`) emits through
    # the SAME tracer surface the serving engine uses — `on_retry` and
    # `on_fault_injected` above are shared verbatim (the (step, site)
    # coordinate is the optimizer step and compiled-program name);
    # these three cover what only training has: checkpoints and the
    # restore+replay recovery.

    def on_checkpoint_saved(self, step: int, wall_s: float) -> None:
        """A step-granular verified checkpoint finished dispatching."""

    def on_restore(self, step: int, restored_step: int,
                   site: str) -> None:
        """Training state lost at ``(step, site)``; rolled back to the
        verified checkpoint at ``restored_step``."""

    def on_recovery(self, step: int, restored_step: int,
                    replayed: int) -> None:
        """In-process recovery completed: ``replayed`` steps re-run
        from the replay buffer, training resumes at ``step``."""


NULL_TRACER = NullTracer()


class RequestTracer(NullTracer):
    """The real tracer: one span per request, engine events alongside.

    Args:
      clock: monotonic timestamp source (pass the engine's injectable
        clock in tests so span times line up with deadlines).
      sink: optional record consumer — an object with
        ``write(record)`` (:class:`~pddl_tpu.obs.export.JsonlEventLog`)
        or a plain callable. Finished spans and engine events are
        written as they settle; nothing buffers unboundedly.
      max_events_per_span: per-span event cap (drops counted).
      max_decode_events_per_span: separate, smaller budget for the
        per-token ``decode`` events, so a long stream can never crowd
        rare lifecycle events (replay, re-admission, deadline shed)
        out of the overall cap.
      max_finished: retained finished-span records (a bounded deque —
        the sink holds the full history, the tracer a recent window).
      emit_ticks: also write one ``kind="tick"`` record per engine
        step to the sink (off by default — the engine's telemetry ring
        already holds per-tick records; turn this on when the JSONL
        log must be self-contained).
    """

    enabled = True

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 sink=None, max_events_per_span: int = 4096,
                 max_decode_events_per_span: int = 512,
                 max_finished: int = 4096, emit_ticks: bool = False):
        self._clock = clock
        self._write = (sink.write if hasattr(sink, "write")
                       else sink) if sink is not None else None
        self._max_events = int(max_events_per_span)
        self._max_decode = int(max_decode_events_per_span)
        self._emit_ticks = bool(emit_ticks)
        self.active: Dict[int, Span] = {}
        self.finished: Deque[Dict[str, object]] = deque(maxlen=max_finished)
        self.engine_events: Deque[Dict[str, object]] = deque(
            maxlen=max_finished)
        self.spans_started = 0
        self.spans_finished = 0
        self.sink_errors = 0
        self.spans_shipped = 0
        self.span_ship_drops = 0

    # --------------------------------------------------------- plumbing
    def _span(self, handle) -> Optional[Span]:
        return self.active.get(handle.request.request_id)

    def _emit(self, record: Dict[str, object]) -> None:
        if self._write is None:
            return
        try:
            self._write(record)
        except Exception:  # noqa: BLE001 - observability must never be
            # a fault source: a closed/full/broken sink degrades to
            # no-export (counted, and the in-process deques still hold
            # the records) instead of crashing the serving engine.
            self.sink_errors += 1

    def _engine_event(self, name: str, kind: str = "engine_event",
                      **attrs) -> None:
        ev: Dict[str, object] = {"schema": SCHEMA_VERSION,
                                 "kind": kind,
                                 "t_s": self._clock(), "name": name}
        ev.update(attrs)
        self.engine_events.append(ev)
        self._emit(ev)

    # ----------------------------------------------------- request hooks
    def on_submit(self, handle, queue_depth: int) -> None:
        rid = handle.request.request_id
        now = self._clock()
        span = Span(trace_id=f"{rid:016x}", span_id="0000000000000001",
                    name="request", request_id=rid, start_s=now,
                    max_events=self._max_events)
        span.attrs["prompt_len"] = len(handle.request.prompt)
        span.attrs["max_new_tokens"] = handle.request.max_new_tokens
        span.event(now, "queued", queue_depth=queue_depth)
        self.active[rid] = span
        self.spans_started += 1

    def on_admit(self, handle, slot: int, replay: bool) -> None:
        span = self._span(handle)
        if span is not None:
            now = self._clock()
            # A replay admission's queue wait counts from its requeue,
            # not from the original submit — otherwise the first
            # service attempt reads as scheduler backlog.
            base = (span.last_requeue_s
                    if replay and span.last_requeue_s is not None
                    else span.start_s)
            span.event(now, "admitted", slot=slot, replay=replay,
                       queue_wait_s=now - base)

    def on_prefix_match(self, handle, blocks_hit: int,
                        tokens_saved: int) -> None:
        span = self._span(handle)
        if span is not None:
            span.event(self._clock(), "prefix_match",
                       blocks_hit=blocks_hit, tokens_saved=tokens_saved)

    def on_prefill_chunk(self, handle, site: str, start: int, width: int,
                         wall_s: float) -> None:
        span = self._span(handle)
        if span is not None:
            span.event(self._clock(), "prefill_chunk", site=site,
                       start=start, width=width, wall_s=wall_s)

    def on_first_token(self, handle, ttft_s: float) -> None:
        span = self._span(handle)
        if span is not None:
            span.attrs["ttft_s"] = ttft_s
            span.event(self._clock(), "first_token", ttft_s=ttft_s)

    def on_token(self, handle, step: int) -> None:
        span = self._span(handle)
        if span is not None:
            if span.decode_events >= self._max_decode:
                span.events_dropped += 1
                return
            span.decode_events += 1
            span.event(self._clock(), "decode", step=step)

    def on_finish(self, handle, reason: str) -> None:
        span = self.active.pop(handle.request.request_id, None)
        if span is None:
            return
        span.attrs["tokens_emitted"] = len(handle.tokens)
        span.attrs["replays"] = handle.replays
        span.finish(self._clock(), reason)
        record = span.to_record()
        self.finished.append(record)
        self.spans_finished += 1
        self._emit(record)

    def on_deadline_shed(self, handle) -> None:
        span = self._span(handle)
        if span is not None:
            span.event(self._clock(), "deadline_shed")

    def on_preempt(self, handle, step: int) -> None:
        span = self._span(handle)
        if span is not None:
            now = self._clock()
            span.last_requeue_s = now  # replay admission waits from HERE
            span.event(now, "preempted", step=step,
                       priority=handle.request.priority.value)
        self._engine_event("preempted", step=step,
                           request_id=handle.request.request_id)

    def on_replay(self, handle, step: int, requeued: bool) -> None:
        span = self._span(handle)
        if span is not None:
            now = self._clock()
            if requeued:
                span.last_requeue_s = now
            span.event(now, "replay", step=step, requeued=requeued)
        self._engine_event("replay", step=step,
                           request_id=handle.request.request_id,
                           requeued=requeued)

    # ------------------------------------------------------ engine hooks
    def on_tick(self, step: int, queue_depth: int, live_slots: int,
                new_tokens: int, wall_s: float) -> None:
        if self._emit_ticks:
            self._emit({"schema": SCHEMA_VERSION, "kind": "tick",
                        "t_s": self._clock(), "step": step,
                        "queue_depth": queue_depth,
                        "live_slots": live_slots,
                        "new_tokens": new_tokens, "wall_s": wall_s})

    def on_retry(self, step: int, site: str, attempt: int) -> None:
        self._engine_event("retry", step=step, site=site, attempt=attempt)

    def on_fault_injected(self, step: int, site: str, kind: str) -> None:
        self._engine_event("fault_injected", step=step, site=site,
                           kind=kind)

    def on_degraded_entry(self, step: int) -> None:
        self._engine_event("degraded_entry", step=step)

    def on_degraded_exit(self, step: int, duration_s: float) -> None:
        self._engine_event("degraded_exit", step=step,
                           duration_s=duration_s)

    def on_fleet_event(self, name: str, **attrs) -> None:
        # Rides the engine-event record stream (same deque, same sink)
        # with kind="fleet_event", so events_named() and the JSONL log
        # cover the fleet without a second pipeline.
        self._engine_event(name, kind="fleet_event", **attrs)

    # --------------------------------------- distributed-tracing hooks
    def on_trace_context(self, request_id: int, trace_id: str,
                         parent_span_id: Optional[str]) -> None:
        span = self.active.get(request_id)
        if span is None:
            return
        if trace_id:
            span.trace_id = trace_id
        if parent_span_id is not None:
            span.attrs["parent_span_id"] = parent_span_id

    def on_restored(self, handle, n_tokens: int) -> None:
        # A restored stream gets a fresh span (the original lives in
        # the source engine's record stream); the router's trace
        # context arrives right after and restamps the trace id.
        rid = handle.request.request_id
        now = self._clock()
        span = Span(trace_id=f"{rid:016x}",
                    span_id="0000000000000001",
                    name="request", request_id=rid, start_s=now,
                    max_events=self._max_events)
        span.attrs["prompt_len"] = len(handle.request.prompt)
        span.attrs["max_new_tokens"] = handle.request.max_new_tokens
        span.attrs["restored"] = True
        span.event(now, "restored", n_tokens=int(n_tokens))
        self.active[rid] = span
        self.spans_started += 1

    def on_chain_export(self, n_blocks: int, wall_s: float) -> None:
        self._engine_event("chain_export", n_blocks=n_blocks,
                           wall_s=wall_s)

    def on_chain_import(self, n_blocks: int, wall_s: float) -> None:
        self._engine_event("chain_import", n_blocks=n_blocks,
                           wall_s=wall_s)

    def on_span_shipped(self, n: int, dropped: int) -> None:
        self.spans_shipped += int(n)
        self.span_ship_drops = max(self.span_ship_drops, int(dropped))

    def on_flight_rotate(self, segments: int,
                         bytes_written: int) -> None:
        self._engine_event("flight_rotate", segments=segments,
                           bytes_written=bytes_written)

    # ------------------------------------------------- training hooks
    def on_checkpoint_saved(self, step: int, wall_s: float) -> None:
        self._engine_event("checkpoint_saved", step=step, wall_s=wall_s)

    def on_restore(self, step: int, restored_step: int,
                   site: str) -> None:
        self._engine_event("restore", step=step,
                           restored_step=restored_step, site=site)

    def on_recovery(self, step: int, restored_step: int,
                    replayed: int) -> None:
        self._engine_event("recovery", step=step,
                           restored_step=restored_step, replayed=replayed)

    def on_drain(self, step: int, n_requests: int) -> None:
        self._engine_event("drain", step=step, n_requests=n_requests)
        # Flush every in-flight span: the drained requests resume in a
        # FRESH engine (new tracer), so these spans would otherwise
        # never reach the sink — at exactly the moment a postmortem
        # needs them. ``finish_reason="drained"`` is not a terminal
        # request state; it marks a span cut short by the snapshot.
        now = self._clock()
        for rid in sorted(self.active):
            span = self.active.pop(rid)
            span.attrs["drained"] = True
            span.finish(now, "drained")
            record = span.to_record()
            self.finished.append(record)
            self.spans_finished += 1
            self._emit(record)

    # -------------------------------------------------------- inspection
    def events_named(self, name: str) -> List[Dict[str, object]]:
        """Engine events with ``name`` (test/debug convenience)."""
        return [e for e in self.engine_events if e["name"] == name]
