"""Device-side ops: augmentation, attention, Pallas kernels.

The reference runs augmentation *inside the model graph* so it executes on
the accelerator and is active only in training
(``/root/reference/imagenet-resnet50.py:53-55``, Keras preprocessing-layer
semantics). :mod:`pddl_tpu.ops.augment` reproduces that placement as jittable
functions the trainer fuses into the train step. Long-context attention ops
live in :mod:`pddl_tpu.ops.ring_attention`.
"""

from pddl_tpu.ops import augment
from pddl_tpu.ops.attention import (
    attention_reference,
    decode_attention,
    flash_attention,
    flash_attention_lse,
)
from pddl_tpu.ops.large_vocab import chunked_cross_entropy
from pddl_tpu.ops.ring_attention import (
    ring_attention,
    ring_attention_flash,
    sequence_parallel_attention,
)

__all__ = [
    "augment",
    "attention_reference",
    "chunked_cross_entropy",
    "decode_attention",
    "flash_attention",
    "flash_attention_lse",
    "ring_attention",
    "ring_attention_flash",
    "sequence_parallel_attention",
]
