"""Device-side ops: augmentation, attention, Pallas kernels.

The reference runs augmentation *inside the model graph* so it executes on
the accelerator and is active only in training
(``/root/reference/imagenet-resnet50.py:53-55``, Keras preprocessing-layer
semantics). :mod:`pddl_tpu.ops.augment` reproduces that placement as jittable
functions the trainer fuses into the train step. Long-context attention ops
live in :mod:`pddl_tpu.ops.ring_attention`.
"""

from pddl_tpu.ops import augment
from pddl_tpu.ops.attention import attention_reference, flash_attention
from pddl_tpu.ops.ring_attention import (
    ring_attention,
    sequence_parallel_attention,
)

__all__ = [
    "augment",
    "attention_reference",
    "flash_attention",
    "ring_attention",
    "sequence_parallel_attention",
]
