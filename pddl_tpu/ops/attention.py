"""Attention ops: reference softmax attention + a Pallas TPU flash kernel.

The reference repo has no attention at all (fixed 224x224 CNN inputs,
SURVEY.md §5 "Long-context": absent) — this module exists because
long-context support is first-class in the TPU build, not an afterthought.
It provides the single-device kernels; cross-device sequence parallelism
lives in :mod:`pddl_tpu.ops.ring_attention`.

Design:

- :func:`attention_reference` — straight jnp (materializes the [Sq, Sk]
  score matrix); numerics oracle for tests and the fallback path.
- :func:`flash_attention` — blockwise online-softmax Pallas kernel: scores
  never leave VMEM, HBM traffic is O(S·d) instead of O(S²), q/k/v blocks
  are MXU-tiled matmuls. Grid is (batch·heads, q_blocks, k_blocks) with the
  k dimension innermost: TPU grids execute sequentially, so running max /
  normalizer / accumulator persist in VMEM scratch across the k sweep.
- Backward: fully fused Pallas kernels as well. The forward additionally
  emits the log-sum-exp rows (lane-replicated, the standard TPU layout);
  the backward recomputes each score block from q/k + LSE in VMEM — never
  materializing the [S, S] probability matrix. Default: a SINGLE fused
  sweep producing dq/dk/dv together (5 MXU passes per block pair, dq
  accumulated across the k sweep in a sequence-sized VMEM scratch); when
  that scratch would not fit (very long sequences), two sweeps — a dq
  kernel (k innermost) and a dk/dv kernel (q innermost) — at 7 passes
  and a second operand read.

All shapes are ``[batch, heads, seq, head_dim]``; dtypes bf16/f32 in, f32
accumulation inside (MXU-native mixed precision).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax NaN-free


def _gqa_rep(q: jnp.ndarray, k: jnp.ndarray) -> int:
    """Query-heads-per-kv-head ratio; 1 for plain MHA.

    Grouped-query attention passes K/V with ``H_kv <= H`` heads; every
    kernel in this module consumes them UNEXPANDED (the q-head → kv-head
    mapping happens in index maps / reshapes), so GQA's bandwidth saving
    holds in training, not just in the decode cache.
    """
    hq, hkv = q.shape[-3], k.shape[-3]
    if hq == hkv:
        return 1
    if hq % hkv:
        raise ValueError(
            f"query heads {hq} not divisible by kv heads {hkv}")
    return hq // hkv


def attention_reference(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    *, causal: bool = False, scale: Optional[float] = None,
    k_offset: int = 0, window: Optional[int] = None,
) -> jnp.ndarray:
    """Plain softmax attention (the numerics oracle).

    ``k_offset`` shifts key/value global positions for causal masking —
    used by ring attention where each shard sees a rotated K/V slice.
    ``window`` (requires ``causal``): sliding-window attention — query t
    sees keys ``[t-window+1, t]`` (Mistral's SWA; window=1 is self-only).
    K/V may carry fewer heads than q (grouped-query attention): each kv
    head serves ``H/H_kv`` consecutive query heads, unexpanded.
    """
    *_, sq, d = q.shape
    sk = k.shape[-2]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (sliding-window "
                             "attention is a causal-LM construct)")
        if window < 1:
            # An empty band would make every row's scores equal (-1e30,
            # not -inf) and softmax silently uniform — raise like the
            # flash path instead.
            raise ValueError(f"window must be >= 1, got {window}")
    rep = _gqa_rep(q, k)
    if rep > 1:
        hkv = k.shape[-3]
        qg = q.reshape(*q.shape[:-3], hkv, rep, sq, d)
        s = jnp.einsum("...grqd,...gkd->...grqk", qg.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
    else:
        s = jnp.einsum("...qd,...kd->...qk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
    if causal:
        q_pos = jnp.arange(sq)[:, None]
        k_pos = jnp.arange(sk)[None, :] + k_offset
        mask = q_pos >= k_pos
        if window is not None:
            mask &= k_pos > q_pos - window
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if rep > 1:
        o = jnp.einsum("...grqk,...gkd->...grqd", p, v.astype(jnp.float32))
        return o.reshape(q.shape).astype(q.dtype)
    return jnp.einsum("...qk,...kd->...qd", p, v.astype(jnp.float32)).astype(q.dtype)


# LSE/di rows are stored lane-replicated — shape [..., seq, LANES] — the
# standard Mosaic-friendly layout for per-row scalars (a bare [seq] column
# would fight the (sublane, lane) tiling).
LANES = 128


def _sequential_grid():
    """CompilerParams pinning sequential ('arbitrary') semantics on every
    grid dim. All four flash pallas_calls depend on sequential grid order
    for correctness: output blocks revisited along the innermost axis
    receive transient garbage writebacks that only the final visit's
    writes (later in grid order) overwrite, and the VMEM accumulators
    init on the first inner step / finalize on the last. Pinned
    explicitly so the assumption survives any change to the backend's
    default dimension semantics."""
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    # Older jax spells it TPUCompilerParams; same fields either way.
    cp = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cp(dimension_semantics=("arbitrary", "arbitrary", "arbitrary"))


def _masked_scores(q_ref, k_ref, qi, ki, *, scale, causal, block_q, block_k,
                   window=None, k_offset=0):
    """Recompute one (bq, bk) score block: s = scale·q·kᵀ, causal-masked.

    Shared by the forward and both backward kernels so the mask/scale
    semantics can never drift between the p used forward and the p
    recomputed backward. ``k_offset`` (static) shifts every key's global
    position — ring attention's off-diagonal rotations see keys that are
    ``i·s_local`` positions earlier than their local index.
    """
    # Operands stay in their storage dtype (bf16 in training) with f32
    # accumulation: bf16xbf16 products are exact in f32, so this matches
    # an f32 matmul of the same (already-rounded) values while running on
    # the MXU's native bf16 path — the f32 path is ~4x slower per pass.
    s = jax.lax.dot_general(                              # (bq, bk) on MXU
        q_ref[0], k_ref[0],
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if scale != 1.0:  # elided when the wrapper folded the scale into q
        s = scale * s
    if causal:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + k_offset + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = q_pos >= k_pos
        if window is not None:
            mask &= k_pos > q_pos - window
        s = jnp.where(mask, s, NEG_INF)
    return s


def _block_in_band(qi, ki, *, causal, block_q, block_k, window, k_offset=0):
    """Static-shape predicate: does block (qi, ki) intersect the causal
    (and, with ``window``, sliding-window) band? Shared by the forward
    and both backward sweeps so skip logic can never drift from the mask
    in :func:`_masked_scores` (same ``k_offset`` shift)."""
    run = True
    if causal:
        run = ki * block_k + k_offset <= qi * block_q + block_q - 1
        if window is not None:
            # block's max k_pos >= block's min q_pos - window + 1
            run &= (ki * block_k + block_k - 1 + k_offset
                    >= qi * block_q - window + 1)
    return run


# --------------------------------------------------------------- flash fwd
def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                  *, scale: float, causal: bool, block_q: int, block_k: int,
                  num_k: int, window=None, k_offset=0):
    """Forward kernel; ``lse_ref is None`` in the inference (no-vjp) variant,
    which then skips the LSE write entirely."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: skip blocks strictly above the diagonal; with a sliding
    # window, also blocks entirely below the band (compute drops from
    # O(S^2) to O(S*window) as S grows).
    run = _block_in_band(qi, ki, causal=causal, block_q=block_q,
                         block_k=block_k, window=window, k_offset=k_offset)

    @pl.when(run)
    def _compute():
        s = _masked_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k, window=window,
                           k_offset=k_offset)
        m_prev = m_ref[:, :1]                             # (bq, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                   # rescale old stats
        p = jnp.exp(s - m_new)                            # (bq, bk)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(                         # (bq, d) on MXU
            p.astype(v_ref.dtype), v_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0] = m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-30))


# Measured (block_q, block_k) per TPU generation, keyed on
# jax device_kind. Only v5e has been benchmarked on hardware (see the
# flash_attention docstring); other generations inherit those values —
# safe everywhere (the f32 score block 512x1024x4B = 2 MB plus q/k/v/acc
# tiles sits well inside the ~16 MB/core VMEM on every generation) but
# not re-tuned. To tune a new chip: run benchmarks/attention_bench.py
# (it sweeps block pairs) and add the winner here.
# Head-dim note (round 5): the pair was originally tuned at D=64; a
# 7-pair fwd+bwd re-sweep at D=128 (B8 H16 S2048, the 67.9%-MFU
# flagship geometry — artifacts/gpt_bench/r05_block_sweep_d128.txt)
# confirms 512x1024 stays optimal there too (15.9 ms vs 16.6 for the
# 1024x1024 runner-up), so the table needs no head_dim key.
TUNED_BLOCKS: dict[str, tuple[int, int]] = {
    "TPU v5 lite": (512, 1024),  # measured
    "TPU v5e": (512, 1024),      # measured (alternate kind string)
}
_DEFAULT_BLOCKS = (512, 1024)


def tuned_blocks(device=None) -> tuple[int, int]:
    """(block_q, block_k) for the local (or given) device's generation."""
    if device is None:
        device = jax.devices()[0]
    return TUNED_BLOCKS.get(getattr(device, "device_kind", ""),
                            _DEFAULT_BLOCKS)


def _resolve_blocks(block_q: Optional[int],
                    block_k: Optional[int]) -> tuple[int, int]:
    """Fill None block sizes from the local device's tuned pair."""
    if block_q is None or block_k is None:
        tq, tk = tuned_blocks()
        block_q = block_q if block_q is not None else tq
        block_k = block_k if block_k is not None else tk
    return block_q, block_k


def flash_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    *, causal: bool = False, scale: Optional[float] = None,
    window: Optional[int] = None,
    block_q: Optional[int] = None, block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    fused_backward: bool = True,
) -> jnp.ndarray:
    """Flash attention, fused Pallas forward AND backward (see module docs).

    ``window`` (requires ``causal``) enables sliding-window attention
    (Mistral's SWA): query t attends to keys ``[t-window+1, t]``. Blocks
    entirely outside the band are skipped in the forward and both
    backward sweeps, so compute scales O(S*window) instead of O(S^2);
    ``window >= S`` degrades gracefully to plain causal.
    :func:`flash_attention_lse` accepts ``window`` too; only the
    ring/sequence-parallel wrapper rejects it.

    ``block_q``/``block_k`` default to the local device generation's tuned
    pair (:func:`tuned_blocks`; re-tune a new chip with
    ``benchmarks/attention_bench.py``). The v5e entry (512, 1024) was
    measured (B4 H16 D64 bf16 causal): fwd+bwd 12.5 ms at S=2048 vs
    17.8 ms for the fused-XLA reference and 5x faster than 128x128 blocks
    at S=8192 — where the reference's O(S²) scores no longer fit HBM at
    all. Shorter sequences clamp the blocks (``_largest_dividing_block``)
    and keep tiling down to S >= 8; below that (single-token decode, tiny
    test shapes) the reference fallback described above applies.

    Under ``jax.grad`` the forward additionally saves per-row LSE and the
    backward recomputes score blocks in VMEM (two fused kernels for dq and
    dk/dv) — the [S, S] matrices never reach HBM in either direction.
    Falls back to :func:`attention_reference` when shapes don't block-tile
    (tiny test shapes) — call sites never need to special-case.

    The fused backward is first-order only (a ``pallas_call`` has no AD
    rule): for higher-order differentiation — Hessian-vector products,
    gradient penalties — pass ``fused_backward=False`` to use the exact
    O(S²)-memory reference path, differentiable at any order.

    K/V may carry fewer heads than q (grouped-query attention). They are
    consumed UNEXPANDED: the kernels map each query head to its kv head
    in the block index maps, so no ``H/H_kv``-times K/V copy is ever
    materialized in HBM, forward or backward — dk/dv come back at kv-head
    shape, accumulated over the query group inside the kernel.
    """
    *_, sq, d = q.shape
    sk = k.shape[-2]
    _gqa_rep(q, k)  # validate head grouping before any dispatch
    scale_v = (1.0 / math.sqrt(d)) if scale is None else scale
    window = _normalize_window(window, causal, sk)
    if not fused_backward:
        return attention_reference(q, k, v, causal=causal, scale=scale_v,
                                   window=window)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q, block_k = _resolve_blocks(block_q, block_k)
    bq = _largest_dividing_block(sq, block_q)
    bk = _largest_dividing_block(sk, block_k)
    if bq < 8 or bk < 8:
        # Degenerate tiling (e.g. prime-ish lengths): the kernel would run
        # sub-VPU-width blocks slower than one fused XLA softmax.
        return attention_reference(q, k, v, causal=causal, scale=scale_v,
                                   window=window)
    q, scale_v = _fold_scale(q, scale_v)
    return _flash(q, k, v, causal, scale_v, bq, bk, bool(interpret), window)


def _normalize_window(window: Optional[int], causal: bool, sk: int,
                      k_offset: int = 0) -> Optional[int]:
    """Validate a sliding-window width and clamp the trivial case.

    One definition shared by :func:`flash_attention` and
    :func:`flash_attention_lse` so the two entry points can never drift:
    window needs ``causal``, must be ``>= 1``, and ``window >= sk``
    degrades to plain causal (returned as None) — but only for aligned
    keys (``k_offset == 0``); offset keys sit further below the
    diagonal, where the band can still cut."""
    if window is None:
        return None
    if not causal:
        raise ValueError("window requires causal=True (sliding-window "
                         "attention is a causal-LM construct)")
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    window = int(window)
    return None if (window >= sk and k_offset == 0) else window


def _fold_scale(q: jnp.ndarray, scale: float) -> tuple[jnp.ndarray, float]:
    """Fold a power-of-two softmax scale into q (bitwise-exact).

    Multiplying by 2^n is exponent arithmetic — no mantissa rounding in
    any binary float format — and scaling q before the dot distributes
    exactly over the f32 accumulation, so ``dot(q*scale, k)`` equals
    ``scale*dot(q, k)`` bit for bit. The win: the kernels skip one full
    VPU pass over every [block_q, block_k] score block in the forward and
    both backward sweeps (the ``scale != 1.0`` branches). The common
    ``1/sqrt(head_dim)`` is a power of two whenever head_dim is a power
    of four (64 -> 1/8, 256 -> 1/16); other scales stay in-kernel.
    """
    m, _ = math.frexp(scale)
    if m == 0.5:
        return q * jnp.asarray(scale, q.dtype), 1.0
    return q, scale


def _largest_dividing_block(n: int, want: int) -> int:
    """Largest block <= ``want`` that tiles ``n`` evenly.

    Sequences shorter than the (large, v5e-tuned) defaults clamp to the
    full length and run as a single block — e.g. ViT's 196 tokens become
    one 196-wide block under want=512. The ``bq < 8`` reference fallback
    at the call site then fires for sequences shorter than 8 (decode
    steps, tiny test shapes) and for degenerate tilings (prime-ish
    lengths above the block size whose largest divisor is tiny)."""
    for b in range(min(want, n), 0, -1):
        if n % b == 0:
            return b
    return 1


def _flash_kernel_nolse(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                        **kw):
    _flash_kernel(q_ref, k_ref, v_ref, o_ref, None, m_ref, l_ref, acc_ref,
                  **kw)


def _sds_like(ref_value):
    """ShapeDtypeStruct factory that propagates the varying-manual-axes set
    of ``ref_value`` — inside shard_map (GPipe stages, seq-sharded regions)
    pallas outputs must declare how they vary across mesh axes."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:  # pre-vma jax: nothing to propagate
        return jax.ShapeDtypeStruct
    vma = getattr(typeof(ref_value), "vma", None)
    if vma:
        return functools.partial(jax.ShapeDtypeStruct, vma=vma)
    return jax.ShapeDtypeStruct


def _kv_index_map(h: int, hkv: int):
    """K/V BlockSpec index map over the flat ``b*h``-major grid axis.

    For GQA the K/V operands stay at ``[b*hkv, S, D]``; each q head's
    grid slot reads its group's kv head: flat kv index
    ``(batch)*hkv + (q_head)//rep``. MHA keeps the identity map (no
    scalar-core arithmetic on the hot path)."""
    if h == hkv:
        return lambda bh, qi, ki: (bh, ki, 0)
    rep = h // hkv
    return lambda bh, qi, ki: ((bh // h) * hkv + (bh % h) // rep, ki, 0)


def _flash_forward_call(q, k, v, causal, scale, block_q, block_k, interpret,
                        want_lse, window=None, k_offset=0):
    """Run the forward kernel; returns flat (out [bh,sq,d], lse or None).

    ``want_lse=False`` (inference / non-differentiated calls) uses a variant
    with no LSE output at all — a pallas_call output can't be DCE'd by XLA,
    so the [bh, sq, LANES] write must not exist rather than be unused.
    K/V may be grouped (``hkv < h``); they are consumed unexpanded via
    :func:`_kv_index_map`.
    """
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    sk = k.shape[-2]
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * hkv, sk, d)
    vf = v.reshape(b * hkv, sk, d)
    num_q = pl.cdiv(sq, block_q)
    num_k = pl.cdiv(sk, block_k)
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    kernel = functools.partial(
        _flash_kernel if want_lse else _flash_kernel_nolse,
        scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k=num_k, window=window,
        k_offset=k_offset,
    )
    sds = _sds_like(qf)
    kv_map = _kv_index_map(h, hkv)

    o_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0))
    lse_spec = pl.BlockSpec((1, block_q, LANES), lambda bh, qi, ki: (bh, qi, 0))
    result = pl.pallas_call(
        kernel,
        grid=(b * h, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
        ],
        out_specs=[o_spec] + ([lse_spec] if want_lse else []),
        out_shape=[sds((b * h, sq, d), q.dtype)]
        + ([sds((b * h, sq, LANES), jnp.float32)] if want_lse else []),
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running max m
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),      # output accumulator
        ],
        compiler_params=_sequential_grid(),
        interpret=interpret,
    )(qf, kf, vf)
    if want_lse:
        return result[0], result[1]
    return result[0], None


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret, window=None,
           k_offset=0):
    b, h, sq, d = q.shape
    out, _ = _flash_forward_call(q, k, v, causal, scale, block_q, block_k,
                                 interpret, want_lse=False, window=window,
                                 k_offset=k_offset)
    return out.reshape(b, h, sq, d)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               window=None, k_offset=0):
    b, h, sq, d = q.shape
    out, lse = _flash_forward_call(q, k, v, causal, scale, block_q, block_k,
                                   interpret, want_lse=True, window=window,
                                   k_offset=k_offset)
    # Residuals live from forward to backward — across every later layer's
    # forward. Keep LSE packed [bh, sq] for that window; the transient
    # lane-replicated buffer the kernel wrote is freed here.
    return out.reshape(b, h, sq, d), (q, k, v, out, lse[..., 0])


# --------------------------------------------------------------- flash bwd
#
# Standard two-sweep recomputation backward. With
#   p  = exp(scale·qkᵀ − lse),  dp = do·vᵀ,  di = Σ_d(do ⊙ o),
#   ds = p ⊙ (dp − di):
#   dq = scale · ds·k   dk = scale · dsᵀ·q   dv = pᵀ·do
# Each kernel recomputes its p block in VMEM from q/k + saved LSE; the [S,S]
# matrices never touch HBM.

def _block_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, qi, ki,
                *, scale, causal, block_q, block_k, window, k_offset=0):
    """Recompute one block's (p, ds) — the shared first half of every
    backward kernel (masked scores → p from saved LSE → dp → ds). One
    definition so the fused single-sweep kernel and both two-sweep
    fallback kernels can never drift."""
    s = _masked_scores(q_ref, k_ref, qi, ki, scale=scale, causal=causal,
                       block_q=block_q, block_k=block_k, window=window,
                       k_offset=k_offset)
    p = jnp.exp(s - lse_ref[0][:, :1])                    # masked -> exactly 0
    dp = jax.lax.dot_general(                             # (bq, bk)
        do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = p * (dp - di_ref[0][:, :1])
    return p, ds


def _scaled(x, scale):
    """Apply the softmax scale unless it was folded into q (== 1.0)."""
    return (scale * x) if scale != 1.0 else x


def _dq_contrib(ds, k_ref, scale):
    """ds·k → this block's dq rows (bq, d)."""
    return _scaled(jax.lax.dot_general(
        ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32), scale)


def _dk_contrib(ds, q_ref, scale):
    """dsᵀ·q → this block's dk rows (bk, d)."""
    return _scaled(jax.lax.dot_general(
        ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32), scale)


def _dv_contrib(p, do_ref):
    """pᵀ·do → this block's dv rows (bk, d)."""
    return jax.lax.dot_general(
        p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                         dq_ref, acc_ref,
                         *, scale: float, causal: bool, block_q: int,
                         block_k: int, num_k: int, window=None, k_offset=0):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = _block_in_band(qi, ki, causal=causal, block_q=block_q,
                         block_k=block_k, window=window, k_offset=k_offset)

    @pl.when(run)
    def _compute():
        _, ds = _block_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                            qi, ki, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k, window=window,
                            k_offset=k_offset)
        acc_ref[:] += _dq_contrib(ds, k_ref, scale)

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                          dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                          *, scale: float, causal: bool, block_q: int,
                          block_k: int, num_q: int, inner_steps: int,
                          window=None, k_offset=0):
    """dk/dv sweep. The inner grid axis covers ``rep * num_q`` steps under
    GQA — all query heads of the kv head's group, q blocks innermost — so
    dk/dv accumulate the WHOLE group in scratch and each K/V block is
    fetched once per group instead of once per query head. ``qi`` is the
    per-head q-block index decoded from the flat inner step."""
    ki = pl.program_id(1)
    t = pl.program_id(2)
    qi = t % num_q  # per-q-head block index (t == qi for MHA)

    @pl.when(t == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    # Same band predicate as the forward, from the dkv grid's viewpoint:
    # above-diagonal OR fully-below-window blocks contribute nothing.
    run = _block_in_band(qi, ki, causal=causal, block_q=block_q,
                         block_k=block_k, window=window, k_offset=k_offset)

    @pl.when(run)
    def _compute():
        p, ds = _block_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                            qi, ki, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k, window=window,
                            k_offset=k_offset)
        dv_acc_ref[:] += _dv_contrib(p, do_ref)
        dk_acc_ref[:] += _dk_contrib(ds, q_ref, scale)

    @pl.when(t == inner_steps - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                            dq_ref, dk_ref, dv_ref,
                            dq_acc_ref, dk_acc_ref, dv_acc_ref,
                            *, scale: float, causal: bool, block_q: int,
                            block_k: int, num_q: int, num_k: int,
                            inner_steps: int, window=None, k_offset=0):
    """Single-sweep fused backward: dq, dk, dv from ONE pass over the
    (k_block, q_block) grid.

    The two-sweep backward reads q/k/v/do twice and recomputes the score
    and dp matmuls in both kernels (7 MXU passes per block pair); here
    each block pair is visited once (5 passes) and the operands are read
    once per sweep. The price is a dq accumulator covering the WHOLE
    local sequence (``rep·S_q × D`` f32) living in VMEM scratch across
    the k sweep — the caller falls back to the two-sweep kernels when
    that does not fit (very long sequences).

    Grid: ``(b·hkv, num_k, rep·num_q)`` — same shape as the dkv sweep;
    dk/dv accumulate per (kv-head, k-block) across the inner axis, dq
    rows accumulate at ``t·block_q`` offsets across the OUTER k sweep
    and are emitted on its last iteration. dq output blocks mapped at
    earlier k iterations receive transient garbage writebacks that the
    final iteration's writes (later in sequential grid order)
    overwrite."""
    ki = pl.program_id(1)
    t = pl.program_id(2)
    qi = t % num_q

    @pl.when(jnp.logical_and(ki == 0, t == 0))
    def _init_dq():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    @pl.when(t == 0)
    def _init_dkv():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    run = _block_in_band(qi, ki, causal=causal, block_q=block_q,
                         block_k=block_k, window=window, k_offset=k_offset)

    @pl.when(run)
    def _compute():
        p, ds = _block_p_ds(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                            qi, ki, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k, window=window,
                            k_offset=k_offset)
        dv_acc_ref[:] += _dv_contrib(p, do_ref)
        dk_acc_ref[:] += _dk_contrib(ds, q_ref, scale)
        rows = pl.ds(t * block_q, block_q)
        dq_acc_ref[rows, :] += _dq_contrib(ds, k_ref, scale)

    @pl.when(t == inner_steps - 1)
    def _finalize_dkv():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)

    @pl.when(ki == num_k - 1)
    def _finalize_dq():
        dq_ref[0] = dq_acc_ref[pl.ds(t * block_q, block_q), :].astype(
            dq_ref.dtype)


# dq accumulator budget for the fused single-sweep backward: rep·S_q·D
# f32 must sit in VMEM alongside the operand blocks (~16 MB/core total).
_FUSED_BWD_DQ_BYTES = 6 * 1024 * 1024


def _flash_bwd(causal, scale, block_q, block_k, interpret, window, k_offset,
               res, g):
    return _flash_bwd_impl(causal, scale, block_q, block_k, interpret, res,
                           g, dlse=None, window=window, k_offset=k_offset)


def _flash_bwd_impl(causal, scale, block_q, block_k, interpret, res, g,
                    dlse=None, window=None, k_offset=0):
    """Shared fused backward. ``dlse`` (``[b, h, sq]`` or None) is the LSE
    output's cotangent for the (o, lse) variant: since
    d(lse)/d(s) = p, it enters every kernel as ``ds = p·(dp − di + dlse)``
    — folded here as ``di − dlse`` so the kernels stay untouched. dv has
    no lse term (lse is a function of q/k only)."""
    q, k, v, out, lse_packed = res
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    rep = h // hkv
    sk = k.shape[-2]
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * hkv, sk, d)
    vf = v.reshape(b * hkv, sk, d)
    dof = g.reshape(b * h, sq, d)
    num_q = pl.cdiv(sq, block_q)
    num_k = pl.cdiv(sk, block_k)
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    # Re-expand packed LSE and compute di = rowsum(do ⊙ o), both
    # lane-replicated for the kernels (transient buffers, freed after the
    # two pallas calls; everything O(S²) stays inside the kernels).
    lse = jnp.broadcast_to(lse_packed[..., None], (b * h, sq, LANES))
    di_rows = jnp.sum(dof.astype(jnp.float32) * out.astype(jnp.float32),
                      axis=-1, keepdims=True)
    if dlse is not None:
        di_rows = di_rows - dlse.reshape(b * h, sq, 1).astype(jnp.float32)
    di = jnp.broadcast_to(di_rows, (b * h, sq, LANES))

    sds = _sds_like(qf)

    # Specs shared by the fused single-sweep backward and the dkv sweep
    # of the two-sweep fallback (grid (b·hkv, k_blocks, rep·q_blocks)).
    def _q_flat(bkv, t):
        if rep == 1:
            return bkv
        return (bkv // hkv) * h + (bkv % hkv) * rep + t // num_q

    qT_spec = pl.BlockSpec(
        (1, block_q, d), lambda bkv, j, t: (_q_flat(bkv, t), t % num_q, 0))
    rowT_spec = pl.BlockSpec(
        (1, block_q, LANES), lambda bkv, j, t: (_q_flat(bkv, t), t % num_q, 0))
    kT_spec = pl.BlockSpec((1, block_k, d), lambda bkv, j, t: (bkv, j, 0))

    if rep * sq * d * 4 <= _FUSED_BWD_DQ_BYTES:
        # Single fused sweep: 5 MXU passes per block pair instead of 7,
        # operands read once. See _flash_bwd_fused_kernel.
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _flash_bwd_fused_kernel, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, num_q=num_q,
                num_k=num_k, inner_steps=rep * num_q, window=window,
                k_offset=k_offset,
            ),
            grid=(b * hkv, num_k, rep * num_q),
            in_specs=[qT_spec, kT_spec, kT_spec, qT_spec, rowT_spec,
                      rowT_spec],
            out_specs=[qT_spec, kT_spec, kT_spec],
            out_shape=[
                sds((b * h, sq, d), q.dtype),
                sds((b * hkv, sk, d), k.dtype),
                sds((b * hkv, sk, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((rep * num_q * block_q, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            compiler_params=_sequential_grid(),
            interpret=interpret,
        )(qf, kf, vf, dof, lse, di)
        return (dq.reshape(b, h, sq, d), dk.reshape(b, hkv, sk, d),
                dv.reshape(b, hkv, sk, d))

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    row_spec = pl.BlockSpec((1, block_q, LANES), lambda bh, i, j: (bh, i, 0))
    kv_map = _kv_index_map(h, hkv)
    k_spec = pl.BlockSpec((1, block_k, d), lambda bh, i, j: kv_map(bh, i, j))

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, num_k=num_k, window=window,
            k_offset=k_offset,
        ),
        grid=(b * h, num_q, num_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=sds((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_sequential_grid(),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, di)

    # dk/dv sweep: grid (b*hkv, k_blocks, rep*q_blocks) — the inner axis
    # runs q blocks innermost within each query head of the kv head's
    # group, so the k/v accumulators persist in scratch across the whole
    # group (dk/dv are SUMS over the group's query heads) and each K/V
    # block is read once per group, not once per query head.
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, num_q=num_q,
            inner_steps=rep * num_q, window=window, k_offset=k_offset,
        ),
        grid=(b * hkv, num_k, rep * num_q),
        in_specs=[qT_spec, kT_spec, kT_spec, qT_spec, rowT_spec, rowT_spec],
        out_specs=[kT_spec, kT_spec],
        out_shape=[
            sds((b * hkv, sk, d), k.dtype),
            sds((b * hkv, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_sequential_grid(),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, di)

    return (dq.reshape(b, h, sq, d), dk.reshape(b, hkv, sk, d),
            dv.reshape(b, hkv, sk, d))


_flash.defvjp(_flash_fwd, _flash_bwd)


# ------------------------------------------------------- (o, lse) variant
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_lse(q, k, v, causal, scale, block_q, block_k, interpret,
               window=None, k_offset=0):
    (o, lse), _ = _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k,
                                 interpret, window, k_offset)
    return o, lse


def _flash_lse_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
                   window=None, k_offset=0):
    b, h, sq, d = q.shape
    out, lse = _flash_forward_call(q, k, v, causal, scale, block_q, block_k,
                                   interpret, want_lse=True, window=window,
                                   k_offset=k_offset)
    lse_rows = lse[..., 0]
    return ((out.reshape(b, h, sq, d), lse_rows.reshape(b, h, sq)),
            (q, k, v, out, lse_rows))


def _flash_lse_bwd(causal, scale, block_q, block_k, interpret, window,
                   k_offset, res, g):
    do, dlse = g
    return _flash_bwd_impl(causal, scale, block_q, block_k, interpret, res,
                           do, dlse=dlse, window=window, k_offset=k_offset)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _attention_reference_lse(q, k, v, causal, scale, window=None,
                             k_offset=0):
    """O(S²) (o, lse) fallback with the reference's exact masking.
    Supports grouped K/V like every other kernel in this module."""
    rep = _gqa_rep(q, k)
    if rep > 1:
        hkv = k.shape[-3]
        sq, d = q.shape[-2:]
        qg = q.reshape(*q.shape[:-3], hkv, rep, sq, d)
        s = scale * jnp.einsum("...grqd,...gkd->...grqk",
                               qg.astype(jnp.float32), k.astype(jnp.float32))
    else:
        s = scale * jnp.einsum("...qd,...kd->...qk", q.astype(jnp.float32),
                               k.astype(jnp.float32))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        q_pos = jnp.arange(sq)[:, None]
        k_pos = jnp.arange(sk)[None, :] + k_offset
        mask = q_pos >= k_pos
        if window is not None:
            mask &= k_pos > q_pos - window
        s = jnp.where(mask, s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    if rep > 1:
        o = jnp.einsum("...grqk,...gkd->...grqd", p, v.astype(jnp.float32))
        return (o.reshape(q.shape).astype(q.dtype),
                lse.reshape(*q.shape[:-1]))
    o = jnp.einsum("...qk,...kd->...qd", p, v.astype(jnp.float32))
    return o.astype(q.dtype), lse


def flash_attention_lse(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    *, causal: bool = False, scale: Optional[float] = None,
    window: Optional[int] = None, k_offset: int = 0,
    block_q: Optional[int] = None, block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`flash_attention` that ALSO returns per-row logsumexp.

    ``(o [B,H,S,D], lse [B,H,S])`` — the pair needed to merge partial
    attention over key/value blocks held elsewhere (ring attention's
    flash path): normalized partials combine as
    ``o = Σᵢ oᵢ·exp(lseᵢ − m) / Σᵢ exp(lseᵢ − m)``. Fully differentiable
    including through ``lse`` (the cotangent folds into the fused
    backward's row term). Falls back to an O(S²) reference when shapes
    don't tile, exactly like :func:`flash_attention`. Grouped K/V
    (``H_kv < H``) is supported unexpanded like everywhere else — this
    is what lets ring attention rotate kv-head-sized shards.

    ``k_offset`` (static) shifts the keys' global positions for the
    causal/window mask — ring attention's rotation ``i`` passes
    ``-i·s_local`` so each visiting shard masks at its true positions.
    """
    *_, sq, d = q.shape
    sk = k.shape[-2]
    _gqa_rep(q, k)  # validate head grouping before any dispatch
    scale_v = (1.0 / math.sqrt(d)) if scale is None else scale
    window = _normalize_window(window, causal, sk, k_offset)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q, block_k = _resolve_blocks(block_q, block_k)
    bq = _largest_dividing_block(sq, block_q)
    bk = _largest_dividing_block(sk, block_k)
    if bq < 8 or bk < 8:
        return _attention_reference_lse(q, k, v, causal, scale_v, window,
                                        k_offset)
    q, scale_v = _fold_scale(q, scale_v)
    return _flash_lse(q, k, v, causal, scale_v, bq, bk, bool(interpret),
                      window, k_offset)


# ----------------------------------------------------------- decode sweep
# K-cache size (bytes, PER ARRAY — v doubles it) up to which a
# single-token decode step reads the WHOLE cache in one fused pass
# instead of the chunked loop. The loop's while/dynamic-slice machinery
# is a fixed per-layer cost; the extra read scales with batch x cache,
# so the gate is bytes-based. Re-measured in round 5 under value-fetch
# syncs (block_until_ready is not a reliable barrier on the tunneled
# transport, so the round-4 placement at 2 MB was tuned on bad timing):
# at 0.5 MB/layer (llama-small GQA) single-shot wins ~8%; at 1.5 MB
# (GPT-small MHA) the prefix-bounded sweep wins ~7% at B1 and ~9% at B8
# (benchmarks/decode_attribution.py). The crossover sits between, so
# the gate is 1 MB.
_SINGLE_SHOT_MAX_KC_BYTES = 1024 * 1024


def cache_slot_insert(pool: jnp.ndarray, row: jnp.ndarray, slot) -> jnp.ndarray:
    """Insert a batch-1 cache leaf ``row [1, ...]`` as row ``slot`` of the
    pooled leaf ``pool [S, ...]`` (the serving engine's slot model: one
    resident cache whose batch dim is a pool of request slots).

    ``slot`` is a traced int32 scalar — slot choice is a runtime value,
    so admitting into any slot reuses one compiled program. The whole
    row is overwritten, which is what makes stale K/V from the slot's
    previous occupant unreachable-by-construction after an admit.
    """
    if row.shape != (1,) + pool.shape[1:]:
        raise ValueError(
            f"row {row.shape} is not a batch-1 slice of pool {pool.shape}")
    return jax.lax.dynamic_update_slice(
        pool, row.astype(pool.dtype), (slot,) + (0,) * (pool.ndim - 1))


def cache_slot_reset(pool: jnp.ndarray, slot) -> jnp.ndarray:
    """Zero one slot row of a pooled cache leaf (evict hygiene — not
    required for correctness, since :func:`cache_slot_insert` overwrites
    the whole row on the next admit, but useful for tests/debugging)."""
    return cache_slot_insert(
        pool, jnp.zeros((1,) + pool.shape[1:], pool.dtype), slot)


def cache_blocks_gather(pool: jnp.ndarray, block_ids) -> jnp.ndarray:
    """Gather KV blocks ``block_ids [M]`` from a block-pool leaf
    ``[N, ..., block_size, D]`` into one contiguous batch-1 cache prefix
    ``[1, ..., M*block_size, D]`` (block ``j``'s tokens land at positions
    ``[j*block_size, (j+1)*block_size)``).

    The prefix-cache twin of :func:`cache_slot_insert`: ``block_ids`` is
    a runtime int32 vector of FIXED length, so one compiled program
    serves every hit depth — callers pad short chains with the reserved
    scratch block (id 0), whose junk lands at positions the suffix
    prefill overwrites or the slot's position counter parks. The gather
    COPIES: a admitted request's slot never aliases pool storage, which
    is what makes pool eviction safe while the request decodes
    (copy-on-write by construction).
    """
    block_ids = jnp.asarray(block_ids, jnp.int32)
    if block_ids.ndim != 1:
        raise ValueError(f"block_ids must be [M], got {block_ids.shape}")
    if pool.ndim < 3:
        raise ValueError(
            f"pool leaf must be [N, ..., block_size, D], got {pool.shape}")
    m = block_ids.shape[0]
    bs, d = pool.shape[-2], pool.shape[-1]
    g = jnp.take(pool, block_ids, axis=0)      # [M, ..., bs, D]
    g = jnp.moveaxis(g, 0, -3)                 # [..., M, bs, D]
    return g.reshape(g.shape[:-3] + (m * bs, d))[None]


def cache_blocks_scatter(pool: jnp.ndarray, row: jnp.ndarray, block_ids,
                         start_block) -> jnp.ndarray:
    """Write a batch-1 cache row's tokens
    ``[start_block*block_size, (start_block+M)*block_size)`` into pool
    blocks ``block_ids [M]`` of a ``[N, ..., block_size, D]`` leaf — the
    donation half of the prefix cache (a finished prefill's prompt K/V
    becomes shared, immutable pool blocks).

    ``start_block`` is a traced int32 block index; ``block_ids`` is a
    fixed-length runtime vector (pad with the scratch block 0 — its
    content is junk by contract and never reachable through the radix
    index). Out-of-range source positions are clamped per token rather
    than shifting the whole slice, so padded tail blocks read junk
    without corrupting the real blocks' mapping.
    """
    block_ids = jnp.asarray(block_ids, jnp.int32)
    if block_ids.ndim != 1:
        raise ValueError(f"block_ids must be [M], got {block_ids.shape}")
    if row.shape[0] != 1 or row.ndim != pool.ndim:
        raise ValueError(
            f"row {row.shape} is not a batch-1 cache leaf matching pool "
            f"{pool.shape}")
    m = block_ids.shape[0]
    bs, d = pool.shape[-2], pool.shape[-1]
    pos = jnp.asarray(start_block, jnp.int32) * bs + jnp.arange(m * bs)
    window = jnp.take(row[0], jnp.minimum(pos, row.shape[-2] - 1), axis=-2)
    blocks = window.reshape(window.shape[:-2] + (m, bs, d))
    blocks = jnp.moveaxis(blocks, -3, 0)       # [M, ..., bs, D]
    return pool.at[block_ids].set(blocks.astype(pool.dtype))


# ------------------------------------------------------ paged decode
# True paged attention (vLLM PagedAttention, SOSP '23): decode reads
# K/V straight out of the serving engine's block POOL through a
# per-slot block-table indirection, so a shared prompt prefix exists
# ONCE in HBM no matter how many live requests reference it and
# admission never copies pool blocks into a resident row. Three ops:
#
# - :func:`paged_cache_insert` — write the current token(s) of every
#   slot into its table-mapped pool block (the paged twin of the
#   row-cache dynamic_update_slice writes in the decode modules).
# - :func:`paged_decode_attention` — attention over the pool through
#   the table. The jnp path (chunked gather + online softmax, HBM
#   traffic bounded by the deepest live slot exactly like
#   :func:`decode_attention`) is the CPU/tier-1 numerics ORACLE; the
#   Pallas path (:func:`paged_decode_attention_kernel`) is the TPU
#   hot-path kernel — the block table rides in SMEM via scalar
#   prefetch and drives the K/V BlockSpec index maps, so each grid
#   step DMAs exactly one pool block.
#
# Safety contract shared with `serve/kvcache/block_pool.py`: block 0
# is the reserved scratch sink — parked slots' table rows are all
# scratch, junk writes land there, and masked reads never reach past
# a slot's position counter, so scratch content is junk by
# construction and harmless by masking.


def paged_cache_insert(pool: jnp.ndarray, kv: jnp.ndarray, block_table,
                       index) -> jnp.ndarray:
    """Write ``kv [B, H_kv, s, D]`` at global positions
    ``index (+ arange(s))`` into pool blocks resolved through
    ``block_table [B, T]`` (``pool [N, H_kv, block_size, D]``).

    ``index`` is a scalar (batch-1 chunk prefill at a traced offset) or
    a per-row ``[B]`` vector (the serving tick: every slot writes one
    token at its own depth). Positions whose block index falls outside
    the table are deflected to the scratch block — padded prefill junk
    beyond a prompt's allocated blocks can never reach a real block.
    Distinct valid positions map to distinct (block, offset) pairs, so
    the scatter has no write conflicts except on scratch, whose content
    is junk by contract.

    The multi-token (batch-1 chunk prefill) path works at BLOCK
    granularity: read the span's blocks, splice the chunk in
    contiguously, scatter whole rows back. A per-token scatter of a
    [C]-token chunk costs C strided row-strip writes (measured ~20x a
    contiguous write on XLA CPU); a dozen whole-block copies cost
    memcpy.
    """
    n, hkv, bs, d = pool.shape
    b, _, s, _ = kv.shape
    block_table = jnp.asarray(block_table, jnp.int32)
    if block_table.ndim != 2 or block_table.shape[0] != b:
        raise ValueError(
            f"block_table must be [B={b}, T], got {block_table.shape}")
    t = block_table.shape[1]
    index = jnp.asarray(index, jnp.int32)
    if s > 1 and b == 1:
        # Block-granular read-modify-write over the chunk's span.
        first = index // bs                       # traced span start block
        n_span = -(-s // bs) + 1                  # static span width
        span = first + jnp.arange(n_span)
        ids = jnp.where(span < t,
                        jnp.take(block_table[0], jnp.minimum(span, t - 1)),
                        0)                        # off-table -> scratch
        blocks = jnp.take(pool, ids, axis=0)      # [n_span, Hkv, bs, D]
        flat = jnp.moveaxis(blocks, 0, 1).reshape(hkv, n_span * bs, d)
        flat = jax.lax.dynamic_update_slice(
            flat, kv[0].astype(pool.dtype), (0, index % bs, 0))
        blocks = jnp.moveaxis(flat.reshape(hkv, n_span, bs, d), 1, 0)
        return pool.at[ids].set(blocks)
    pos = index[..., None] + jnp.arange(s, dtype=jnp.int32)   # [s] or [B, s]
    pos = jnp.broadcast_to(pos, (b, s))
    blk = pos // bs
    off = pos % bs
    bid = jnp.take_along_axis(block_table, jnp.minimum(blk, t - 1), axis=1)
    bid = jnp.where(blk < t, bid, 0)  # out-of-table junk -> scratch
    updates = jnp.moveaxis(kv, 2, 1).reshape(b * s, hkv, d)   # [B*s, Hkv, D]
    return pool.at[bid.reshape(-1), :, off.reshape(-1)].set(
        updates.astype(pool.dtype))


def paged_decode_attention(
    q: jnp.ndarray, k_pool: jnp.ndarray, v_pool: jnp.ndarray,
    block_table, index, *, window: Optional[int] = None,
    scale: Optional[float] = None, blocks_per_chunk: Optional[int] = None,
    kernel: Optional[bool] = None, interpret: Optional[bool] = None,
):
    """Attention over a paged KV pool through a per-slot block table.

    Semantically :func:`decode_attention` over the VIRTUAL cache
    ``cache[b, :, j*bs + o] == pool[block_table[b, j], :, o]`` — same
    masking, same online softmax, same prefix-bounded sweep — but the
    per-request cache rows never exist contiguously: the pool IS the
    storage and the table is the only per-slot state.

    Args:
      q: ``[B, H, s, D]`` post-RoPE queries (``s == 1`` on the decode
        tick; ``s > 1`` for chunked prefill continuing at ``index``).
      k_pool/v_pool: ``[N, H_kv, block_size, D]`` pool leaves; the
        current tokens must already be written
        (:func:`paged_cache_insert` runs first, like the row path).
      block_table: ``[B, T]`` int32 pool block ids; entries beyond a
        slot's depth are scratch (never read — masked).
      index: tokens in the (virtual) cache before this call; scalar or
        per-row ``[B]``.
      window: sliding-window mask (non-rolling only — ring caches are
        not paged).
      blocks_per_chunk: table entries visited per sweep iteration on
        the jnp path. Default (``None``): ~512 cache tokens per
        iteration for single-token steps and ~256 for multi-token
        chunks — the same sweep widths :func:`decode_attention` uses,
        measured to amortize the gather/loop overhead on CPU without
        blowing up the per-iteration score block.
      kernel: ``True`` forces the Pallas kernel (decode steps only,
        ``s == 1``), ``False`` the jnp reference, ``None`` (default)
        picks the kernel on TPU and the reference elsewhere.
      interpret: Pallas interpret mode (defaults to non-TPU backends).

    Returns ``[B, H, s, D]`` in q's dtype.
    """
    b, h, s, d = q.shape
    n, hkv, bs, _ = k_pool.shape
    rep = _gqa_rep(q, k_pool)
    block_table = jnp.asarray(block_table, jnp.int32)
    if block_table.ndim != 2 or block_table.shape[0] != b:
        raise ValueError(
            f"block_table must be [B={b}, T], got {block_table.shape}")
    t = block_table.shape[1]
    index = jnp.asarray(index, jnp.int32)
    if index.ndim > 1 or (index.ndim == 1 and index.shape[0] != b):
        raise ValueError(
            f"index must be a scalar or [B]={b} vector, got {index.shape}")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    scale_v = (1.0 / math.sqrt(d)) if scale is None else scale
    if kernel is None:
        kernel = jax.default_backend() == "tpu" and s == 1
    if kernel:
        if s != 1:
            raise ValueError(
                "the paged Pallas kernel serves single-token decode "
                f"steps only (got a {s}-token block); multi-token "
                "prefill takes the jnp path (kernel=False)")
        return paged_decode_attention_kernel(
            q, k_pool, v_pool, block_table, index, scale=scale_v,
            window=window, interpret=interpret)

    # ---- jnp reference path (the tier-1 oracle) ----
    if blocks_per_chunk is None:
        blocks_per_chunk = max(1, (512 if s == 1 else 256) // bs)
    cb = min(int(blocks_per_chunk), t)
    chunk = cb * bs
    n_chunks = -(-t // cb)
    qg = q.reshape(b, hkv, rep, s, d)
    total = index + s
    q_pos = index[..., None] + jnp.arange(s)

    def _bcast(mask):
        return mask if mask.ndim == 2 else mask[:, None, None]

    def body(c, carry):
        m, l, acc = carry
        start_blk = jnp.minimum(c * cb, t - cb)       # clamped tail
        ids = jax.lax.dynamic_slice(block_table, (0, start_blk),
                                    (b, cb))          # [B, cb]
        kc = jnp.take(k_pool, ids.reshape(-1), axis=0)
        vc = jnp.take(v_pool, ids.reshape(-1), axis=0)
        # [B*cb, Hkv, bs, D] -> [B, Hkv, cb*bs, D]
        kc = jnp.moveaxis(kc.reshape(b, cb, hkv, bs, d), 1, 2) \
            .reshape(b, hkv, chunk, d)
        vc = jnp.moveaxis(vc.reshape(b, cb, hkv, bs, d), 1, 2) \
            .reshape(b, hkv, chunk, d)
        sb = jnp.einsum("bgrqd,bgkd->bgrqk", qg.astype(k_pool.dtype), kc,
                        preferred_element_type=jnp.float32) * scale_v
        pos = start_blk * bs + jnp.arange(chunk)
        dedup = pos >= c * chunk  # drop the clamped tail's re-read overlap
        mask = pos[..., None, :] <= q_pos[..., :, None]
        if window is not None:
            mask &= pos[..., None, :] > q_pos[..., :, None] - window
        mask &= dedup[None, :]
        sb = jnp.where(_bcast(mask), sb, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sb, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(sb - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bgrqk,bgkd->bgrqd", p.astype(v_pool.dtype), vc,
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    live = jnp.minimum((jnp.max(total) + chunk - 1) // chunk, n_chunks)
    m0 = jnp.full((b, hkv, rep, s, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, rep, s, 1), jnp.float32)
    acc0 = jnp.zeros((b, hkv, rep, s, d), jnp.float32)
    if n_chunks == 1:
        m, l, acc = body(0, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, live, body, (m0, l0, acc0))
    return (acc / jnp.maximum(l, 1e-30)).reshape(b, h, s, d).astype(q.dtype)


def _paged_decode_kernel(table_ref, index_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, scale: float, bs: int,
                         num_t: int, hkv: int, rep: int,
                         window: Optional[int]):
    """One (slot, table-entry) grid step of paged decode attention.

    ``table_ref``/``index_ref`` are scalar-prefetched (SMEM): the table
    drove this step's K/V BlockSpec index maps (the DMA fetched pool
    block ``table[b, j]``), and the per-slot depth gates the compute —
    blocks past the slot's live prefix are skipped entirely, so the
    sweep costs what the slot's depth costs, exactly like the chunked
    jnp path. Running max / denominator / accumulator persist in VMEM
    scratch across the (sequential, innermost) table sweep.
    """
    bq = pl.program_id(0)
    j = pl.program_id(1)
    depth = index_ref[bq]  # tokens in the virtual cache before this step

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = j * bs <= depth  # block intersects [0, depth] (current token incl.)
    if window is not None:
        run = jnp.logical_and(run, (j + 1) * bs - 1 > depth - window)

    @pl.when(run)
    def _compute():
        # [Hkv, rep, D] x [Hkv, bs, D] -> [Hkv, rep, bs], batched on the
        # kv-head dim, f32 accumulation on the MXU.
        qg = q_ref[0].reshape(hkv, rep, q_ref.shape[-1])
        sb = jax.lax.dot_general(
            qg, k_ref[0], (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, sb.shape, 2)
        mask = pos <= depth
        if window is not None:
            mask = jnp.logical_and(mask, pos > depth - window)
        sb = jnp.where(mask, sb, NEG_INF)
        sb = sb.reshape(hkv * rep, bs)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(sb, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(sb - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(  # [Hkv, rep, bs] x [Hkv, bs, D]
            p.reshape(hkv, rep, bs).astype(v_ref.dtype), v_ref[0],
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32).reshape(hkv * rep, -1)
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_t - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def paged_decode_attention_kernel(
    q: jnp.ndarray, k_pool: jnp.ndarray, v_pool: jnp.ndarray,
    block_table, index, *, scale: Optional[float] = None,
    window: Optional[int] = None, interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """The Pallas paged decode kernel (single-token steps).

    Grid ``(B, T)`` with the table sweep innermost (sequential TPU grid
    order, like the flash kernels): the scalar-prefetched block table
    steers each step's K/V BlockSpec at pool block
    ``block_table[b, j]`` — indirection happens in the DMA index map,
    never as a gathered copy in HBM — and the per-slot depth (also
    prefetched) skips dead blocks, so a parked slot costs one skipped
    sweep and a live one exactly its prefix. Numerics match the jnp
    reference path of :func:`paged_decode_attention` (same masking and
    online softmax; pinned by `tests/test_paged_attention.py`).
    """
    b, h, s, d = q.shape
    if s != 1:
        raise ValueError(f"decode kernel takes single-token steps, got s={s}")
    n, hkv, bs, _ = k_pool.shape
    rep = _gqa_rep(q, k_pool)
    t = jnp.asarray(block_table, jnp.int32).shape[1]
    scale_v = (1.0 / math.sqrt(d)) if scale is None else scale
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    index = jnp.broadcast_to(jnp.asarray(index, jnp.int32), (b,))
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    qf = q.reshape(b, h, d)
    cp = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, t),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda bq, j, tbl, idx: (bq, 0, 0)),
            pl.BlockSpec((1, hkv, bs, d),
                         lambda bq, j, tbl, idx: (tbl[bq, j], 0, 0, 0)),
            pl.BlockSpec((1, hkv, bs, d),
                         lambda bq, j, tbl, idx: (tbl[bq, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda bq, j, tbl, idx: (bq, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, LANES), jnp.float32),  # running max
            pltpu.VMEM((h, LANES), jnp.float32),  # running denom
            pltpu.VMEM((h, d), jnp.float32),      # output accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale_v, bs=bs,
                          num_t=t, hkv=hkv, rep=rep, window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=cp(dimension_semantics=("arbitrary", "arbitrary")),
        interpret=bool(interpret),
    )(jnp.asarray(block_table, jnp.int32), index, qf, k_pool, v_pool)
    return out.reshape(b, h, 1, d)


def decode_attention(
    q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
    index, *, window: Optional[int] = None, rolling: bool = False,
    chunk: int = 512, scale: Optional[float] = None,
    history_only: bool = False, return_lse: bool = False,
):
    """Serving-path attention over a KV cache, at the bandwidth roofline.

    The naive decode step (what this replaced) expanded the cache to
    query-head count, cast it to f32, and scored every padded position —
    ~6x the necessary HBM traffic for a GQA model plus dead-position
    work. Here instead:

    - the cache is read in its STORAGE dtype (bf16 in serving); the
      score matmul accumulates in f32 on the MXU
      (``preferred_element_type``), like the training kernel;
    - K/V stay at kv-head granularity — q is grouped ``[B, H_kv, rep,
      S, D]`` against the unexpanded cache;
    - the sweep visits only ``ceil((index+S)/chunk)`` cache chunks via a
      dynamic-trip-count ``fori_loop`` with online softmax, so HBM
      traffic and compute are bounded by the VALID PREFIX, not the
      padded cache length.

    Args:
      q: ``[B, H, S, D]`` post-RoPE queries (``S`` tokens being decoded).
      k_cache/v_cache: ``[B, H_kv, L, D]`` cache, current tokens already
        written at their slots.
      index: tokens in the cache BEFORE this call (query global
        positions are ``index .. index+S-1``). Scalar int32, or a
        PER-ROW ``[B]`` int32 vector — the continuous-batching serving
        engine's path, where each batch row is an independent request
        slot at its own depth; masking is then per row and the chunk
        sweep is bounded by the DEEPEST row.
      window: sliding-window width (Mistral SWA); masks keys below
        ``q_pos - window + 1``.
      rolling: the cache is a RING buffer of size ``L`` (requires
        ``L >= window``): slot ``j`` holds the newest global position
        ``p ≡ j (mod L)`` with ``p <= index+S-1``. Slot→position is
        reconstructed arithmetically for masking; never-written slots
        (``p < 0``) are masked out.
      chunk: cache positions per loop iteration (clamped to ``L``; need
        not divide it — the tail chunk clamps its start and masks the
        overlap).
      history_only: the cache holds ONLY the ``index`` tokens BEFORE this
        call (the current block is NOT written): queries attend strictly
        to ``pos < index``. The chunked-prefill building block — merge
        the result with the block's own (windowed, causal) attention in
        logsumexp space.
      return_lse: also return per-row logsumexp ``[B, H, S]`` (for
        merging partials, as in ring attention).

    Returns ``[B, H, S, D]`` in q's dtype (plus lse under ``return_lse``).

    Single-token steps over SMALL caches (``_SINGLE_SHOT_MAX_KC_BYTES``,
    batch included) skip the loop entirely and run ONE fused masked pass
    over the whole cache: the loop's while/dynamic-slice machinery is a
    fixed per-layer cost that dwarfs the few extra megabytes of read at
    single-stream sizes, while large-batch/long-cache steps keep the
    prefix-bounded sweep (their extra read would scale with B·L).
    """
    b, h, s, d = q.shape
    hkv, cache_len = k_cache.shape[1], k_cache.shape[2]
    rep = _gqa_rep(q, k_cache)
    index = jnp.asarray(index, jnp.int32)
    if index.ndim > 1 or (index.ndim == 1 and index.shape[0] != b):
        raise ValueError(
            f"index must be a scalar or [B]={b} vector, got {index.shape}")
    if rolling:
        # Both invariants are static; violating either silently loses
        # in-window history, so fail loudly here instead.
        if window is None:
            raise ValueError("rolling=True needs a sliding window (the "
                             "ring holds only the newest position per "
                             "slot — unwindowed attention would silently "
                             "miss overwritten history)")
        if cache_len < window:
            raise ValueError(
                f"rolling cache length {cache_len} < window {window}: "
                "in-window keys would be overwritten before leaving the "
                "band")
    scale_v = (1.0 / math.sqrt(d)) if scale is None else scale
    # Short-cache single-token steps: one fused pass, no loop (see
    # docstring). The chunked loop remains for big-batch/long caches
    # (bounded HBM traffic) and multi-token prefill (bounded score
    # memory).
    kc_bytes = b * hkv * cache_len * d * jnp.dtype(k_cache.dtype).itemsize
    if s == 1 and kc_bytes <= _SINGLE_SHOT_MAX_KC_BYTES:
        chunk = cache_len
    # Chunks need NOT divide the cache: the final chunk's slice start is
    # clamped and the overlap with the previous chunk masked out (the
    # dedup term below), so a non-round cache length costs one partially
    # re-read chunk — never a degenerate chunk=1 sweep.
    chunk = min(chunk, cache_len)
    n_chunks = -(-cache_len // chunk)

    qg = q.reshape(b, hkv, rep, s, d)
    # Tokens the cache holds: through this block (written before the
    # call) unless history_only, where the block is attended separately.
    total = index if history_only else index + s
    # Global positions of the queries: [s] for a shared scalar index,
    # [B, s] for the per-row vector path ([..., None] makes the same
    # expression produce both ranks; every mask term below follows the
    # same pattern, so the two paths share one masking definition).
    q_pos = index[..., None] + jnp.arange(s)

    def _bcast(mask):
        """Lift a mask to broadcast against sb [b, g, r, s, chunk]:
        shared masks enter as [s, chunk] (or [1, chunk]); per-row masks
        as [B, s, chunk] (or [B, 1, chunk]) and gain the (g, r) axes."""
        return mask if mask.ndim == 2 else mask[:, None, None]

    def body(c, carry):
        m, l, acc = carry
        start = jnp.minimum(c * chunk, cache_len - chunk)  # clamped tail
        kc = jax.lax.dynamic_slice(
            k_cache, (0, 0, start, 0), (b, hkv, chunk, d))
        vc = jax.lax.dynamic_slice(
            v_cache, (0, 0, start, 0), (b, hkv, chunk, d))
        sb = jnp.einsum("bgrqd,bgkd->bgrqk", qg.astype(k_cache.dtype), kc,
                        preferred_element_type=jnp.float32) * scale_v
        slot = start + jnp.arange(chunk)
        dedup = slot >= c * chunk  # drop the clamped tail's re-read overlap
        if rolling:
            # Newest global position congruent to the slot index; jnp's
            # mod is non-negative, so unwritten slots land at p < 0.
            # Vector total: [B, 1] against slot [chunk] → per-row [B,
            # chunk] positions.
            t1 = total[..., None] - 1
            pos = t1 - (t1 - slot) % cache_len
            valid = pos >= 0
        else:
            pos = slot
            valid = None
        if history_only:
            # strictly pre-block keys; broadcasts against the per-query
            # window term below
            mask = pos[..., None, :] < index[..., None, None]
        else:
            mask = pos[..., None, :] <= q_pos[..., :, None]
        if window is not None:
            mask &= pos[..., None, :] > q_pos[..., :, None] - window
        if valid is not None:
            mask &= valid[..., None, :]
        mask &= dedup[None, :]
        sb = jnp.where(_bcast(mask), sb, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(sb, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(sb - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bgrqk,bgkd->bgrqd", p.astype(v_cache.dtype), vc,
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    # Bound the sweep to chunks overlapping the valid prefix — the
    # DEEPEST row's prefix on the vector path (shallower rows mask the
    # excess). A rolling cache is dense once wrapped, so every chunk is
    # live after that; the min() still trims the pre-wrap phase.
    live = jnp.minimum((jnp.max(total) + chunk - 1) // chunk, n_chunks)
    m0 = jnp.full((b, hkv, rep, s, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, hkv, rep, s, 1), jnp.float32)
    acc0 = jnp.zeros((b, hkv, rep, s, d), jnp.float32)
    if n_chunks == 1:
        # Whole cache in one pass — no while loop in the program at all.
        m, l, acc = body(0, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, live, body, (m0, l0, acc0))
    if history_only:
        # Rows with an empty valid prefix (index 0 — or a zero-depth row
        # on the vector path) must still produce the zero-iteration
        # result: a fully-masked pass makes every row's p uniform
        # (exp(NEG_INF - NEG_INF) == 1), so mask such rows back to the
        # inits instead of running on trust. Only history_only can be
        # empty — the regular path always sees at least the current
        # token (total = index + s >= 1) — so the decode hot path never
        # pays these wheres.
        keep = total[..., None, None, None, None] > 0
        m = jnp.where(keep, m, m0)
        l = jnp.where(keep, l, l0)
        acc = jnp.where(keep, acc, acc0)
    out = (acc / jnp.maximum(l, 1e-30)).reshape(b, h, s, d).astype(q.dtype)
    if return_lse:
        # Rows with nothing attended (empty history) keep lse ~ -inf so
        # a logsumexp-space merge gives them zero weight.
        lse = (m + jnp.log(jnp.maximum(l, 1e-30))).reshape(b, h, s)
        return out, lse
    return out
