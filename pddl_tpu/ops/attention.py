"""Attention ops: reference softmax attention + a Pallas TPU flash kernel.

The reference repo has no attention at all (fixed 224x224 CNN inputs,
SURVEY.md §5 "Long-context": absent) — this module exists because
long-context support is first-class in the TPU build, not an afterthought.
It provides the single-device kernels; cross-device sequence parallelism
lives in :mod:`pddl_tpu.ops.ring_attention`.

Design:

- :func:`attention_reference` — straight jnp (materializes the [Sq, Sk]
  score matrix); numerics oracle for tests and the fallback path.
- :func:`flash_attention` — blockwise online-softmax Pallas kernel: scores
  never leave VMEM, HBM traffic is O(S·d) instead of O(S²), q/k/v blocks
  are MXU-tiled matmuls. Grid is (batch·heads, q_blocks, k_blocks) with the
  k dimension innermost: TPU grids execute sequentially, so running max /
  normalizer / accumulator persist in VMEM scratch across the k sweep.
- Backward: ``flash_attention`` carries a ``jax.custom_vjp`` whose backward
  pass recomputes scores via the reference path (flash forward is where the
  memory win matters for inference/eval; a fused Pallas backward is a
  planned optimization — the API contract will not change).

All shapes are ``[batch, heads, seq, head_dim]``; dtypes bf16/f32 in, f32
accumulation inside (MXU-native mixed precision).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30  # large-negative instead of -inf: keeps masked softmax NaN-free


def attention_reference(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    *, causal: bool = False, scale: Optional[float] = None,
    k_offset: int = 0,
) -> jnp.ndarray:
    """Plain softmax attention (the numerics oracle).

    ``k_offset`` shifts key/value global positions for causal masking —
    used by ring attention where each shard sees a rotated K/V slice.
    """
    *_, sq, d = q.shape
    sk = k.shape[-2]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    s = jnp.einsum("...qd,...kd->...qk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        q_pos = jnp.arange(sq)[:, None]
        k_pos = jnp.arange(sk)[None, :] + k_offset
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p, v.astype(jnp.float32)).astype(q.dtype)


# --------------------------------------------------------------- flash fwd
def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, scale: float, causal: bool, block_q: int, block_k: int,
                  num_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: skip blocks strictly above the diagonal.
    run = True
    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale          # (bq, d)
        k = k_ref[0].astype(jnp.float32)                  # (bk, d)
        s = jax.lax.dot_general(                          # (bq, bk) on MXU
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)

        m_prev = m_ref[:, :1]                             # (bq, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                   # rescale old stats
        p = jnp.exp(s - m_new)                            # (bq, bk)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(                         # (bq, d) on MXU
            p, v_ref[0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    *, causal: bool = False, scale: Optional[float] = None,
    block_q: int = 128, block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention with a reference-path backward (see module docs).

    Falls back to :func:`attention_reference` when shapes don't block-tile
    (tiny test shapes) — call sites never need to special-case.
    """
    *_, sq, d = q.shape
    sk = k.shape[-2]
    scale_v = (1.0 / math.sqrt(d)) if scale is None else scale
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bq = _largest_dividing_block(sq, block_q)
    bk = _largest_dividing_block(sk, block_k)
    if bq < 8 or bk < 8:
        # Degenerate tiling (e.g. prime-ish lengths): the kernel would run
        # sub-VPU-width blocks slower than one fused XLA softmax.
        return attention_reference(q, k, v, causal=causal, scale=scale_v)
    return _flash(q, k, v, causal, scale_v, bq, bk, bool(interpret))


def _largest_dividing_block(n: int, want: int) -> int:
    """Largest block <= ``want`` that tiles ``n`` evenly.

    ViT token counts are rarely powers of two (224/16 -> 196 tokens), so a
    fixed 128 block would never divide and the kernel would silently fall
    back; 196 tiles as 98."""
    for b in range(min(want, n), 0, -1):
        if n % b == 0:
            return b
    return 1


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[-2]
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    num_q = pl.cdiv(sq, block_q)
    num_k = pl.cdiv(sk, block_k)
    from jax.experimental.pallas import tpu as pltpu  # noqa: PLC0415

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k=num_k,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, num_q, num_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),   # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),     # output accumulator
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(
            q_, k_, v_, causal=causal, scale=scale),
        q, k, v,
    )
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)
