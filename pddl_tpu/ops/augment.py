"""Jittable image preprocessing/augmentation (device-side, train-only).

Parity with the reference's preprocessing stack:

- ``Rescaling(1./255)`` — ``/root/reference/imagenet-resnet50.py:53`` →
  :func:`rescale`.
- ``RandomCrop`` — ``imagenet-resnet50.py:54`` → :func:`random_crop`. Note
  the reference's quirk: ``RandomCrop(244, 244)`` on a 224x224 input (a
  typo for 224; SURVEY.md §0) makes Keras upscale-then-crop. We implement
  the *intended* semantics (crop ≤ input, pad if larger) — a deliberate
  faithfulness fix, documented here.
- ``RandomFlip("horizontal")`` — ``imagenet-resnet50.py:55`` →
  :func:`random_flip_horizontal`.
- ``tf.image.resize_with_crop_or_pad(i, 224, 224)`` (map-time, ``:36-41``)
  → :func:`center_crop_or_pad`.

All functions take explicit PRNG keys (functional randomness — the
determinism story the reference lacks) and are shape-static so XLA fuses
them into the surrounding step with no extra HBM round-trips.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def rescale(x: jnp.ndarray, scale: float = 1.0 / 255, offset: float = 0.0) -> jnp.ndarray:
    return x * scale + offset


def center_crop_or_pad(x: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    """``tf.image.resize_with_crop_or_pad`` semantics, static shapes.

    Works on [..., H, W, C]. Crops centrally when larger, zero-pads evenly
    when smaller (TF pads bottom/right the extra pixel; we match).
    """
    h, w = x.shape[-3], x.shape[-2]

    def _axis(cur: int, tgt: int, axis: int, arr: jnp.ndarray) -> jnp.ndarray:
        if cur > tgt:
            start = (cur - tgt) // 2
            arr = jax.lax.slice_in_dim(arr, start, start + tgt, axis=axis)
        elif cur < tgt:
            before = (tgt - cur) // 2
            after = tgt - cur - before
            pad = [(0, 0)] * arr.ndim
            pad[axis] = (before, after)
            arr = jnp.pad(arr, pad)
        return arr

    x = _axis(h, height, x.ndim - 3, x)
    x = _axis(w, width, x.ndim - 2, x)
    return x


def random_crop(rng: jax.Array, x: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    """Per-image random crop of a [B, H, W, C] batch (pads first if smaller)."""
    if x.shape[-3] < height or x.shape[-2] < width:
        x = center_crop_or_pad(
            x, max(height, x.shape[-3]), max(width, x.shape[-2])
        )
    b, h, w, _ = x.shape
    keys = jax.random.split(rng, b)

    def _one(key, img):
        kh, kw = jax.random.split(key)
        top = jax.random.randint(kh, (), 0, h - height + 1)
        left = jax.random.randint(kw, (), 0, w - width + 1)
        return jax.lax.dynamic_slice(
            img, (top, left, 0), (height, width, img.shape[-1])
        )

    return jax.vmap(_one)(keys, x)


def random_flip_horizontal(rng: jax.Array, x: jnp.ndarray) -> jnp.ndarray:
    """Per-image horizontal flip with p=0.5 on [B, H, W, C]."""
    flip = jax.random.bernoulli(rng, 0.5, (x.shape[0],))
    flipped = jnp.flip(x, axis=-2)
    return jnp.where(flip[:, None, None, None], flipped, x)


def standard_augment(
    crop: Optional[int] = 224,
    flip: bool = True,
    rescale_factor: Optional[float] = 1.0 / 255,
) -> Callable[[jax.Array, jnp.ndarray], jnp.ndarray]:
    """The reference's full augmentation stack as one jittable fn.

    Equivalent to the model-graph prelude Rescaling -> RandomCrop ->
    RandomFlip (``imagenet-resnet50.py:53-55``), with the RandomCrop size
    bug fixed to the intended 224.
    """

    def _augment(rng: jax.Array, x: jnp.ndarray) -> jnp.ndarray:
        if rescale_factor is not None:
            x = rescale(x, rescale_factor)
        if crop is not None:
            crop_rng, rng = jax.random.split(rng)
            x = random_crop(crop_rng, x, crop, crop)
        if flip:
            flip_rng, rng = jax.random.split(rng)
            x = random_flip_horizontal(flip_rng, x)
        return x

    return _augment


def standard_eval_transform(
    crop: Optional[int] = 224,
    rescale_factor: Optional[float] = 1.0 / 255,
) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Deterministic eval/predict counterpart of :func:`standard_augment`.

    In the reference, ``Rescaling`` runs at inference too and ``RandomCrop``
    center-crops when not training (Keras preprocessing-layer semantics), so
    evaluation sees the same input distribution as training. This returns
    that deterministic pipeline: rescale + center crop/pad — pass it as
    ``Trainer(eval_transform=...)`` whenever ``augment`` is set.
    """

    def _transform(x: jnp.ndarray) -> jnp.ndarray:
        if rescale_factor is not None:
            x = rescale(x, rescale_factor)
        if crop is not None:
            x = center_crop_or_pad(x, crop, crop)
        return x

    return _transform
