"""Chunked large-vocab softmax cross-entropy: loss without the logits.

For a big-vocab LM the logits tensor dominates peak memory: GPT-2's
50257-way head at B8 S2048 is a 3.3 GB f32 array that exists only to be
consumed by the loss (the reference tops out at a 1000-way Dense,
``/root/reference/imagenet-resnet50.py:60`` — this is a beyond-parity,
TPU-memory-shaped op). :func:`chunked_cross_entropy` fuses the head
matmul into the loss: it scans the vocab in chunks, keeping a running
online logsumexp (the flash-attention trick applied to the classifier),
and the backward recomputes each chunk's logits from the saved LSE — so
peak extra memory is ``[tokens, chunk_size]`` instead of
``[tokens, vocab]``, at the cost of one extra pass of head-matmul FLOPs
in the backward.

Integration: :func:`pddl_tpu.models.gpt.fused_lm_loss` is the
first-class path — the GPT family's ``features_only`` apply mode feeds
this op directly (gradients flow to features, kernel, and bias exactly
as if the full logits had been built; equivalence incl. the bf16
configuration in ``tests/test_gpt.py``, op-level coverage in
``tests/test_large_vocab.py``).

Measured on v5e (GPT-2-small shape, B8 S2048 V50257, head+CE fwd+bwd):
at ``chunk_size = vocab`` (one fused step, the speed setting) the custom
VJP beats the materialized logits path 33.7 vs 39.7 ms — only logsumexp
rows cross the fwd/bwd boundary, though the forward still builds one
transient ``[tokens, V]`` f32 chunk. Sub-vocab chunks (e.g. 4096) are
wall-clock-neutral vs the logits path with ~0.8 GB lower peak temp
allocation — the memory-headroom setting for long context / large
vocabs. Matmuls run on the operands' storage dtype with f32
accumulation (``_dot_acc32``), matching a ``Dense(dtype=bf16)`` head's
semantics while keeping the softmax math f32.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _pad_vocab(kernel, bias, chunk_size):
    """Pad V up to a chunk multiple; padded classes get bias -1e30 (their
    exp underflows to exactly 0 in the sumexp, and labels never point at
    them)."""
    v = kernel.shape[-1]
    pad = (-v) % chunk_size
    if pad:
        kernel = jnp.pad(kernel, ((0, 0), (0, pad)))
        bias = jnp.pad(bias, (0, pad), constant_values=-1e30)
    return kernel, bias, v + pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _chunked_ce(features, kernel, bias, labels, chunk_size):
    loss, _ = _forward(features, kernel, bias, labels, chunk_size)
    return loss


def _dot_acc32(a, b):
    """``a @ b`` in the operands' storage dtype, f32 accumulation.

    bf16 operands ride the MXU at full rate (upcasting them to f32 first
    would lower to the slower multi-pass f32 emulation) while the
    accumulator — and everything softmax-related downstream — stays f32.
    This also matches the materialized head's semantics exactly: a
    ``Dense(dtype=bf16)`` computes its matmul from bf16 operands too.
    """
    return jax.lax.dot_general(
        a, b.astype(a.dtype), (((a.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _forward(features, kernel, bias, labels, chunk_size):
    n, e = features.shape
    kernel_p, bias_p, v_pad = _pad_vocab(kernel, bias, chunk_size)
    n_chunks = v_pad // chunk_size
    # Scan carries: running max, normalized sumexp, label logit.

    def body(carry, ci):
        m, s, lab = carry
        k_c = jax.lax.dynamic_slice_in_dim(
            kernel_p, ci * chunk_size, chunk_size, axis=1)
        b_c = jax.lax.dynamic_slice_in_dim(
            bias_p, ci * chunk_size, chunk_size, axis=0)
        logits = _dot_acc32(features, k_c) + b_c.astype(jnp.float32)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        local = labels - ci * chunk_size
        in_chunk = (local >= 0) & (local < chunk_size)
        gathered = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk_size - 1)[:, None], axis=1
        )[:, 0]
        lab = jnp.where(in_chunk, gathered, lab)
        return (m_new, s, lab), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, lab), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    lse = m + jnp.log(s)
    loss = jnp.mean(lse - lab)
    return loss, lse


def _fwd(features, kernel, bias, labels, chunk_size):
    loss, lse = _forward(features, kernel, bias, labels, chunk_size)
    return loss, (features, kernel, bias, labels, lse)


def _bwd(chunk_size, res, g):
    features, kernel, bias, labels, lse = res
    n, e = features.shape
    kernel_p, bias_p, v_pad = _pad_vocab(kernel, bias, chunk_size)
    n_chunks = v_pad // chunk_size
    scale = g / n  # d(mean)/d(token)

    def body(carry, ci):
        dfeat = carry
        k_c = jax.lax.dynamic_slice_in_dim(
            kernel_p, ci * chunk_size, chunk_size, axis=1)
        b_c = jax.lax.dynamic_slice_in_dim(
            bias_p, ci * chunk_size, chunk_size, axis=0).astype(jnp.float32)
        # Recompute this chunk's probabilities from the saved LSE.
        p = jnp.exp(_dot_acc32(features, k_c) + b_c - lse[:, None])  # [N, C]
        local = labels - ci * chunk_size
        in_chunk = (local >= 0) & (local < chunk_size)
        onehot = (jnp.clip(local, 0, chunk_size - 1)[:, None]
                  == jnp.arange(chunk_size)[None, :]) & in_chunk[:, None]
        delta = (p - onehot) * scale                  # [N, C] f32
        # Backward matmuls in the features dtype as well (the cotangent of
        # a bf16 Dense is bf16); accumulation stays f32.
        delta_d = delta.astype(features.dtype)
        dfeat = dfeat + _dot_acc32(delta_d, k_c.T)    # [N, E]
        dk_c = _dot_acc32(features.T, delta_d)        # [E, C]
        db_c = jnp.sum(delta, axis=0)                 # [C]
        return dfeat, (dk_c, db_c)

    dfeat0 = jnp.zeros((n, e), jnp.float32)
    dfeat, (dk_chunks, db_chunks) = jax.lax.scan(
        body, dfeat0, jnp.arange(n_chunks))
    # [n_chunks, E, C] -> [E, V_pad] -> trim padding.
    dk = dk_chunks.transpose(1, 0, 2).reshape(e, v_pad)
    db = db_chunks.reshape(v_pad)
    v = kernel.shape[-1]
    return (dfeat.astype(features.dtype), dk[:, :v].astype(kernel.dtype),
            db[:v].astype(bias.dtype), None)


_chunked_ce.defvjp(_fwd, _bwd)


def chunked_cross_entropy(
    features: jnp.ndarray,
    kernel: jnp.ndarray,
    labels: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    *,
    chunk_size: int = 8192,
) -> jnp.ndarray:
    """Mean token CE of ``softmax(features @ kernel + bias)`` vs ``labels``
    without materializing the logits.

    Args:
      features: ``[..., E]`` pre-head activations (any leading dims).
      kernel: ``[E, V]`` lm-head weight.
      labels: integer ``[...]`` matching the leading dims.
      bias: optional ``[V]``.
      chunk_size: vocab slab per scan step; peak extra memory is
        ``tokens x chunk_size`` floats. V is padded internally to a
        multiple.

    Returns the scalar mean cross-entropy (f32). Gradients flow to
    features/kernel/bias via a custom VJP that recomputes per-chunk
    logits from the saved logsumexp.
    """
    e = features.shape[-1]
    flat = features.reshape(-1, e)
    flat_labels = labels.reshape(-1).astype(jnp.int32)
    if bias is None:
        bias = jnp.zeros((kernel.shape[-1],), jnp.float32)
    # Never scan wider than the vocab: a small head would otherwise pad
    # up to a full default-width chunk and waste the extra matmul FLOPs.
    chunk_size = min(chunk_size, kernel.shape[-1])
    return _chunked_ce(flat, kernel, bias, flat_labels, chunk_size)
