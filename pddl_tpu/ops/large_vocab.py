"""Chunked large-vocab softmax cross-entropy: loss without the logits.

For a big-vocab LM the logits tensor dominates peak memory: GPT-2's
50257-way head at B8 S2048 is a 3.3 GB f32 array that exists only to be
consumed by the loss (the reference tops out at a 1000-way Dense,
``/root/reference/imagenet-resnet50.py:60`` — this is a beyond-parity,
TPU-memory-shaped op). :func:`chunked_cross_entropy` fuses the head
matmul into the loss: it scans the vocab in chunks, keeping a running
online logsumexp (the flash-attention trick applied to the classifier),
and the backward recomputes each chunk's logits from the saved LSE — so
peak extra memory is ``[tokens, chunk_size]`` instead of
``[tokens, vocab]``, at the cost of one extra pass of head-matmul FLOPs
in the backward.

Integration: apply the transformer WITHOUT its lm_head (features
``[B, S, E]``), keep the head kernel/bias as ordinary params, and make
this op the loss — gradients flow to features, kernel, and bias exactly
as if the full logits had been built (verified bitwise-close in
``tests/test_large_vocab.py``, which also shows the
``capture_intermediates`` integration pattern on the GPT family).

Measured on v5e (GPT-2-small shape, B8 S2048 V50257, chunk 4096,
loss+grad step — ``benchmarks/large_vocab_bench.py``): identical loss
and wall-clock to the logits path (~193 ms/step both) with 0.8 GB lower
peak temp allocation; the win is headroom — larger batches/sequences
fit before the loss becomes the memory ceiling.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _pad_vocab(kernel, bias, chunk_size):
    """Pad V up to a chunk multiple; padded classes get bias -1e30 (their
    exp underflows to exactly 0 in the sumexp, and labels never point at
    them)."""
    v = kernel.shape[-1]
    pad = (-v) % chunk_size
    if pad:
        kernel = jnp.pad(kernel, ((0, 0), (0, pad)))
        bias = jnp.pad(bias, (0, pad), constant_values=-1e30)
    return kernel, bias, v + pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _chunked_ce(features, kernel, bias, labels, chunk_size):
    loss, _ = _forward(features, kernel, bias, labels, chunk_size)
    return loss


def _forward(features, kernel, bias, labels, chunk_size):
    n, e = features.shape
    kernel_p, bias_p, v_pad = _pad_vocab(kernel, bias, chunk_size)
    n_chunks = v_pad // chunk_size
    # Scan carries: running max, normalized sumexp, label logit.
    f32 = features.astype(jnp.float32)

    def body(carry, ci):
        m, s, lab = carry
        k_c = jax.lax.dynamic_slice_in_dim(
            kernel_p, ci * chunk_size, chunk_size, axis=1)
        b_c = jax.lax.dynamic_slice_in_dim(
            bias_p, ci * chunk_size, chunk_size, axis=0)
        logits = f32 @ k_c.astype(jnp.float32) + b_c.astype(jnp.float32)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        local = labels - ci * chunk_size
        in_chunk = (local >= 0) & (local < chunk_size)
        gathered = jnp.take_along_axis(
            logits, jnp.clip(local, 0, chunk_size - 1)[:, None], axis=1
        )[:, 0]
        lab = jnp.where(in_chunk, gathered, lab)
        return (m_new, s, lab), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, lab), _ = jax.lax.scan(body, init, jnp.arange(n_chunks))
    lse = m + jnp.log(s)
    loss = jnp.mean(lse - lab)
    return loss, lse


def _fwd(features, kernel, bias, labels, chunk_size):
    loss, lse = _forward(features, kernel, bias, labels, chunk_size)
    return loss, (features, kernel, bias, labels, lse)


def _bwd(chunk_size, res, g):
    features, kernel, bias, labels, lse = res
    n, e = features.shape
    kernel_p, bias_p, v_pad = _pad_vocab(kernel, bias, chunk_size)
    n_chunks = v_pad // chunk_size
    f32 = features.astype(jnp.float32)
    scale = g / n  # d(mean)/d(token)

    def body(carry, ci):
        dfeat = carry
        k_c = jax.lax.dynamic_slice_in_dim(
            kernel_p, ci * chunk_size, chunk_size, axis=1).astype(jnp.float32)
        b_c = jax.lax.dynamic_slice_in_dim(
            bias_p, ci * chunk_size, chunk_size, axis=0).astype(jnp.float32)
        # Recompute this chunk's probabilities from the saved LSE.
        p = jnp.exp(f32 @ k_c + b_c - lse[:, None])  # [N, C]
        local = labels - ci * chunk_size
        in_chunk = (local >= 0) & (local < chunk_size)
        onehot = (jnp.clip(local, 0, chunk_size - 1)[:, None]
                  == jnp.arange(chunk_size)[None, :]) & in_chunk[:, None]
        delta = (p - onehot) * scale                  # [N, C]
        dfeat = dfeat + delta @ k_c.T                 # [N, E]
        dk_c = f32.T @ delta                          # [E, C]
        db_c = jnp.sum(delta, axis=0)                 # [C]
        return dfeat, (dk_c, db_c)

    dfeat0 = jnp.zeros((n, e), jnp.float32)
    dfeat, (dk_chunks, db_chunks) = jax.lax.scan(
        body, dfeat0, jnp.arange(n_chunks))
    # [n_chunks, E, C] -> [E, V_pad] -> trim padding.
    dk = dk_chunks.transpose(1, 0, 2).reshape(e, v_pad)
    db = db_chunks.reshape(v_pad)
    v = kernel.shape[-1]
    return (dfeat.astype(features.dtype), dk[:, :v].astype(kernel.dtype),
            db[:v].astype(bias.dtype), None)


_chunked_ce.defvjp(_fwd, _bwd)


def chunked_cross_entropy(
    features: jnp.ndarray,
    kernel: jnp.ndarray,
    labels: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    *,
    chunk_size: int = 8192,
) -> jnp.ndarray:
    """Mean token CE of ``softmax(features @ kernel + bias)`` vs ``labels``
    without materializing the logits.

    Args:
      features: ``[..., E]`` pre-head activations (any leading dims).
      kernel: ``[E, V]`` lm-head weight.
      labels: integer ``[...]`` matching the leading dims.
      bias: optional ``[V]``.
      chunk_size: vocab slab per scan step; peak extra memory is
        ``tokens x chunk_size`` floats. V is padded internally to a
        multiple.

    Returns the scalar mean cross-entropy (f32). Gradients flow to
    features/kernel/bias via a custom VJP that recomputes per-chunk
    logits from the saved logsumexp.
    """
    e = features.shape[-1]
    flat = features.reshape(-1, e)
    flat_labels = labels.reshape(-1).astype(jnp.int32)
    if bias is None:
        bias = jnp.zeros((kernel.shape[-1],), jnp.float32)
    # Never scan wider than the vocab: a small head would otherwise pad
    # up to a full default-width chunk and waste the extra matmul FLOPs.
    chunk_size = min(chunk_size, kernel.shape[-1])
    return _chunked_ce(flat, kernel, bias, flat_labels, chunk_size)
