"""Batched LoRA gather-matmul: the device half of per-request adapters.

S-LoRA (Sheng et al., 2023) shows that thousands of LoRA adapters can
share ONE batched forward pass when the adapter weights live in a
fixed-shape device pool and each batch row gathers its own factors by
integer id — the exact pattern the serving engine already uses for
per-slot sampling params and KV block tables: all per-slot variation
is RUNTIME DATA, never compiled-program shape. These ops are that
pattern applied to low-rank weight deltas.

Pool layout (see `pddl_tpu/serve/tenant/adapters.py` for the host-side
registry/refcount/LRU machinery):

    pool_a  [P, d, r]   down-projection factors, one row per pool slot
    pool_b  [P, r, V]   up-projection factors (scale pre-folded)

Row 0 is the reserved IDENTITY row (all zeros — the "no adapter" case,
mirroring the KV block pool's scratch-block-0 convention): a slot whose
adapter id is 0 computes ``(h @ 0) @ 0 == 0`` and adds an exact float
zero to its logits, so unadapted requests in a mixed batch are
bit-identical to the base model with no branch in the compiled program.

The adapted matrix in this repo's v1 tenancy scope is the LM HEAD
(``delta_logits = (h @ A) @ B``): adapting only the output projection
keeps every KV cache entry ADAPTER-INVARIANT — K/V remain pure
functions of (prompt tokens, position, base params) — which is what
lets the prefix cache and the paged block pool keep sharing prompt KV
ACROSS tenants (an attention-projection LoRA would make shared blocks
wrong for every other adapter). See docs/SERVING.md § "Multi-tenant
serving" for the trade-off discussion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# The reserved identity pool row (all-zero factors = base model); the
# host-side adapter pool never assigns it. Mirrors
# `serve/kvcache/block_pool.SCRATCH_BLOCK`.
IDENTITY_ROW = 0


def batched_lora_delta(feats, pool_a, pool_b, rows):
    """Per-row low-rank logit deltas for one fused serving tick.

    Args:
      feats: ``[B, d]`` pre-head features (post-final-norm — the tensor
        the LM head consumes).
      pool_a: ``[P, d, r]`` pooled down factors.
      pool_b: ``[P, r, V]`` pooled up factors (scaling pre-folded).
      rows: ``[B]`` int32 pool-row ids (0 = identity/no adapter).

    Returns ``[B, V]`` float32 deltas to add to the base logits. All of
    ``rows`` is runtime data: one compiled program serves every tenant
    mix, and gathers cost O(B·(d·r + r·V)) regardless of how many
    adapters are registered.
    """
    a = jnp.take(pool_a, rows, axis=0)  # [B, d, r]
    b = jnp.take(pool_b, rows, axis=0)  # [B, r, V]
    z = jnp.einsum("bd,bdr->br", feats.astype(jnp.float32), a)
    return jnp.einsum("br,brv->bv", z, b)


def adapter_pool_load(pool_a, pool_b, row, a, b):
    """Load one adapter's factors into pool row ``row`` (runtime value —
    one compiled program loads into any slot). Returns the updated
    ``(pool_a, pool_b)``; NOT donated by the engine on purpose: the
    update copies, so a faulted load can simply retry against the
    intact old pool (no consumed-buffer hazard, unlike the KV trees)."""
    row = jnp.asarray(row, jnp.int32)
    return (jax.lax.dynamic_update_index_in_dim(
                pool_a, a.astype(pool_a.dtype), row, 0),
            jax.lax.dynamic_update_index_in_dim(
                pool_b, b.astype(pool_b.dtype), row, 0))


def merge_lora_into_head(params, a, b):
    """TEST ORACLE: the merged-weights reference — fold one adapter into
    ``lm_head.kernel`` of a params tree (``W' = W + A @ B``, scale
    already folded into ``b`` like the pool stores it). Returns a new
    tree; the batched pooled apply must be token-exact against
    ``generate()`` over these merged params."""
    merged = dict(params)
    head = dict(merged["lm_head"])
    kernel = head["kernel"]
    v = b.shape[-1]
    delta = jnp.asarray(a, kernel.dtype) @ jnp.asarray(b, kernel.dtype)
    head["kernel"] = kernel.at[:, :v].add(delta)
    merged["lm_head"] = head
    return merged
