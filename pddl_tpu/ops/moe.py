"""Mixture-of-Experts: top-k routed FFN (Switch top-1 / GShard top-2),
expert-parallel ready.

Beyond-parity capability (the reference has no MoE — SURVEY.md §2c lists
expert parallelism as absent; the mesh reserves an ``expert`` axis for it,
``pddl_tpu/core/mesh.py``). TPU-first formulation:

- **Dense one-hot dispatch** (the Mesh-TF/Switch-Transformer pattern):
  routing becomes two einsums against a ``[tokens, experts, capacity]``
  dispatch tensor — all FLOPs are MXU contractions with static shapes; no
  gather/scatter, no dynamic shapes, nothing XLA can't tile.
- **Expert-major weights**: expert FFN kernels are ``[n_experts, ...]`` so
  sharding dim 0 over the ``expert`` mesh axis places one expert group per
  device; XLA lowers the dispatch/combine einsums to the all-to-alls.
- **Capacity factor**: batch rows are the dispatch groups; each expert
  processes at most ``capacity_factor * top_k * seq / n_experts`` tokens
  per group (dispatch tensors are ``[B, S, N, C]`` — linear in batch;
  top-2 routes twice the token-slots, so capacity scales with ``top_k``).
  Overflow tokens pass through the residual (standard Switch behavior),
  keeping per-expert work static-shaped.
- **Load-balancing aux loss** (Switch loss: ``n·Σ fᵢ·Pᵢ``) is exported via
  ``self.sow("losses", ...)``; the Trainer adds every sown loss to the
  task loss.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


class SwitchFFN(nn.Module):
    """Top-k routed expert FFN (drop-in for a transformer MLP block).

    Input/output ``[batch, seq, embed]``. ``top_k=1`` is the Switch
    Transformer; ``top_k=2`` is GShard/Mixtral-style routing where every
    token is processed by its two highest-probability experts with the
    two gates renormalized to sum to one (``normalize_gates`` — exactly
    transformers' Mixtral routing: softmax over all experts, top-k,
    renormalize by the kept sum), second choices queueing behind the
    group's first choices for capacity.

    Expert architecture (``expert_act``):

    - ``"gelu"`` — two-layer GELU FFN with biases, hidden
      ``mlp_ratio·embed`` (the Switch classic; the ViT family's MoE).
    - ``"swiglu"`` — ``w2·(silu(x·w1) ⊙ (x·w3))``, bias-free, hidden
      ``hidden_dim`` (the Mixtral expert; parameter names w1/w3/w2
      follow the HF checkpoint layout so
      :func:`pddl_tpu.ckpt.hf_import.load_hf_llama` maps them 1:1).
    """

    num_experts: int
    mlp_ratio: int = 4
    hidden_dim: int | None = None  # overrides mlp_ratio * embed when set
    top_k: int = 1
    capacity_factor: float = 1.25
    expert_act: str = "gelu"  # "gelu" | "swiglu" (Mixtral)
    normalize_gates: bool = True  # top_k >= 2: g_j / sum_j g_j
    aux_loss_weight: float = 0.01
    # Eval/serving (train=False) uses capacity == seq — enough for the
    # worst case: the k choices per token are DISTINCT experts (each
    # choice zeroes its expert from `remaining`), so one expert can
    # receive at most S tokens per batch row. Inference is therefore
    # DROPLESS regardless of capacity_factor. Real Mixtral checkpoints
    # assume dropless routing; without this, an imbalanced prompt
    # silently diverges from the reference logits. The price is
    # dispatch/combine tensors growing to [B, S, N, S] at eval.
    eval_dropless: bool = True
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True, /):
        # train is positional-only to match the transformer blocks'
        # remat static_argnums convention (vit.TransformerBlock).
        b, s, d = x.shape
        n = self.num_experts
        if not 1 <= self.top_k <= n:
            raise ValueError(
                f"top_k={self.top_k} must be in [1, num_experts={n}]")
        if self.expert_act not in ("gelu", "swiglu"):
            raise ValueError(f"unknown expert_act {self.expert_act!r}")
        # Batch rows are the dispatch groups (the Switch/Mesh-TF "group"
        # dim): capacity is per group, so dispatch/combine are
        # [B, S, N, C] — linear in batch, never quadratic in total tokens.
        # top-2 doubles routed token-slots, so capacity scales with k.
        if not train and self.eval_dropless:
            capacity = s
        else:
            capacity = max(1, int(self.capacity_factor * self.top_k * s / n))
        hidden = self.hidden_dim if self.hidden_dim is not None \
            else d * self.mlp_ratio

        # Router (f32 for a stable softmax regardless of compute dtype).
        router_logits = nn.Dense(
            n, dtype=jnp.float32, param_dtype=self.param_dtype, name="router"
        )(x.astype(jnp.float32))
        probs = nn.softmax(router_logits, axis=-1)            # (B, S, N)

        # k sequential choices (k is tiny and static — an unrolled Python
        # loop of MXU-friendly one-hot ops, no sorting network needed).
        # Choice j's queue positions start after the KEPT tokens of
        # choices < j (mesh-tf top-2 convention), so second choices never
        # displace first choices from an expert's capacity.
        remaining = probs
        offset = jnp.zeros((b, n), probs.dtype)     # kept tokens per expert
        gates, dispatches = [], []
        first_choice_onehot = None
        for _ in range(self.top_k):
            gate = jnp.max(remaining, axis=-1)                # (B, S)
            raw_onehot = nn.one_hot(
                jnp.argmax(remaining, axis=-1), n)            # (B, S, N)
            if first_choice_onehot is None:
                first_choice_onehot = raw_onehot
            remaining = remaining * (1.0 - raw_onehot)
            position = (jnp.cumsum(raw_onehot, axis=1)
                        + offset[:, None, :]) * raw_onehot    # 1-based
            onehot = raw_onehot * (position <= capacity)
            offset = offset + jnp.sum(onehot, axis=1)
            pos_in_expert = (position - 1.0) * onehot         # 0-based
            dispatches.append(onehot[..., None] * nn.one_hot(
                pos_in_expert.sum(axis=-1).astype(jnp.int32), capacity
            )[..., None, :])                                  # (B, S, N, C)
            gates.append(gate)

        if self.top_k > 1 and self.normalize_gates:
            denom = sum(gates) + 1e-9
            gates = [g / denom for g in gates]

        dispatch = sum(dispatches)
        # Dropped tokens have an all-zero dispatch row, so gating needs no
        # explicit kept mask.
        combine = sum(dsp * g[..., None, None]
                      for dsp, g in zip(dispatches, gates))

        # Load-balancing loss BEFORE capacity drop (Switch eq. 4-6; for
        # top-k the token fraction counts FIRST choices, per GShard):
        # n * sum_i( fraction_of_tokens_i * mean_router_prob_i ).
        frac = jnp.mean(first_choice_onehot, axis=(0, 1))
        mean_prob = jnp.mean(probs, axis=(0, 1))
        aux = self.aux_loss_weight * n * jnp.sum(frac * mean_prob)
        self.sow("losses", "moe_aux_loss", aux)
        # Measured capacity-drop observable: the fraction of routed
        # token-slots (top_k per token) whose expert queue was already
        # full, i.e. tokens this layer silently skipped. `offset` is the
        # kept count per (batch row, expert) after all k choices. Sown
        # into "metrics" (surfaced into the training logs by the
        # Trainer); exactly 0.0 on the dropless eval path.
        kept = jnp.sum(offset)
        drop_rate = 1.0 - kept / (b * s * self.top_k)
        self.sow("metrics", "moe_drop_rate", drop_rate)

        dispatch = dispatch.astype(self.dtype)
        combine = combine.astype(self.dtype)
        xc = x.astype(self.dtype)

        # Expert-major parameters: dim 0 shards over the `expert` mesh axis.
        # batch_axis=(0,): the expert dim must not count toward fan-in, or
        # every expert initializes sqrt(n) too small.
        he = nn.initializers.he_normal(batch_axis=(0,))

        # Dispatch -> expert FFN -> combine: all MXU einsums, static shapes.
        expert_in = jnp.einsum("bsnc,bsd->bncd", dispatch, xc)
        if self.expert_act == "swiglu":
            w1 = self.param("w1", he, (n, d, hidden),
                            self.param_dtype).astype(self.dtype)  # gate
            w3 = self.param("w3", he, (n, d, hidden),
                            self.param_dtype).astype(self.dtype)  # up
            w2 = self.param("w2", he, (n, hidden, d),
                            self.param_dtype).astype(self.dtype)  # down
            gate_h = jnp.einsum("bncd,ndh->bnch", expert_in, w1)
            up_h = jnp.einsum("bncd,ndh->bnch", expert_in, w3)
            expert_out = jnp.einsum("bnch,nhd->bncd",
                                    nn.silu(gate_h) * up_h, w2)
        else:
            w1 = self.param("w1", he, (n, d, hidden),
                            self.param_dtype).astype(self.dtype)
            b1 = self.param("b1", nn.initializers.zeros, (n, hidden),
                            self.param_dtype).astype(self.dtype)
            w2 = self.param("w2", he, (n, hidden, d),
                            self.param_dtype).astype(self.dtype)
            b2 = self.param("b2", nn.initializers.zeros, (n, d),
                            self.param_dtype).astype(self.dtype)
            h = nn.gelu(jnp.einsum("bncd,ndh->bnch", expert_in, w1)
                        + b1[:, None, :])
            expert_out = jnp.einsum("bnch,nhd->bncd", h, w2) + b2[:, None, :]
        return jnp.einsum("bsnc,bncd->bsd", combine, expert_out)
