"""Mixture-of-Experts: Switch-style top-1 routed FFN, expert-parallel ready.

Beyond-parity capability (the reference has no MoE — SURVEY.md §2c lists
expert parallelism as absent; the mesh reserves an ``expert`` axis for it,
``pddl_tpu/core/mesh.py``). TPU-first formulation:

- **Dense one-hot dispatch** (the Mesh-TF/Switch-Transformer pattern):
  routing becomes two einsums against a ``[tokens, experts, capacity]``
  dispatch tensor — all FLOPs are MXU contractions with static shapes; no
  gather/scatter, no dynamic shapes, nothing XLA can't tile.
- **Expert-major weights**: expert FFN kernels are ``[n_experts, ...]`` so
  sharding dim 0 over the ``expert`` mesh axis places one expert group per
  device; XLA lowers the dispatch/combine einsums to the all-to-alls.
- **Capacity factor**: batch rows are the dispatch groups; each expert
  processes at most ``capacity_factor * seq / n_experts`` tokens per group
  (dispatch tensors are ``[B, S, N, C]`` — linear in batch). Overflow
  tokens pass through the residual (standard Switch behavior), keeping
  per-expert work static-shaped.
- **Load-balancing aux loss** (Switch loss: ``n·Σ fᵢ·Pᵢ``) is exported via
  ``self.sow("losses", ...)``; the Trainer adds every sown loss to the
  task loss.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


class SwitchFFN(nn.Module):
    """Top-1 routed expert FFN (drop-in for a transformer MLP block).

    Input/output ``[batch, seq, embed]``; experts are two-layer GELU FFNs
    with hidden dim ``mlp_ratio * embed``.
    """

    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        n = self.num_experts
        # Batch rows are the dispatch groups (the Switch/Mesh-TF "group"
        # dim): capacity is per group, so dispatch/combine are
        # [B, S, N, C] — linear in batch, never quadratic in total tokens.
        capacity = max(1, int(self.capacity_factor * s / n))
        hidden = d * self.mlp_ratio

        # Router (f32 for a stable softmax regardless of compute dtype).
        router_logits = nn.Dense(
            n, dtype=jnp.float32, param_dtype=self.param_dtype, name="router"
        )(x.astype(jnp.float32))
        probs = nn.softmax(router_logits, axis=-1)            # (B, S, N)
        expert_index = jnp.argmax(probs, axis=-1)             # (B, S)
        expert_gate = jnp.max(probs, axis=-1)                 # (B, S)

        # Capacity-limited one-hot dispatch: position of each token within
        # its expert's queue (per group); tokens past capacity are dropped
        # (residual passthrough happens at the call site via x + moe(x)).
        raw_onehot = nn.one_hot(expert_index, n)              # (B, S, N)
        position = jnp.cumsum(raw_onehot, axis=1) * raw_onehot  # 1-based
        onehot = raw_onehot * (position <= capacity)
        pos_in_expert = (position - 1.0) * onehot             # 0-based, 0 where dropped
        # (B, S, N, C) one-hot over capacity slots.
        dispatch = onehot[..., None] * nn.one_hot(
            pos_in_expert.sum(axis=-1).astype(jnp.int32), capacity
        )[..., None, :]
        combine = dispatch * expert_gate[..., None, None]     # gate-weighted

        # Load-balancing loss BEFORE capacity drop (Switch eq. 4-6):
        # n * sum_i( fraction_of_tokens_i * mean_router_prob_i ).
        frac = jnp.mean(raw_onehot, axis=(0, 1))
        mean_prob = jnp.mean(probs, axis=(0, 1))
        aux = self.aux_loss_weight * n * jnp.sum(frac * mean_prob)
        self.sow("losses", "moe_aux_loss", aux)

        dispatch = dispatch.astype(self.dtype)
        combine = combine.astype(self.dtype)
        xc = x.astype(self.dtype)

        # Expert-major parameters: dim 0 shards over the `expert` mesh axis.
        # batch_axis=(0,): the expert dim must not count toward fan-in, or
        # every expert initializes sqrt(n) too small.
        he = nn.initializers.he_normal(batch_axis=(0,))
        w1 = self.param("w1", he, (n, d, hidden),
                        self.param_dtype).astype(self.dtype)
        b1 = self.param("b1", nn.initializers.zeros, (n, hidden),
                        self.param_dtype).astype(self.dtype)
        w2 = self.param("w2", he, (n, hidden, d),
                        self.param_dtype).astype(self.dtype)
        b2 = self.param("b2", nn.initializers.zeros, (n, d),
                        self.param_dtype).astype(self.dtype)

        # Dispatch -> expert FFN -> combine: all MXU einsums, static shapes.
        expert_in = jnp.einsum("bsnc,bsd->bncd", dispatch, xc)
        h = nn.gelu(jnp.einsum("bncd,ndh->bnch", expert_in, w1) + b1[:, None, :])
        expert_out = jnp.einsum("bnch,nhd->bncd", h, w2) + b2[:, None, :]
        return jnp.einsum("bsnc,bncd->bsd", combine, expert_out)
