"""GPipe pipeline parallelism over the ``stage`` mesh axis.

Beyond-parity capability (the reference has no pipeline parallelism —
SURVEY.md §2c). TPU-native formulation: instead of an RPC/stream scheduler
(the GPU-framework shape of PP), the whole pipeline is ONE compiled SPMD
program —

- stage parameters are stacked on a leading ``[n_stages, ...]`` dim and
  sharded over the ``stage`` mesh axis (one stage per mesh position);
- the batch is split into microbatches; a ``lax.scan`` over
  ``n_micro + n_stages - 1`` ticks runs every stage every tick (SPMD), and
  activations hop to the next stage via ``lax.ppermute`` — neighbor
  exchange on the ICI ring;
- stage 0 injects a fresh microbatch each tick, the last stage collects
  finished microbatches; the classic GPipe bubble is the
  ``(n_stages - 1) / (n_micro + n_stages - 1)`` idle fraction.

Because the schedule is ``scan`` + ``ppermute`` (both differentiable), the
backward pass IS the reverse pipeline — ``jax.grad`` derives it; no
hand-written 1F1B schedule, no framework scheduler thread.

Composes with data parallelism: the batch dim stays sharded over ``data``
inside the same ``shard_map``.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pddl_tpu.core.collectives import pcast_varying
from pddl_tpu.core.mesh import DATA_AXIS, STAGE_AXIS, shard_map

PyTree = Any


def gpipe_apply(
    stage_params: PyTree,
    x: jnp.ndarray,
    *,
    mesh: Mesh,
    stage_fn: Callable[[PyTree, jnp.ndarray], jnp.ndarray],
    n_microbatches: int,
    stage_axis: str = STAGE_AXIS,
    data_axis: str = DATA_AXIS,
    check_vma: bool = True,
    remat_stages: bool = False,
) -> jnp.ndarray:
    """Run ``x`` through the stage pipeline; returns same-shape activations.

    Args:
      stage_params: pytree whose leaves have leading dim ``n_stages``
        (sharded over ``stage_axis`` by the strategy).
      x: ``[batch, ...]`` activations (sharded over ``data_axis``).
      stage_fn: pure ``(params_slice, microbatch) -> microbatch`` for ONE
        stage (e.g. a flax ``module.apply`` closure). Applied under vmap-
        free SPMD — one call per device per tick.
      n_microbatches: microbatch count M; ``batch % M == 0``. Larger M
        shrinks the pipeline bubble (``(S-1)/(M+S-1)``) but each microbatch
        must stay big enough to keep the MXU busy.
      remat_stages: rematerialize each stage call in the backward. The
        AD-derived backward saves one stage-internal activation set per
        tick: ``M + S - 1`` ticks of ``B/M``-row microbatches, i.e.
        ``temp ≈ c·B·(M+S-1)/M`` at fixed global batch (measured law —
        larger M SHRINKS the envelope toward the ``c·B`` floor while
        also shrinking the bubble). What caps model size is the floor's
        constant ``c`` — every block-internal activation of the global
        batch — and remat cuts it ~5-10x by keeping only tick-boundary
        microbatches and recomputing stage internals in the backward
        (measured: benchmarks/gpipe_memory_bench.py,
        docs/ARCHITECTURE.md §7d; exactness: tests/test_pipeline.py).
    """
    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)
    n_stages = mesh.shape[stage_axis]
    batch = x.shape[0]
    dp = mesh.shape[data_axis]
    if batch % dp:
        raise ValueError(
            f"batch {batch} not divisible by the {data_axis} axis size {dp}")
    if (batch // dp) % n_microbatches:
        raise ValueError(
            f"per-data-shard batch {batch // dp} not divisible by "
            f"{n_microbatches} microbatches"
        )
    if n_stages == 1:  # degenerate: no pipeline, just apply the one stage
        return stage_fn(jax.tree.map(lambda p: p[0], stage_params), x)

    def pipelined(params, xs):
        # params leaves: [1, ...] (this device's stage); xs: local batch shard.
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        sid = lax.axis_index(stage_axis)
        last = n_stages - 1
        xs_mb = xs.reshape((n_microbatches, -1) + xs.shape[1:])  # (M, mb/dp, ...)

        def probe(h):
            return stage_fn(params, h)

        zero = jnp.zeros_like(xs_mb[0])
        out_shape = jax.eval_shape(probe, zero)
        outs0 = jnp.zeros((n_microbatches,) + out_shape.shape, out_shape.dtype)
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            buf, outs = carry
            # Stage 0 injects microbatch t (clamped once the feed runs dry).
            inj = lax.dynamic_index_in_dim(
                xs_mb, jnp.minimum(t, n_microbatches - 1), 0, keepdims=False
            ).astype(buf.dtype)
            buf = jnp.where(sid == 0, inj, buf)
            y = stage_fn(params, buf)
            # Last stage collects microbatch t-(S-1) once it exists.
            idx = t - last
            updated = lax.dynamic_update_index_in_dim(
                outs, y.astype(outs.dtype), jnp.maximum(idx, 0), 0
            )
            outs = jnp.where((sid == last) & (idx >= 0), updated, outs)
            # Activations hop one stage forward around the ICI ring.
            buf = lax.ppermute(y, stage_axis, perm)
            return (buf, outs), None

        # The carries are logically per-device (stage-varying) even though
        # their initial values are constants — cast them to varying so the
        # scan carry type is stable (see also ring_attention).
        buf_init = pcast_varying(zero, (stage_axis,))
        outs_init = pcast_varying(outs0, (data_axis, stage_axis))
        (_, outs), _ = lax.scan(
            tick, (buf_init, outs_init), jnp.arange(n_microbatches + n_stages - 1)
        )
        # Only the last stage holds real outputs; psum broadcasts them to
        # every stage position (making the result stage-invariant).
        outs = lax.psum(jnp.where(sid == last, outs, 0.0), stage_axis)
        return outs.reshape((-1,) + outs.shape[2:])

    param_specs = jax.tree.map(
        lambda p: P(stage_axis, *([None] * (p.ndim - 1))), stage_params
    )
    return shard_map(
        pipelined,
        mesh=mesh,
        in_specs=(param_specs, P(data_axis, *([None] * (x.ndim - 1)))),
        out_specs=P(data_axis, *([None] * (x.ndim - 1))),
        # check_vma=False only for stage_fns whose pallas interpret mode
        # can't declare varying axes (CPU test path); Mosaic on TPU can.
        check_vma=check_vma,
    )(stage_params, x)
