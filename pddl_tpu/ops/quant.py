"""Weight-only int8 storage for serving (w8a16: int8 weights, bf16 math).

Why: single-stream decode reads every matmul parameter once per tick
(ARCHITECTURE.md §7e — the layer GEMV chain runs near its weight-read
bound), so the B1 weight-read floor is set by parameter BYTES, not
FLOPs. Storing matmul kernels as int8 with per-output-channel scales
halves those bytes against bf16; activations and arithmetic stay in the
model's compute dtype, so the only numeric change is the weight
rounding (measured, not assumed — `benchmarks/specdecode_bench.py
--int8` reports the val-loss delta of the quantized model on held-out
text alongside the throughput).

Mechanics — deliberately framework-light:

- :func:`quantize_int8` walks a params tree and replaces each eligible
  kernel ``w`` (ndim >= 2, size >= ``min_elems``, not an embedding) with
  a dict ``{"qvalue": int8, "scale": f32, "like": dtype-carrier}``:
  symmetric per-output-channel quantization with the scale reduced over
  the CONTRACTION axis (axis 0 — every Dense/DenseGeneral kernel in the
  model families contracts its leading axis), so each output channel
  spans the full int8 range independently.
- :func:`dequantize` maps the tree back to dense weights
  (``q * scale`` in f32, cast to the original dtype recorded by the
  zero-length ``like`` leaf). It is the ``param_transform`` hook of the
  decode programs (:func:`pddl_tpu.models.gpt.generate`,
  :func:`~pddl_tpu.models.speculative.generate_speculative`, and the
  online engine :class:`pddl_tpu.serve.ServeEngine` — the hook applies
  inside the engine's prefill and fused tick, so int8 serving composes
  with continuous batching unchanged): applied INSIDE the jitted
  program, every tick, so the int8 tensors are what lives in (and
  streams from) HBM — XLA fuses the convert+scale into the consuming
  matmul's operand read rather than materializing a dense copy.
- Embeddings are skipped by name (``embed`` in the path): decode
  GATHERS one row per token — quantizing a table that contributes no
  streaming traffic buys nothing and the axis-0 scale rule would be
  wrong for a ``[vocab, features]`` gather anyway. Norm scales/biases
  fall under ``min_elems``.

Reference stake: the reference's endpoint is ``model.save`` then serve
(`/root/reference/imagenet-resnet50.py:72`); this is the serving
memory/bandwidth story for that artifact on TPU.
"""

from __future__ import annotations

from typing import Dict

import jax.numpy as jnp

__all__ = ["quantize_int8", "dequantize", "quantized_bytes"]

_QKEYS = frozenset(("qvalue", "scale", "like"))


def _is_qleaf(node) -> bool:
    return isinstance(node, dict) and set(node.keys()) == _QKEYS


def quantize_int8(params, *, min_elems: int = 65536):
    """Params tree → tree with eligible kernels stored as int8.

    Eligible: array leaves with ``ndim >= 2`` and ``size >= min_elems``
    whose path does not mention an embedding. The default ``min_elems``
    keeps every norm/bias (and tiny test-model kernels) in their
    original dtype — quantizing them saves nothing and costs accuracy.
    """
    def walk(node, path):
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        w = jnp.asarray(node)
        name = "/".join(str(p) for p in path).lower()
        if w.ndim < 2 or w.size < min_elems or "embed" in name:
            return w
        # Symmetric per-output-channel: reduce |w| over the contraction
        # axis (0). amax==0 channels (a dead column) get scale 1 to keep
        # the division finite; their quantized values are all zero.
        amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0)
        q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                     -127, 127).astype(jnp.int8)
        return {"qvalue": q, "scale": scale,
                "like": jnp.zeros((0,), w.dtype)}

    return walk(params, ())


def dequantize(qparams):
    """Inverse of :func:`quantize_int8`; identity on untouched leaves.

    Safe to call inside jit (this is the decode programs'
    ``param_transform``): the dequant is traced per use site, and the
    convert+scale fuses into the consuming matmul's operand read.
    """
    def walk(node):
        if _is_qleaf(node):
            w = node["qvalue"].astype(jnp.float32) * node["scale"]
            return w.astype(node["like"].dtype)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(qparams)


def quantized_bytes(tree) -> Dict[str, int]:
    """{"bytes": total stored bytes, "quantized_leaves": n} — the memory
    claim as a measurement, not arithmetic."""
    total, nq = 0, 0

    def walk(node):
        nonlocal total, nq
        if _is_qleaf(node):
            nq += 1
            total += (node["qvalue"].size * node["qvalue"].dtype.itemsize
                      + node["scale"].size * node["scale"].dtype.itemsize)
            return
        if isinstance(node, dict):
            for v in node.values():
                walk(v)
            return
        arr = jnp.asarray(node)
        total += arr.size * arr.dtype.itemsize

    walk(tree)
    return {"bytes": int(total), "quantized_leaves": int(nq)}
