"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

First-class long-context support (absent from the reference, which is a
fixed-224x224 CNN repo — SURVEY.md §5 "Long-context": this is a designed-in
capability of the TPU build, not parity). The sequence dimension is sharded
across devices; each device holds its local Q block permanently and the
K/V blocks *rotate around the ICI ring* via ``lax.ppermute`` — after
``seq``-axis-size steps every Q has attended to every K/V without any
device ever materializing the full sequence (memory O(S/n), comms
bandwidth-optimal on the torus).

Math: blockwise online softmax (same running max/denominator update as the
flash kernel in :mod:`pddl_tpu.ops.attention`) accumulated across ring
steps — numerically exact, not an approximation. Causal masking uses
*global* positions reconstructed from each shard's ring offset, so shards
that lie entirely in the future contribute nothing (their p == 0).

Usage (inside ``jax.shard_map`` over a mesh with a ``seq`` axis)::

    out = ring_attention(q, k, v, axis_name="seq", causal=True)

or at the array level via :func:`sequence_parallel_attention`, which wraps
the shard_map.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from pddl_tpu.core.collectives import axis_size, pcast_varying
from pddl_tpu.core.mesh import shard_map
from pddl_tpu.ops.attention import NEG_INF


def _band_hops(n: int, s_local: int, window: Optional[int]) -> int:
    """Ring rotations that can carry in-band keys (incl. the diagonal).

    The sliding-window band is translation-invariant along the ring, so
    rotation ``i`` contributes iff the shard ``i`` hops back overlaps
    some query's ``(q-window, q]`` — a STATIC property of ``i``:
    ``i·s_local <= window + s_local - 2``. Rotations (and their
    ``ppermute`` hops) beyond that are skipped entirely: compute and ICI
    traffic scale O(window), not O(S)."""
    if window is None:
        return n
    return min(n, (window + s_local - 2) // s_local + 1)


def ring_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    axis_name: str = "seq", *, causal: bool = False,
    scale: Optional[float] = None, window: Optional[int] = None,
) -> jnp.ndarray:
    """Per-shard ring attention; call inside ``shard_map``.

    Args are local shards ``[batch, heads, seq_local, head_dim]``; returns
    the local output shard of exact global attention. K/V may be grouped
    (``H_kv < H``, GQA): the *unexpanded* kv-head-sized shards rotate
    around the ring, so per-hop ``ppermute`` ICI traffic is
    ``H/H_kv``-times smaller than rotating expanded K/V would be.
    ``window`` (requires ``causal``): Mistral-style sliding-window
    attention — the loop stops after :func:`_band_hops` rotations, so a
    long-context SWA model pays O(window) ring compute and comms.
    """
    from pddl_tpu.ops.attention import _gqa_rep

    b, h, s_local, d = q.shape
    hkv = k.shape[1]
    # Shape-static, so the check is free — direct shard_map callers get
    # the descriptive error instead of an opaque reshape failure.
    rep = _gqa_rep(q, k)
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale_v = (1.0 / math.sqrt(d)) if scale is None else scale
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    hops = _band_hops(n, s_local, window)

    # Grouped layout [B, H_kv, rep, S, D] for q and the accumulators; the
    # per-rotation einsums contract each kv head against its whole query
    # group in one pass. rep == 1 (MHA) makes the group axis size-1.
    qf = (q.astype(jnp.float32) * scale_v).reshape(b, hkv, rep, s_local, d)
    q_pos = my * s_local + jnp.arange(s_local)  # global positions of local Q

    def step(i, carry):
        m, l, acc, kc, vc = carry
        # kc/vc originated on shard (my - i) mod n after i rotations.
        src = (my - i) % n
        s = jnp.einsum("bgrqd,bgkd->bgrqk", qf, kc.astype(jnp.float32))
        if causal:
            k_pos = src * s_local + jnp.arange(s_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            if window is not None:
                mask &= k_pos[None, :] > q_pos[:, None] - window
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bgrqk,bgkd->bgrqd", p, vc.astype(jnp.float32))
        # Rotate K/V one hop around the ring (neighbor exchange on ICI).
        perm = [(j, (j + 1) % n) for j in range(n)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return m_new, l, acc, kc, vc

    # pcast-to-varying: the accumulators are logically per-shard
    # (device-varying along the ring axis) even though their initial values
    # are constants.
    def _vary(x):
        return pcast_varying(x, axis_name)

    m0 = _vary(jnp.full((b, hkv, rep, s_local, 1), NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((b, hkv, rep, s_local, 1), jnp.float32))
    acc0 = _vary(jnp.zeros((b, hkv, rep, s_local, d), jnp.float32))
    m, l, acc, _, _ = lax.fori_loop(0, hops, step, (m0, l0, acc0, k, v))
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(b, h, s_local, d).astype(q.dtype)


def ring_attention_flash(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    axis_name: str = "seq", *, causal: bool = False,
    scale: Optional[float] = None, window: Optional[int] = None,
) -> jnp.ndarray:
    """Ring attention whose per-rotation compute is the FLASH kernel.

    The XLA path (:func:`ring_attention`) materializes an
    ``[s_local, s_local]`` score block per rotation; here each rotation
    runs :func:`~pddl_tpu.ops.attention.flash_attention_lse` on the
    local Q against the visiting K/V shard (scores stay in VMEM) and
    the normalized partials merge in logsumexp space:
    ``o = Σᵢ oᵢ·exp(lseᵢ − m) / Σᵢ exp(lseᵢ − m)``. Under ``causal``,
    the diagonal rotation (``src == my``) runs the causal kernel,
    earlier shards (``src < my``) run unmasked, later shards contribute
    nothing (lse = −inf) — block-level causality over the ring, exact
    row-level causality inside the kernel.

    ``window`` (requires ``causal``): the rotation loop UNROLLS to the
    :func:`_band_hops` in-band rotations, each running the kernel with a
    static ``k_offset = -i·s_local`` so its causal+window mask sits at
    the visiting shard's true positions; out-of-band rotations (and
    their ppermute hops) never execute.
    """
    from pddl_tpu.ops.attention import flash_attention_lse

    b, h, s_local, d = q.shape
    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    scale_v = (1.0 / math.sqrt(d)) if scale is None else scale
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    perm = [(j, (j + 1) % n) for j in range(n)]

    def merge(m, s, acc, o_i, lse_i):
        m_new = jnp.maximum(m, lse_i)
        alpha = jnp.exp(m - m_new)
        w = jnp.exp(lse_i - m_new)
        s = s * alpha + w
        acc = acc * alpha[..., None] + o_i.astype(jnp.float32) * w[..., None]
        return m_new, s, acc

    def step(i, carry):
        m, s, acc, kc, vc = carry
        # The visiting shard originated on src = my - i (mod n); for
        # i >= 1 it is never the diagonal: strictly past iff my >= i.
        o_i, lse_i = flash_attention_lse(q, kc, vc, causal=False,
                                         scale=scale_v)
        if causal:
            keep = (my - i) % n < my
            # Future shards contribute nothing: -inf lse makes their merge
            # weight w == 0, which also zeroes o_i. The kernel still runs
            # on those devices — the per-rotation ppermute barrier means
            # the busiest device sets each rotation's wall-clock, so the
            # wasted flops cost no time. Masking instead of lax.cond also
            # removes one of the two check_vma blockers; the kernel's own
            # internals remain the other (see sequence_parallel_attention).
            lse_i = jnp.where(keep, lse_i, NEG_INF)
        m, s, acc = merge(m, s, acc, o_i, lse_i)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return m, s, acc, kc, vc

    def _vary(x):
        return pcast_varying(x, axis_name)

    # Rotation 0 always sees the device's own K/V shard (src == my). Under
    # causal that is the diagonal block, which needs row-level masking
    # INSIDE the kernel — selecting the causal kernel statically here
    # removes the data-dependent branch entirely.
    o0, lse0 = flash_attention_lse(q, k, v, causal=causal, scale=scale_v,
                                   window=window)
    m0 = _vary(jnp.full((b, h, s_local), NEG_INF, jnp.float32))
    s0 = _vary(jnp.zeros((b, h, s_local), jnp.float32))
    acc0 = _vary(jnp.zeros((b, h, s_local, d), jnp.float32))
    m, s, acc = merge(m0, s0, acc0, o0, lse0)

    if window is not None:
        # Unrolled in-band rotations: i is a Python int, so the kernel's
        # k_offset (and the band-skip predicates inside it) are static.
        hops = _band_hops(n, s_local, window)
        kc, vc = k, v
        for i in range(1, hops):
            kc = lax.ppermute(kc, axis_name, perm)
            vc = lax.ppermute(vc, axis_name, perm)
            o_i, lse_i = flash_attention_lse(
                q, kc, vc, causal=True, window=window,
                k_offset=-i * s_local, scale=scale_v)
            # Wrapped sources are future shards: zero their weight.
            lse_i = jnp.where((my - i) % n < my, lse_i, NEG_INF)
            m, s, acc = merge(m, s, acc, o_i, lse_i)
        return (acc / jnp.maximum(s, 1e-30)[..., None]).astype(q.dtype)

    kc = lax.ppermute(k, axis_name, perm)
    vc = lax.ppermute(v, axis_name, perm)
    m, s, acc, _, _ = lax.fori_loop(1, n, step, (m, s, acc, kc, vc))
    return (acc / jnp.maximum(s, 1e-30)[..., None]).astype(q.dtype)


def sequence_parallel_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
    mesh: Mesh, *, axis_name: str = "seq", causal: bool = False,
    scale: Optional[float] = None, use_flash: bool = False,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Array-level wrapper: global ``[B, H, S, D]`` inputs sharded on S.

    Installs the shard_map over ``mesh``'s sequence axis; XLA lowers the
    per-step ``ppermute`` to ICI neighbor exchange. ``use_flash`` routes
    each rotation through the Pallas kernel (:func:`ring_attention_flash`)
    instead of the XLA einsum path — same math (in f32 bit-comparable;
    bf16 inputs see one extra per-rotation rounding where the XLA path
    keeps a single f32 accumulator), with O(block) instead of
    O(s_local²) score memory per rotation.

    ``window`` (requires ``causal``): sliding-window attention composed
    with the ring — rotations whose shard lies wholly outside the band
    are skipped (no kernel launch, no ppermute hop), so long-context SWA
    costs O(window) per device instead of O(S).
    """
    from pddl_tpu.ops.attention import _gqa_rep, _normalize_window

    _gqa_rep(q, k)  # validate head grouping before entering the shard_map
    window = _normalize_window(window, causal, k.shape[-2])
    spec = P(None, None, axis_name, None)
    inner = ring_attention_flash if use_flash else ring_attention
    fn = functools.partial(inner, axis_name=axis_name,
                           causal=causal, scale=scale, window=window)
    # check_vma: the flash ring is branch-free (the former lax.cond around
    # the pallas call is gone), but the varying-axes checker still cannot
    # see through the pallas kernel itself: its internal dynamic_slices mix
    # varying ref data with invariant grid indices, and jax's own error
    # says to "pass the check_vma=False argument" until that propagation
    # exists. tests/test_attention.py::test_flash_ring_check_vma_limitation
    # pins the exact failure so a jax upgrade that fixes it flips this
    # flag. The XLA ring path runs fully checked.
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=not use_flash,
    )(q, k, v)
