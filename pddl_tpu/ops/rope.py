"""Rotary position embeddings (RoPE) for the Llama family.

The reference repo is a fixed-resolution CNN codebase
(`/root/reference/imagenet-resnet50.py:52`) with no positional encoding of
any kind — this op exists for the TPU build's long-context transformer
families, where RoPE is what modern decoder LMs (Llama/Mistral/Qwen) use
instead of GPT-2's learned position table.

Convention: the half-split ("rotate_half") layout used by HF
``transformers``' Llama implementation — the head dim is split into two
halves ``[x1, x2]`` and rotated as ``[x1·cos − x2·sin, x2·cos + x1·sin]``
with the frequency vector CONCATENATED twice (not interleaved). Matching
HF exactly is what makes ``ckpt/hf_import.load_hf_llama`` checkpoints
reproduce logits bit-for-bit-ish (f32 tolerance) — see
``tests/test_llama.py``.

Angles are computed in f32 regardless of the activation dtype (bf16
angles visibly corrupt long-range positions), then the rotation is
applied in the input's dtype.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(positions: jnp.ndarray, head_dim: int,
                 *, theta: float = 10000.0) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``(cos, sin)`` tables for integer ``positions`` (any shape).

    Returns f32 arrays of shape ``positions.shape + (head_dim,)`` with the
    HF layout: frequencies for the first half, duplicated for the second.
    """
    if head_dim % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {head_dim}")
    inv_freq = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., D/2]
    emb = jnp.concatenate([angles, angles], axis=-1)              # [..., D]
    return jnp.cos(emb), jnp.sin(emb)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """Rotate ``x [..., S, D]`` by per-position ``(cos, sin) [S, D]`` tables.

    ``cos``/``sin`` broadcast against ``x``'s leading dims (pass
    ``[S, D]`` tables for ``[B, H, S, D]`` activations). Computation
    happens in f32; the result is cast back to ``x.dtype``.
    """
    xf = x.astype(jnp.float32)
    out = xf * cos + _rotate_half(xf) * sin
    return out.astype(x.dtype)


def apply_rope_qk(q: jnp.ndarray, k: jnp.ndarray, positions: jnp.ndarray,
                  *, theta: float = 10000.0) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Apply RoPE to query/key ``[B, H, S, D]`` at integer ``positions``.

    ``positions`` is ``[S]`` (shared across the batch — training and
    single-request decode) or ``[B, S]`` (per-row positions — the
    serving engine's slot model, where each batch row is a request at
    its own depth; the tables gain a broadcast head axis).

    q and k may carry different head counts (grouped-query attention);
    the same tables broadcast over both.
    """
    cos, sin = rope_cos_sin(positions, q.shape[-1], theta=theta)
    if positions.ndim == 2:  # [B, S, D] → [B, 1, S, D] over heads
        cos, sin = cos[:, None], sin[:, None]
    return apply_rope(q, cos, sin), apply_rope(k, cos, sin)
