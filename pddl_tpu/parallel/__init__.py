"""Distribution strategies: the reference's four modes over one SPMD core.

Reference surface (SURVEY.md §1-§2):

- none / single device (``/root/reference/imagenet-resnet50.py``)
- ``tf.distribute.MirroredStrategy`` (``imagenet-resnet50-mirror.py:21``)
- ``tf.distribute.MultiWorkerMirroredStrategy`` + Slurm + NCCL
  (``imagenet-resnet50-multiworkers.py:16-25``)
- ``ParameterServerStrategy`` + ``MinSizePartitioner`` + gRPC cluster
  (``imagenet-resnet50-ps.py:31-84``)
- Horovod (``imagenet-resnet50-hvd.py``) — lives in
  :mod:`pddl_tpu.compat.hvd` as an API shim over the same core.

On TPU all of them lower to mesh + NamedSharding + XLA collectives; a
Strategy only decides (a) which devices form the mesh, (b) how state is
sharded, (c) batch-size arithmetic, (d) who logs/saves.
"""

from pddl_tpu.parallel.base import Strategy, get_strategy
from pddl_tpu.parallel.single import SingleDeviceStrategy
from pddl_tpu.parallel.mirrored import MirroredStrategy
from pddl_tpu.parallel.multiworker import MultiWorkerMirroredStrategy
from pddl_tpu.parallel.ps import ParameterServerStrategy
from pddl_tpu.parallel.tensor_parallel import (
    ExpertParallelStrategy,
    TensorParallelStrategy,
)
from pddl_tpu.parallel.pipeline import PipelineStrategy

__all__ = [
    "Strategy",
    "get_strategy",
    "SingleDeviceStrategy",
    "MirroredStrategy",
    "MultiWorkerMirroredStrategy",
    "ParameterServerStrategy",
    "TensorParallelStrategy",
    "ExpertParallelStrategy",
    "PipelineStrategy",
]
