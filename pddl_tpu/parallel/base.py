"""Strategy protocol: what varies between the reference's distribution modes.

A Strategy owns the mesh and the sharding rules; the Trainer
(:mod:`pddl_tpu.train.loop`) is strategy-agnostic — exactly the factoring
the reference never did (its ~60-line skeleton is duplicated 8x with only
the strategy block changing; SURVEY.md §0).
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from pddl_tpu.core import dist
from pddl_tpu.core.mesh import DATA_AXIS, build_mesh, MeshConfig, mesh_num_replicas

PyTree = Any


class Strategy:
    """Base strategy: replicated state, data-sharded batches.

    Subclasses override device selection (``mesh_config``), state sharding
    (``state_sharding``), and bootstrap (``setup``).
    """

    name = "base"

    def __init__(self, mesh_config: Optional[MeshConfig] = None):
        self._mesh_config = mesh_config or MeshConfig()
        self._mesh: Optional[Mesh] = None

    # -- bootstrap ---------------------------------------------------------
    def setup(self) -> Mesh:
        """Build (once) and return the mesh. Subclasses may bootstrap
        multi-host first (the ``strategy.scope()`` moment)."""
        if self._mesh is None:
            self._mesh = build_mesh(self._mesh_config)
        return self._mesh

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            self.setup()
        return self._mesh

    # -- replica arithmetic ------------------------------------------------
    @property
    def num_replicas_in_sync(self) -> int:
        """TF's ``strategy.num_replicas_in_sync``
        (``imagenet-resnet50-mirror.py:54``)."""
        return mesh_num_replicas(self.mesh, DATA_AXIS)

    def scale_batch_size(self, per_replica_batch: int) -> int:
        """Global batch = per-replica x replicas — the reference's
        ``32 * strategy.num_replicas_in_sync`` arithmetic
        (``imagenet-resnet50-mirror.py:54``,
        ``imagenet-resnet50-multiworkers.py:70``)."""
        return per_replica_batch * self.num_replicas_in_sync

    def scale_learning_rate(self, base_lr: float) -> float:
        """Linear LR scaling rule: ``base_lr * replicas`` (Horovod's
        ``0.1 * size``, ``imagenet-resnet50-hvd.py:99``).

        Never applied automatically — the Trainer uses the LR it is given.
        Calling this is the opt-in: the hvd compat shim and the hvd config
        preset do; presets mirroring the other reference scripts must not
        (those scripts never scale LR)."""
        return base_lr * self.num_replicas_in_sync

    # -- sharding rules ----------------------------------------------------
    def batch_sharding(self) -> NamedSharding:
        from pddl_tpu.core.sharding import batch_sharding

        return batch_sharding(self.mesh, DATA_AXIS)

    def state_sharding(self, state: PyTree) -> PyTree:
        """Sharding for the TrainState: replicated by default (mirrored
        variables), overridden by the PS strategy."""
        repl = NamedSharding(self.mesh, PartitionSpec())
        return jax.tree.map(lambda _: repl, state)

    # -- data distribution -------------------------------------------------
    @property
    def data_process_count(self) -> int:
        """Processes contributing shards to this strategy's mesh.

        1 for a local-only mesh (mirrored on one host, even inside a
        multi-host job); ``jax.process_count()`` for a global mesh.
        """
        return len({d.process_index for d in self.mesh.devices.flat})

    def distribute_batch(self, batch: PyTree) -> PyTree:
        """Host-local numpy batch -> globally-sharded jax.Array.

        Each participating process contributes its local shard; together
        they form the global batch (the auto-shard DATA policy analogue,
        ``imagenet-resnet50-multiworkers.py:66-69``).
        """
        sharding = self.batch_sharding()
        n_procs = self.data_process_count
        leaves = jax.tree.leaves(batch)
        if leaves:
            local = np.asarray(leaves[0]).shape[0]
            from pddl_tpu.core.mesh import validate_divisible

            validate_divisible(local * n_procs, self.mesh)

        def _to_global(x):
            x = np.asarray(x)
            if n_procs == 1:
                return jax.device_put(x, sharding)
            return jax.make_array_from_process_local_data(sharding, x)

        return jax.tree.map(_to_global, batch)

    def distribute_dataset(self, it: Iterator[PyTree]) -> Iterator[PyTree]:
        for batch in it:
            yield self.distribute_batch(batch)

    # -- process topology --------------------------------------------------
    @property
    def process_index(self) -> int:
        return dist.process_index()

    @property
    def is_coordinator(self) -> bool:
        """Who logs and saves (rank-0 gating,
        ``imagenet-resnet50-hvd.py:28,96,117,125``)."""
        return dist.is_coordinator()

    def __repr__(self) -> str:
        return f"{type(self).__name__}(replicas={self.num_replicas_in_sync})"


_STRATEGIES: dict[str, type] = {}


def register_strategy(name: str):
    def deco(cls):
        _STRATEGIES[name] = cls
        cls.name = name
        return cls

    return deco


def get_strategy(name: str, **kwargs) -> Strategy:
    """Strategy by config string (``single``/``mirrored``/``multiworker``/``ps``)."""
    try:
        cls = _STRATEGIES[name]
    except KeyError:
        raise ValueError(f"unknown strategy {name!r}; known: {sorted(_STRATEGIES)}") from None
    return cls(**kwargs)
