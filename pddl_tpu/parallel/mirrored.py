"""Mirrored strategy: single-host sync data parallelism.

Parity with ``tf.distribute.MirroredStrategy``
(``/root/reference/imagenet-resnet50-mirror.py:21``): variables replicated
on every local device, per-step gradient all-reduce, global batch scaled by
replica count (``:54``). The reference's NCCL ring becomes an XLA all-reduce
over ICI — not called explicitly: with params replicated and the batch
sharded over ``data``, XLA's SPMD partitioner inserts the gradient
all-reduce during compilation (SURVEY.md §2b C11).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

from pddl_tpu.core.mesh import MeshConfig, build_mesh
from pddl_tpu.parallel.base import Strategy, register_strategy


@register_strategy("mirrored")
class MirroredStrategy(Strategy):
    """Data parallelism over this host's local devices."""

    def __init__(self, devices: Optional[Sequence[jax.Device]] = None):
        super().__init__(MeshConfig(local_only=True))
        self._devices = devices

    def setup(self):
        if self._mesh is None:
            devs = list(self._devices) if self._devices else jax.local_devices()
            self._mesh = build_mesh(MeshConfig(data=len(devs)), devices=devs)
        return self._mesh
