"""Multi-worker mirrored strategy: multi-host sync data parallelism.

Parity with ``tf.distribute.MultiWorkerMirroredStrategy`` + Slurm resolver +
NCCL (``/root/reference/imagenet-resnet50-multiworkers.py:16-25``). The whole
resolver/NCCL-options block collapses into :func:`pddl_tpu.core.dist.initialize`
(Slurm/TPU-metadata discovery) plus one global mesh; cross-host gradient
all-reduce is compiled by XLA over ICI within a slice and DCN across slices
(SURVEY.md §3.3).

Dataset sharding follows the DATA auto-shard policy the reference sets
(``imagenet-resnet50-multiworkers.py:66-69``): each process feeds its local
part of the global batch; ``Strategy.distribute_batch`` assembles the global
array via ``jax.make_array_from_process_local_data``.
"""

from __future__ import annotations

from typing import Optional

from pddl_tpu.core import dist
from pddl_tpu.core.mesh import MeshConfig, build_mesh
from pddl_tpu.parallel.base import Strategy, register_strategy


@register_strategy("multiworker")
class MultiWorkerMirroredStrategy(Strategy):
    """Data parallelism over every device of every participating host."""

    def __init__(self, coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 hybrid: bool = False):
        super().__init__(MeshConfig())
        self._bootstrap = (coordinator_address, num_processes, process_id)
        self._hybrid = hybrid
        self.cluster: Optional[dist.ClusterSpec] = None

    def setup(self):
        if self._mesh is None:
            self.cluster = dist.initialize(*self._bootstrap)
            if self._hybrid:
                # Multi-slice job: slice-major data axis so the gradient
                # all-reduce is hierarchical (ICI within a slice, one DCN
                # hop between slices) — core/mesh.py build_hybrid_mesh.
                from pddl_tpu.core.mesh import build_hybrid_mesh

                self._mesh = build_hybrid_mesh(MeshConfig())
            else:
                self._mesh = build_mesh(MeshConfig())
        return self._mesh

    @property
    def num_workers(self) -> int:
        """Worker count as the reference derives from ``SLURM_NTASKS``
        (``imagenet-resnet50-multiworkers.py:29``)."""
        return dist.process_count()
