"""Multi-worker mirrored strategy: multi-host sync data parallelism.

Parity with ``tf.distribute.MultiWorkerMirroredStrategy`` + Slurm resolver +
NCCL (``/root/reference/imagenet-resnet50-multiworkers.py:16-25``). The whole
resolver/NCCL-options block collapses into :func:`pddl_tpu.core.dist.initialize`
(Slurm/TPU-metadata discovery) plus one global mesh; cross-host gradient
all-reduce is compiled by XLA over ICI within a slice and DCN across slices
(SURVEY.md §3.3).

Dataset sharding follows the DATA auto-shard policy the reference sets
(``imagenet-resnet50-multiworkers.py:66-69``): each process feeds its local
part of the global batch; ``Strategy.distribute_batch`` assembles the global
array via ``jax.make_array_from_process_local_data``.

Failure detection (the capability the reference waves at with
``GRPC_FAIL_FAST`` and a Horovod re-broadcast comment, SURVEY.md §5):
under SPMD a lost worker does not produce a tidy error — the surviving
processes HANG in the next collective. :class:`HeartbeatMonitor` turns
that hang into a detection: every process beats a per-worker file on the
shared checkpoint filesystem at batch boundaries (atomic replace, no
coordination), and every process checks the others' beat ages on a
coarser cadence. A stale beat raises :class:`WorkerLost` /
flips the shared RESTART marker, so every survivor exits its step loop
at a batch boundary instead of hanging in the dead collective — the
job supervisor then relaunches at the new world size and
``Trainer.fit(resume=...)`` restores the shared checkpoint onto the
smaller mesh (the elastic-restore path, ``tests/test_elastic_restore.py``).
:class:`HeartbeatCallback` packages the beat/check/stop cycle as a
Trainer callback.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional

from pddl_tpu.core import dist
from pddl_tpu.core.mesh import MeshConfig, build_mesh
from pddl_tpu.parallel.base import Strategy, register_strategy
from pddl_tpu.train.callbacks import Callback

log = logging.getLogger(__name__)


@register_strategy("multiworker")
class MultiWorkerMirroredStrategy(Strategy):
    """Data parallelism over every device of every participating host."""

    def __init__(self, coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 hybrid: bool = False):
        super().__init__(MeshConfig())
        self._bootstrap = (coordinator_address, num_processes, process_id)
        self._hybrid = hybrid
        self.cluster: Optional[dist.ClusterSpec] = None

    def setup(self):
        if self._mesh is None:
            self.cluster = dist.initialize(*self._bootstrap)
            if self._hybrid:
                # Multi-slice job: slice-major data axis so the gradient
                # all-reduce is hierarchical (ICI within a slice, one DCN
                # hop between slices) — core/mesh.py build_hybrid_mesh.
                from pddl_tpu.core.mesh import build_hybrid_mesh

                self._mesh = build_hybrid_mesh(MeshConfig())
            else:
                self._mesh = build_mesh(MeshConfig())
        return self._mesh

    @property
    def num_workers(self) -> int:
        """Worker count as the reference derives from ``SLURM_NTASKS``
        (``imagenet-resnet50-multiworkers.py:29``)."""
        return dist.process_count()


# ---------------------------------------------------------------------------
# Failure detection: shared-filesystem heartbeats + coordinated restart.


class WorkerLost(RuntimeError):
    """One or more workers stopped heartbeating — the collective they
    were part of will never complete. Carries the lost process ids."""

    def __init__(self, lost, timeout_s: float):
        self.lost = sorted(lost)
        super().__init__(
            f"worker(s) {self.lost} missed the heartbeat deadline "
            f"({timeout_s:.1f}s) — coordinate a restart at the new "
            "world size and resume from the shared checkpoint")


class HeartbeatMonitor:
    """Worker liveness over a shared directory — no extra network.

    Each process atomically replaces ``hb_<pid>`` with the current
    wall-clock time (`beat`); any process can ask who has gone quiet
    (`failed` / `check`). The directory rides the checkpoint
    filesystem (GCS/NFS — already required for multi-host saves), so
    detection needs no side channel that could itself be partitioned
    away from the data path.

    Coordinated restart: `request_restart` drops one RESTART marker
    every worker polls (`restart_requested`) at batch boundaries — the
    survivors exit their step loops cleanly instead of hanging in the
    dead collective, and the relaunched job clears the marker
    (`clear_restart`) before resuming from the shared checkpoint.

    ``clock`` is injectable (tests drive fake time); it must be a
    WALL clock shared across hosts (``time.time``), not a per-process
    monotonic clock.
    """

    def __init__(self, directory: str, process_id: Optional[int] = None,
                 num_processes: Optional[int] = None,
                 timeout_s: float = 60.0, clock=time.time):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.process_id = (process_id if process_id is not None
                           else dist.process_index())
        self.num_processes = (num_processes if num_processes is not None
                              else dist.process_count())
        self.timeout_s = float(timeout_s)
        self._clock = clock
        # Never-beat grace reference: a worker that never starts is as
        # lost as one that dies, but only after a full timeout from
        # when WE started watching (start() refreshes it).
        self._started_s = float(clock())

    # ------------------------------------------------------------ paths
    def _beat_path(self, pid: int) -> str:
        return os.path.join(self.directory, f"hb_{pid}")

    @property
    def _restart_path(self) -> str:
        return os.path.join(self.directory, "RESTART")

    # ------------------------------------------------------------ beats
    def beat(self) -> None:
        """Stamp this worker alive (atomic replace: readers never see a
        torn timestamp)."""
        path = self._beat_path(self.process_id)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(repr(float(self._clock())))
        os.replace(tmp, path)

    def last_seen(self) -> Dict[int, Optional[float]]:
        """Beat timestamp per expected worker (None = never beat)."""
        out: Dict[int, Optional[float]] = {}
        for pid in range(self.num_processes):
            try:
                with open(self._beat_path(pid)) as f:
                    out[pid] = float(f.read().strip())
            except (OSError, ValueError):
                out[pid] = None
        return out

    def failed(self) -> List[int]:
        """Workers whose beat is stale (or missing) for more than a
        timeout since max(their last beat, this monitor's start) — the
        grace from OUR start covers both a worker that never launches
        and a relaunched incarnation reading the previous run's stale
        beat files: every peer gets one fresh timeout from the moment
        this monitor begins watching."""
        now = float(self._clock())
        lost = []
        for pid, seen in self.last_seen().items():
            if pid == self.process_id:
                continue
            ref = max(seen, self._started_s) if seen is not None \
                else self._started_s
            if now - ref > self.timeout_s:
                lost.append(pid)
        return lost

    def start(self) -> None:
        """Open the never-beat grace window and stamp our first beat."""
        self._started_s = float(self._clock())
        self.beat()

    def check(self) -> None:
        """Raise :class:`WorkerLost` if anyone has gone quiet."""
        lost = self.failed()
        if lost:
            raise WorkerLost(lost, self.timeout_s)

    # ----------------------------------------------- coordinated restart
    def request_restart(self, reason: str = "") -> None:
        tmp = f"{self._restart_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(reason or f"requested by process {self.process_id}")
        os.replace(tmp, self._restart_path)

    def restart_requested(self) -> bool:
        return os.path.exists(self._restart_path)

    def clear_restart(self) -> None:
        try:
            os.remove(self._restart_path)
        except FileNotFoundError:
            pass


class HeartbeatCallback(Callback):
    """The beat/check/stop cycle as a Trainer callback.

    Beats every batch (one atomic file replace — microseconds against a
    training step), checks the fleet every ``check_every_steps``. On
    detection it requests the coordinated restart, stops training at
    the batch boundary (``trainer.stop_training`` — the same clean-exit
    path preemption uses, so any checkpoint callbacks get their
    train-end flush), and re-raises :class:`WorkerLost` at train end so
    the supervisor sees a non-zero exit. Workers that merely OBSERVE
    the restart marker stop the same way without raising — only the
    detector reports. Compose with ``CheckpointEveryN`` + a relaunch at
    the new world size + ``fit(resume=...)`` for the full elastic
    story (scale-down restore: ``tests/test_elastic_restore.py``).
    """

    def __init__(self, monitor: HeartbeatMonitor,
                 check_every_steps: int = 10):
        self.monitor = monitor
        self.check_every_steps = max(1, int(check_every_steps))
        self.lost: Optional[WorkerLost] = None
        self._n = 0

    def on_train_begin(self, state):
        self.lost = None
        self._n = 0
        # A new incarnation starts clean: the previous run's RESTART
        # marker did its job (every survivor stopped); leaving it would
        # stop the relaunched job on its first batch. Stale beat files
        # are covered by start()'s fresh grace window.
        self.monitor.clear_restart()
        self.monitor.start()
        return None

    def on_train_batch_end(self, step, state, logs):
        self.monitor.beat()
        self._n += 1
        if self._n % self.check_every_steps:
            return None
        # Marker poll AND liveness check ride the same coarse cadence:
        # both are shared-filesystem metadata round-trips, and their
        # consumer (a supervisor relaunch after a heartbeat timeout)
        # tolerates seconds of latency — only the beat itself needs to
        # be per-batch.
        if self.monitor.restart_requested():
            log.warning("heartbeat: restart requested by another worker "
                        "— stopping at the batch boundary")
            self.trainer.stop_training = True
            return None
        try:
            self.monitor.check()
        except WorkerLost as lost:
            log.error("heartbeat: %s", lost)
            self.lost = lost
            self.monitor.request_restart(str(lost))
            self.trainer.stop_training = True
        return None

    def on_train_end(self, state, logs):
        if self.lost is not None:
            raise self.lost
        return None
