"""Pipeline-parallel strategy: DP x PP over a ``data`` x ``stage`` mesh.

Beyond-parity capability (the reference has no pipeline parallelism —
SURVEY.md §2c). Stage-stacked parameters (leading ``[n_stages, ...]`` dim,
see :class:`pddl_tpu.models.vit.GPipeViT`) shard dim 0 over the ``stage``
axis — one stage's weights per mesh position; the GPipe schedule itself is
:func:`pddl_tpu.ops.pipeline.gpipe_apply` (scan + ppermute, one compiled
SPMD program, AD-derived backward). Optimizer moments inherit the stage
layout via the same path rules.
"""

from __future__ import annotations

from typing import Optional, Sequence

from pddl_tpu.core.mesh import MeshConfig, STAGE_AXIS
from pddl_tpu.parallel.base import register_strategy
from pddl_tpu.parallel.tensor_parallel import (
    Rule,
    TensorParallelStrategy,
    _shard_dim,
)

# Stage-stacked parameter trees live under a "stages" key; everything in
# them shards its leading (stage) dim. Embed/head params fall through the
# rule table and replicate.
PIPELINE_RULES: Sequence[Rule] = (
    (r"/stages/", _shard_dim(0, STAGE_AXIS)),
)


@register_strategy("pipeline")
class PipelineStrategy(TensorParallelStrategy):
    """DP x PP: batch sharded over ``data``, stage weights over ``stage``.

    Args:
      n_stages: size of the ``stage`` mesh axis (remaining devices form
        the ``data`` axis).
      model_parallel: optional TP inside each stage (composes; the rule
        table is consulted first-match so pass combined rules if both are
        wanted on custom models).
    """

    def __init__(self, n_stages: int, model_parallel: int = 1,
                 rules: Sequence[Rule] = PIPELINE_RULES, **kwargs):
        super().__init__(model_parallel=model_parallel, rules=rules, **kwargs)
        self._mesh_config = MeshConfig(
            data=-1, model=model_parallel, stage=n_stages
        )
        self.n_stages = n_stages
