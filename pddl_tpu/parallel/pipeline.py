"""Pipeline-parallel strategy: DP x PP over a ``data`` x ``stage`` mesh.

Beyond-parity capability (the reference has no pipeline parallelism —
SURVEY.md §2c). Stage-stacked parameters (leading ``[n_stages, ...]`` dim,
see :class:`pddl_tpu.models.vit.GPipeViT`) shard dim 0 over the ``stage``
axis — one stage's weights per mesh position; the GPipe schedule itself is
:func:`pddl_tpu.ops.pipeline.gpipe_apply` (scan + ppermute, one compiled
SPMD program, AD-derived backward). Optimizer moments inherit the stage
layout via the same path rules.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

from jax.sharding import PartitionSpec

from pddl_tpu.core.mesh import MeshConfig, STAGE_AXIS
from pddl_tpu.parallel.base import register_strategy
from pddl_tpu.parallel.tensor_parallel import (
    Rule,
    TensorParallelStrategy,
    VIT_TP_RULES,
    _shard_dim,
)

# Stage-stacked parameter trees live under a "stages" key; everything in
# them shards its leading (stage) dim. Embed/head params fall through the
# rule table and replicate.
PIPELINE_RULES: Sequence[Rule] = (
    (r"/stages/", _shard_dim(0, STAGE_AXIS)),
)


def _stage_shifted(fn: Callable) -> Callable:
    """Lift a TP spec rule onto stage-stacked leaves: the leading dim
    shards over ``stage``, the TP spec applies to the rest."""

    def spec(shape: Tuple[int, ...]) -> Optional[PartitionSpec]:
        if len(shape) < 2:
            return None
        inner = fn(shape[1:])
        if inner is None:
            return None
        return PartitionSpec(STAGE_AXIS, *inner)

    return spec


# 3D parallelism (DP x PP x TP): staged block weights shard over BOTH
# `stage` (leading dim) and `model` (the Megatron layout, shifted right by
# one); anything else under /stages/ (LayerNorms, ...) shards over `stage`
# only; embed/head fall through and replicate.
PIPELINE_TP_RULES: Sequence[Rule] = tuple(
    (r"/stages/.*" + pat.lstrip("/"), _stage_shifted(fn))
    for pat, fn in VIT_TP_RULES
) + tuple(PIPELINE_RULES)


@register_strategy("pipeline")
class PipelineStrategy(TensorParallelStrategy):
    """DP x PP (x TP): batch over ``data``, stage weights over ``stage``,
    optionally Megatron TP over ``model`` inside each stage.

    Args:
      n_stages: size of the ``stage`` mesh axis (remaining devices form
        the ``data`` axis).
      model_parallel: TP degree inside each stage; >1 switches the default
        rule table to the combined 3D layout (``PIPELINE_TP_RULES``).
    """

    def __init__(self, n_stages: int, model_parallel: int = 1,
                 rules: Optional[Sequence[Rule]] = None, **kwargs):
        if rules is None:
            rules = PIPELINE_TP_RULES if model_parallel > 1 else PIPELINE_RULES
        super().__init__(model_parallel=model_parallel, rules=rules, **kwargs)
        self._mesh_config = MeshConfig(
            data=-1, model=model_parallel, stage=n_stages
        )
        self.n_stages = n_stages
