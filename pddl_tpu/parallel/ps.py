"""Parameter-server strategy: sharded variable/optimizer state.

Capability parity with ``tf.distribute.ParameterServerStrategy`` +
``MinSizePartitioner`` + in-process gRPC cluster
(``/root/reference/imagenet-resnet50-ps.py:31-84``): model variables above a
size threshold live *sharded* across hosts/devices and are fetched on
demand, scaling variable capacity with the number of "servers".

TPU-native mapping (SURVEY.md §7 "PS capability mapping", documented
semantic difference): there is no async RPC push/pull on TPU — the analogue
is **sharded state under sync SPMD**. Variables and optimizer state that
cross ``min_shard_bytes`` are laid out split along the ``data`` axis
(ZeRO-style) — at the exact shard count the reference's partitioner would
pick (rounded to a divisor of the axis): full-axis tiling for the big
tensors, a factored ``k``-way-shard × replicate layout for the 2..N-1
middle ground (:meth:`MinSizePartitioner.sharding`). XLA materializes the
all-gather (the "pull") before use and the reduce-scatter (the "push") on
update, riding ICI instead of gRPC.
Capability observables preserved: min-size-gated sharding, shard count
scaling with ``num_ps``, small variables replicated. Semantics are
synchronous, which strictly strengthens the reference's consistency model.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from pddl_tpu.core import dist
from pddl_tpu.core.mesh import DATA_AXIS, MeshConfig, build_mesh
from pddl_tpu.core.sharding import MinSizePartitioner
from pddl_tpu.parallel.base import Strategy, register_strategy

log = logging.getLogger(__name__)

PyTree = Any


@register_strategy("ps")
class ParameterServerStrategy(Strategy):
    """Sharded-state data parallelism (the PS capability, sync-SPMD).

    Args:
      num_ps: cap on shards per variable, mirroring ``max_shards=NUM_PS``
        (``imagenet-resnet50-ps.py:78``). Defaults to the data-axis size.
      min_shard_bytes: sharding threshold, default 256 KiB like the
        reference (``:77``).
      shard_optimizer_state: also shard Adam moments etc. (ZeRO-1 style);
        on by default — optimizer state is where the memory is.
    """

    def __init__(self, num_ps: Optional[int] = None,
                 min_shard_bytes: int = 256 << 10,
                 shard_optimizer_state: bool = True,
                 coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None):
        super().__init__(MeshConfig())
        self.num_ps = num_ps
        self.min_shard_bytes = min_shard_bytes
        self.shard_optimizer_state = shard_optimizer_state
        self._bootstrap = (coordinator_address, num_processes, process_id)

    def setup(self):
        if self._mesh is None:
            dist.initialize(*self._bootstrap)
            self._mesh = build_mesh(MeshConfig())
        return self._mesh

    @property
    def partitioner(self) -> MinSizePartitioner:
        return MinSizePartitioner(
            min_shard_bytes=self.min_shard_bytes,
            max_shards=self.num_ps,
            axis_name=DATA_AXIS,
        )

    def state_sharding(self, state: PyTree) -> PyTree:
        """Params (and optionally optimizer state) via the partitioner;
        scalars/batch_stats replicated."""
        mesh = self.mesh
        part = self.partitioner
        repl = NamedSharding(mesh, PartitionSpec())
        axis_size = mesh.shape[DATA_AXIS]
        capped = [0]  # leaves TF would shard but XLA's even tiling can't

        def shard_leaf(leaf):
            if not hasattr(leaf, "shape") or not hasattr(leaf, "dtype"):
                return repl
            sh = part.sharding(mesh, tuple(leaf.shape), leaf.dtype)
            # TF's partitioner would split this leaf (count > 1) but no
            # divisor of the axis size divides any of its dimensions —
            # even sub-axis tiling can't place it, so it stays whole.
            if (part.num_shards(tuple(leaf.shape), leaf.dtype, axis_size) > 1
                    and sh.is_fully_replicated):
                capped[0] += 1
            return sh

        params_sh = jax.tree.map(shard_leaf, state.params)
        if self.shard_optimizer_state:
            opt_sh = jax.tree.map(shard_leaf, state.opt_state)
        else:
            opt_sh = jax.tree.map(lambda _: repl, state.opt_state)
        if capped[0]:
            log.warning(
                "%d variable(s) would shard under the reference's "
                "MinSizePartitioner but stay REPLICATED here: no even "
                "split is feasible — no divisor of the %d-device data "
                "axis that respects the num_ps cap divides any of their "
                "dimensions (or the mesh has other live axes). Raising "
                "num_ps or lowering min_shard_bytes may shard them.",
                capped[0], axis_size,
            )
        return state.replace(
            step=repl,
            params=params_sh,
            batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
            opt_state=opt_sh,
            # EMA shadows live wherever their parameters live.
            ema_params=jax.tree.map(shard_leaf, state.ema_params),
            ema_batch_stats=jax.tree.map(lambda _: repl,
                                         state.ema_batch_stats),
        )
