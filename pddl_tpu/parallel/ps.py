"""Parameter-server strategy: sharded variable/optimizer state.

Capability parity with ``tf.distribute.ParameterServerStrategy`` +
``MinSizePartitioner`` + in-process gRPC cluster
(``/root/reference/imagenet-resnet50-ps.py:31-84``): model variables above a
size threshold live *sharded* across hosts/devices and are fetched on
demand, scaling variable capacity with the number of "servers".

TPU-native mapping (SURVEY.md §7 "PS capability mapping", documented
semantic difference): there is no async RPC push/pull on TPU — the analogue
is **sharded state under sync SPMD**. Variables and optimizer state that
cross ``min_shard_bytes`` are laid out split along the ``data`` axis
(ZeRO-style); XLA materializes the all-gather (the "pull") before use and
the reduce-scatter (the "push") on update, riding ICI instead of gRPC.
Capability observables preserved: min-size-gated sharding, shard count
scaling with ``num_ps``, small variables replicated. Semantics are
synchronous, which strictly strengthens the reference's consistency model.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from pddl_tpu.core import dist
from pddl_tpu.core.mesh import DATA_AXIS, MeshConfig, build_mesh
from pddl_tpu.core.sharding import MinSizePartitioner
from pddl_tpu.parallel.base import Strategy, register_strategy

log = logging.getLogger(__name__)

PyTree = Any


@register_strategy("ps")
class ParameterServerStrategy(Strategy):
    """Sharded-state data parallelism (the PS capability, sync-SPMD).

    Args:
      num_ps: cap on shards per variable, mirroring ``max_shards=NUM_PS``
        (``imagenet-resnet50-ps.py:78``). Defaults to the data-axis size.
      min_shard_bytes: sharding threshold, default 256 KiB like the
        reference (``:77``).
      shard_optimizer_state: also shard Adam moments etc. (ZeRO-1 style);
        on by default — optimizer state is where the memory is.
    """

    def __init__(self, num_ps: Optional[int] = None,
                 min_shard_bytes: int = 256 << 10,
                 shard_optimizer_state: bool = True,
                 coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None):
        super().__init__(MeshConfig())
        self.num_ps = num_ps
        self.min_shard_bytes = min_shard_bytes
        self.shard_optimizer_state = shard_optimizer_state
        self._bootstrap = (coordinator_address, num_processes, process_id)

    def setup(self):
        if self._mesh is None:
            dist.initialize(*self._bootstrap)
            self._mesh = build_mesh(MeshConfig())
        return self._mesh

    @property
    def partitioner(self) -> MinSizePartitioner:
        return MinSizePartitioner(
            min_shard_bytes=self.min_shard_bytes,
            max_shards=self.num_ps,
            axis_name=DATA_AXIS,
        )

    def state_sharding(self, state: PyTree) -> PyTree:
        """Params (and optionally optimizer state) via the partitioner;
        scalars/batch_stats replicated."""
        mesh = self.mesh
        part = self.partitioner
        repl = NamedSharding(mesh, PartitionSpec())
        axis_size = mesh.shape[DATA_AXIS]
        capped = [0]  # leaves TF would shard but XLA's uniform tiling can't

        def shard_leaf(leaf):
            if not hasattr(leaf, "shape") or not hasattr(leaf, "dtype"):
                return repl
            n = part.num_shards(tuple(leaf.shape), leaf.dtype, axis_size)
            spec = part.spec(tuple(leaf.shape), leaf.dtype, axis_size)
            # TF's partitioner would split this leaf (n > 1) but uniform
            # XLA tiling can't (shard count capped below the axis size, or
            # no dimension divides the axis evenly) — it stays replicated.
            if n > 1 and spec == PartitionSpec():
                capped[0] += 1
            return NamedSharding(mesh, spec)

        params_sh = jax.tree.map(shard_leaf, state.params)
        if self.shard_optimizer_state:
            opt_sh = jax.tree.map(shard_leaf, state.opt_state)
        else:
            opt_sh = jax.tree.map(lambda _: repl, state.opt_state)
        if capped[0]:
            log.warning(
                "%d variable(s) would shard %s-ways under the reference's "
                "MinSizePartitioner but stay REPLICATED here: NamedSharding "
                "tiles uniformly over the full %d-device data axis, and "
                "num_ps/min_shard_bytes cap the shard count below that. "
                "Raise num_ps (or lower min_shard_bytes) to shard them.",
                capped[0], f"<{axis_size}", axis_size,
            )
        return state.replace(
            step=repl,
            params=params_sh,
            batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
            opt_state=opt_sh,
            # EMA shadows live wherever their parameters live.
            ema_params=jax.tree.map(shard_leaf, state.ema_params),
        )
