"""Single-device strategy — the reference's plain scripts
(``/root/reference/imagenet-resnet50.py``, ``imagenet-pretrained-resnet50.py``:
no ``tf.distribute`` anywhere, one GPU).

A 1-device mesh rather than a special case: the train step, shardings and
callbacks are byte-identical to the distributed modes, so moving from one
chip to a pod is a config change (the property the reference lacked).
"""

from __future__ import annotations

from typing import Optional

import jax

from pddl_tpu.core.mesh import MeshConfig
from pddl_tpu.parallel.base import Strategy, register_strategy


@register_strategy("single")
class SingleDeviceStrategy(Strategy):
    def __init__(self, device: Optional[jax.Device] = None):
        super().__init__(MeshConfig(data=1))
        self._device = device

    def setup(self):
        if self._mesh is None:
            from pddl_tpu.core.mesh import build_mesh

            dev = self._device or jax.local_devices()[0]
            self._mesh = build_mesh(MeshConfig(data=1), devices=[dev])
        return self._mesh
