"""Tensor-parallel strategy: Megatron-style weight sharding over ``model``.

Beyond-parity capability (the reference has no tensor parallelism anywhere
in its 788 LoC — SURVEY.md §2c — but the mesh reserves a ``model`` axis for
exactly this). The strategy shards transformer weight matrices over the
``model`` mesh axis by *path rules* and lets XLA's SPMD partitioner derive
everything else — the idiomatic GSPMD formulation of Megatron TP:

- attention q/k/v projections: split by head (column-parallel),
- attention output projection: split on the head input dim (row-parallel),
- MLP up-projection: column-parallel; MLP down-projection: row-parallel.

With that layout XLA places the two canonical all-reduces per transformer
block (after attention-out and after MLP-down) on the ``model`` axis — over
ICI, composed freely with data parallelism on ``data`` (grad all-reduce)
and optimizer/PS sharding. No model changes and no per-replica code: the
rules map paths in the parameter tree (and the optimizer moments, whose
paths mirror it) to ``PartitionSpec``s.

Works out of the box for :mod:`pddl_tpu.models.vit` names; custom models
pass their own ``rules`` (first match wins).
"""

from __future__ import annotations

import logging
import re
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec

from pddl_tpu.core import dist
from pddl_tpu.core.mesh import (
    EXPERT_AXIS,
    MODEL_AXIS,
    MeshConfig,
    build_mesh,
)
from pddl_tpu.parallel.base import Strategy, register_strategy

log = logging.getLogger(__name__)

PyTree = Any

# A rule: (path regex, fn(shape) -> PartitionSpec or None to pass).
Rule = Tuple[str, Callable[[Tuple[int, ...]], Optional[PartitionSpec]]]


def _shard_dim(dim: int, axis: str = MODEL_AXIS):
    """Spec factory: shard dimension ``dim`` of the leaf over ``axis``."""

    def spec(shape: Tuple[int, ...]) -> Optional[PartitionSpec]:
        if dim >= len(shape):
            return None
        axes: list = [None] * len(shape)
        axes[dim] = axis
        return PartitionSpec(*axes)

    return spec


def _shard_heads(shape: Tuple[int, ...]) -> Optional[PartitionSpec]:
    """q/k/v DenseGeneral leaves: kernel (E, H, D) / bias (H, D) — shard H
    (the second-to-last dim)."""
    if len(shape) < 2:
        return None
    return _shard_dim(len(shape) - 2)(shape)


# Megatron layout for pddl_tpu.models.vit module names.
VIT_TP_RULES: Sequence[Rule] = (
    (r"/attn/(query|key|value)/", _shard_heads),          # column-parallel
    # out projection is a 2D (E, E) Dense applied after the [B,S,E] reshape;
    # dim 0 is the flattened head-major H*D input axis -> row-parallel.
    (r"/attn/out/kernel", _shard_dim(0)),
    (r"/attn/out/bias", lambda s: PartitionSpec()),
    (r"/mlp1/kernel", _shard_dim(1)),                     # column-parallel (E, 4E)
    (r"/mlp1/bias", _shard_dim(0)),                       # (4E,)
    (r"/mlp2/kernel", _shard_dim(0)),                     # row-parallel (4E, E)
    (r"/mlp2/bias", lambda s: PartitionSpec()),
    # Vocab-parallel embedding + LM head (the GPT family's largest
    # leaves: [V, E] and [E, V] at V=50k dwarf any block weight).
    # Embedding lookups gather from the vocab-sharded table; the head
    # matmul produces vocab-sharded logits that XLA all-gathers (or
    # keeps sharded into the loss reduction). Megatron's layout. Real
    # vocabs divide nothing (50257 = 29 x 1733) — build the model with
    # GPT(vocab_multiple=...) so the padded V tiles over the axis;
    # otherwise the divisibility fallback replicates these leaves.
    (r"/token_embed/embedding", _shard_dim(0)),
    (r"/lm_head/kernel", _shard_dim(1)),
    (r"/lm_head/bias", _shard_dim(0)),
)

# Llama family (pddl_tpu/models/llama.py): same attention layout as the
# ViT/GPT families (the /attn/ rules apply as-is; GQA just means the
# key/value leaves carry H_kv — which must divide the model axis, or the
# divisibility fallback replicates them), SwiGLU in place of mlp1/mlp2
# (gate/up column-parallel, down row-parallel — silu(gate)·up is
# elementwise in the sharded intermediate dim, so the pair needs no
# collective between them), and Embed/lm_head under Llama's own names.
LLAMA_TP_RULES: Sequence[Rule] = (
    (r"/mlp_(gate|up)/kernel", _shard_dim(1)),            # column-parallel (E, I)
    (r"/mlp_down/kernel", _shard_dim(0)),                 # row-parallel (I, E)
    (r"/embed/embedding", _shard_dim(0)),                 # vocab-parallel
) + tuple(VIT_TP_RULES)

# Expert parallelism: expert-major MoE weights (pddl_tpu/ops/moe.py —
# GELU w1/w2/b1/b2 and Mixtral-SwiGLU w1/w3/w2, all [n_experts, ...])
# shard dim 0 over `expert`; the router stays replicated. Composes with
# the TP rules above.
VIT_EP_RULES: Sequence[Rule] = (
    (r"/moe/(w1|w2|w3|b1|b2)", _shard_dim(0, EXPERT_AXIS)),
    (r"/moe/router/", lambda s: PartitionSpec()),
) + tuple(VIT_TP_RULES)

# The same expert rules over the Llama family's leaf names (Mixtral:
# routed SwiGLU experts inside LlamaBlock).
LLAMA_EP_RULES: Sequence[Rule] = (
    (r"/moe/(w1|w2|w3|b1|b2)", _shard_dim(0, EXPERT_AXIS)),
    (r"/moe/router/", lambda s: PartitionSpec()),
) + tuple(LLAMA_TP_RULES)


@register_strategy("tensor_parallel")
class TensorParallelStrategy(Strategy):
    """DP x TP over a ``data`` x ``model`` mesh.

    Args:
      model_parallel: size of the ``model`` axis (remaining devices go to
        ``data``).
      rules: path-rule table; defaults to the ViT family's Megatron layout.
        Optimizer-state leaves inherit the matching parameter's spec (optax
        moment trees mirror the param tree, so the same paths match).
    """

    def __init__(self, model_parallel: int = 1,
                 rules: Sequence[Rule] = VIT_TP_RULES,
                 coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None):
        super().__init__(MeshConfig(data=-1, model=model_parallel))
        self.rules = [(re.compile(pat), fn) for pat, fn in rules]
        self._bootstrap = (coordinator_address, num_processes, process_id)

    def setup(self):
        if self._mesh is None:
            dist.initialize(*self._bootstrap)
            self._mesh = build_mesh(self._mesh_config)
        return self._mesh

    def _spec_for(self, path: str,
                  shape: Tuple[int, ...]) -> PartitionSpec:
        for pat, fn in self.rules:
            if pat.search(path):
                spec = fn(shape)
                if spec is None:
                    continue
                # Each sharded dim must tile evenly over its mesh axis
                # (model, expert, ...) or the leaf stays replicated.
                for i, ax in enumerate(spec):
                    if ax is None:
                        continue
                    axis_size = self.mesh.shape[ax]
                    if shape[i] % axis_size:
                        log.warning(
                            "rule %s matched %s but dim %d (%d) is not "
                            "divisible by %s axis %d; leaf replicated",
                            pat.pattern, path, i, shape[i], ax, axis_size,
                        )
                        return PartitionSpec()
                # Canonicalize: a 1-way shard IS replication — drop axes of
                # size 1 (e.g. TP rules under an expert-only mesh) and
                # trailing Nones so replicated specs compare equal to P().
                axes = [ax if ax is not None and self.mesh.shape[ax] > 1
                        else None for ax in spec]
                while axes and axes[-1] is None:
                    axes.pop()
                return PartitionSpec(*axes)
        return PartitionSpec()

    def tree_sharding(self, tree: PyTree) -> PyTree:
        """Shardings for any param-shaped tree via the path rules.

        Public so inference paths (sharded GPT generation) can lay out
        raw parameter trees without a TrainState.
        """
        mesh = self.mesh
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        out = []
        for keypath, leaf in flat:
            path = "/" + "/".join(
                str(getattr(k, "key", getattr(k, "name", k)))
                for k in keypath
            )
            if hasattr(leaf, "shape") and leaf.ndim > 0:
                spec = self._spec_for(path, tuple(leaf.shape))
            else:
                spec = PartitionSpec()
            out.append(NamedSharding(mesh, spec))
        return jax.tree_util.tree_unflatten(treedef, out)

    def decode_cache_sharding(self, cache: PyTree) -> PyTree:
        """Shardings for a decode KV cache: heads over ``model``.

        Cache leaves are ``cached_key``/``cached_value`` of shape
        ``[B, H, L, D]`` (pddl_tpu/models/vit.py MultiHeadAttention);
        splitting H over the ``model`` axis co-locates each head's K/V
        with its column-parallel q/k/v projection shards, so decode steps
        need no cross-device K/V movement. Indices and any non-4D leaves
        stay replicated.
        """
        mesh = self.mesh
        mp = mesh.shape[MODEL_AXIS]
        repl = NamedSharding(mesh, PartitionSpec())
        head_sh = NamedSharding(mesh, PartitionSpec(None, MODEL_AXIS))

        def leaf_sharding(keypath, leaf):
            name = str(getattr(keypath[-1], "key", keypath[-1]))
            if (name in ("cached_key", "cached_value")
                    and getattr(leaf, "ndim", 0) == 4
                    and mp > 1 and leaf.shape[1] % mp == 0):
                return head_sh
            return repl

        return jax.tree_util.tree_map_with_path(leaf_sharding, cache)

    def state_sharding(self, state: PyTree) -> PyTree:
        repl = NamedSharding(self.mesh, PartitionSpec())
        return state.replace(
            step=repl,
            params=self.tree_sharding(state.params),
            batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
            opt_state=self.tree_sharding(state.opt_state),
            # EMA shadows inherit the TP layout of their parameters.
            ema_params=self.tree_sharding(state.ema_params),
            ema_batch_stats=jax.tree.map(lambda _: repl,
                                         state.ema_batch_stats),
        )


@register_strategy("expert_parallel")
class ExpertParallelStrategy(TensorParallelStrategy):
    """DP x EP (x TP) over a ``data`` x ``expert`` (x ``model``) mesh.

    Expert-major MoE weights (``[n_experts, ...]``, see
    :class:`pddl_tpu.ops.moe.SwitchFFN`) shard dim 0 over ``expert`` — one
    expert group per device position; XLA lowers the dispatch/combine
    einsums to all-to-alls on the ``expert`` axis. All other transformer
    weights follow the Megatron TP rules (over ``model``, size 1 unless
    ``model_parallel`` is raised), so EP and TP compose in one rule table.
    """

    def __init__(self, expert_parallel: int, model_parallel: int = 1,
                 rules: Sequence[Rule] = VIT_EP_RULES, **kwargs):
        super().__init__(model_parallel=model_parallel, rules=rules, **kwargs)
        self._mesh_config = MeshConfig(
            data=-1, model=model_parallel, expert=expert_parallel
        )
