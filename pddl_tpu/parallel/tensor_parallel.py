"""Tensor-parallel strategy: Megatron-style weight sharding over ``model``.

Beyond-parity capability (the reference has no tensor parallelism anywhere
in its 788 LoC — SURVEY.md §2c — but the mesh reserves a ``model`` axis for
exactly this). The strategy shards transformer weight matrices over the
``model`` mesh axis by *path rules* and lets XLA's SPMD partitioner derive
everything else — the idiomatic GSPMD formulation of Megatron TP:

- attention q/k/v projections: split by head (column-parallel),
- attention output projection: split on the head input dim (row-parallel),
- MLP up-projection: column-parallel; MLP down-projection: row-parallel.

With that layout XLA places the two canonical all-reduces per transformer
block (after attention-out and after MLP-down) on the ``model`` axis — over
ICI, composed freely with data parallelism on ``data`` (grad all-reduce)
and optimizer/PS sharding. No model changes and no per-replica code: the
rules map paths in the parameter tree (and the optimizer moments, whose
paths mirror it) to ``PartitionSpec``s.

Works out of the box for :mod:`pddl_tpu.models.vit` names; custom models
pass their own ``rules`` (first match wins).
"""

from __future__ import annotations

import logging
import re
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec

from pddl_tpu.core import dist
from pddl_tpu.core.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    MeshConfig,
    build_mesh,
)
from pddl_tpu.parallel.base import Strategy, register_strategy

log = logging.getLogger(__name__)

PyTree = Any

# A rule: (path regex, fn(shape) -> PartitionSpec or None to pass).
Rule = Tuple[str, Callable[[Tuple[int, ...]], Optional[PartitionSpec]]]


def _shard_dim(dim: int):
    """Spec factory: shard dimension ``dim`` of the leaf over ``model``."""

    def spec(shape: Tuple[int, ...]) -> Optional[PartitionSpec]:
        if dim >= len(shape):
            return None
        axes: list = [None] * len(shape)
        axes[dim] = MODEL_AXIS
        return PartitionSpec(*axes)

    return spec


def _shard_heads(shape: Tuple[int, ...]) -> Optional[PartitionSpec]:
    """q/k/v DenseGeneral leaves: kernel (E, H, D) / bias (H, D) — shard H
    (the second-to-last dim)."""
    if len(shape) < 2:
        return None
    return _shard_dim(len(shape) - 2)(shape)


# Megatron layout for pddl_tpu.models.vit module names.
VIT_TP_RULES: Sequence[Rule] = (
    (r"/attn/(query|key|value)/", _shard_heads),          # column-parallel
    # out projection is a 2D (E, E) Dense applied after the [B,S,E] reshape;
    # dim 0 is the flattened head-major H*D input axis -> row-parallel.
    (r"/attn/out/kernel", _shard_dim(0)),
    (r"/attn/out/bias", lambda s: PartitionSpec()),
    (r"/mlp1/kernel", _shard_dim(1)),                     # column-parallel (E, 4E)
    (r"/mlp1/bias", _shard_dim(0)),                       # (4E,)
    (r"/mlp2/kernel", _shard_dim(0)),                     # row-parallel (4E, E)
    (r"/mlp2/bias", lambda s: PartitionSpec()),
)


@register_strategy("tensor_parallel")
class TensorParallelStrategy(Strategy):
    """DP x TP over a ``data`` x ``model`` mesh.

    Args:
      model_parallel: size of the ``model`` axis (remaining devices go to
        ``data``).
      rules: path-rule table; defaults to the ViT family's Megatron layout.
        Optimizer-state leaves inherit the matching parameter's spec (optax
        moment trees mirror the param tree, so the same paths match).
    """

    def __init__(self, model_parallel: int = 1,
                 rules: Sequence[Rule] = VIT_TP_RULES,
                 coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None):
        super().__init__(MeshConfig(data=-1, model=model_parallel))
        self.rules = [(re.compile(pat), fn) for pat, fn in rules]
        self._bootstrap = (coordinator_address, num_processes, process_id)

    def setup(self):
        if self._mesh is None:
            dist.initialize(*self._bootstrap)
            self._mesh = build_mesh(self._mesh_config)
        return self._mesh

    def _spec_for(self, path: str, shape: Tuple[int, ...],
                  model_size: int) -> PartitionSpec:
        for pat, fn in self.rules:
            if pat.search(path):
                spec = fn(shape)
                if spec is None:
                    continue
                # The sharded dim must tile evenly over the model axis.
                for i, ax in enumerate(spec):
                    if ax == MODEL_AXIS and shape[i] % model_size:
                        log.warning(
                            "TP rule %s matched %s but dim %d (%d) is not "
                            "divisible by model axis %d; leaf replicated",
                            pat.pattern, path, i, shape[i], model_size,
                        )
                        return PartitionSpec()
                return spec
        return PartitionSpec()

    def state_sharding(self, state: PyTree) -> PyTree:
        mesh = self.mesh
        model_size = mesh.shape[MODEL_AXIS]

        def tree_sharding(tree):
            flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
            out = []
            for keypath, leaf in flat:
                path = "/" + "/".join(
                    str(getattr(k, "key", getattr(k, "name", k)))
                    for k in keypath
                )
                if hasattr(leaf, "shape") and leaf.ndim > 0:
                    spec = self._spec_for(path, tuple(leaf.shape), model_size)
                else:
                    spec = PartitionSpec()
                out.append(NamedSharding(mesh, spec))
            return jax.tree_util.tree_unflatten(treedef, out)

        repl = NamedSharding(mesh, PartitionSpec())
        return state.replace(
            step=repl,
            params=tree_sharding(state.params),
            batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
            opt_state=tree_sharding(state.opt_state),
        )
