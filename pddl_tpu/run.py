"""Experiment runner + CLI: the reference's 8 scripts as one entry point.

Each reference script is ``python imagenet-resnet50-<variant>.py`` with
everything hard-coded (``/root/reference/imagenet-resnet50.py:1-72`` et al.).
Here the equivalent is::

    python -m pddl_tpu --preset mirrored --data-dir /data/imagenet
    python -m pddl_tpu --preset hvd --synthetic --epochs 2   # smoke run

with working flags (the reference's own argparse attempt used broken names
``' -- ps'``/``' -- worker'``, ``imagenet-resnet50-ps.py:21-27``).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

from pddl_tpu.config import ExperimentConfig, PRESETS, get_preset


def build_trainer(cfg: ExperimentConfig, strategy=None):
    """Construct (trainer, callbacks) from a config. Import-heavy, so local."""
    import jax.numpy as jnp

    from pddl_tpu.models import registry
    from pddl_tpu.ops.augment import standard_augment, standard_eval_transform
    from pddl_tpu.parallel.base import get_strategy
    from pddl_tpu.train import callbacks as cb
    from pddl_tpu.train.loop import Trainer

    strategy = strategy or get_strategy(cfg.strategy, **_strategy_options(cfg))
    model_kwargs = dict(
        num_classes=cfg.num_classes,
        dtype=jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else jnp.float32,
        param_dtype=(jnp.bfloat16 if cfg.param_dtype == "bfloat16"
                     else jnp.float32),
        bn_mode=cfg.bn_mode,
    )
    # Transformer families only; an explicit "none" is the default and is
    # not forwarded. Other families fail HERE with guidance, not with a
    # model-constructor TypeError.
    if cfg.vocab_multiple > 1:
        if not _is_lm(cfg.model):
            raise ValueError(
                f"--vocab-multiple applies to language models "
                f"(gpt*/llama*), not {cfg.model!r}"
            )
        model_kwargs["vocab_multiple"] = cfg.vocab_multiple
    if cfg.remat and cfg.remat != "none":
        from pddl_tpu.models.registry import REMAT_MODELS

        if cfg.model not in REMAT_MODELS:
            raise ValueError(
                f"--remat applies to transformer models "
                f"({sorted(REMAT_MODELS)}), not {cfg.model!r}"
            )
        model_kwargs["remat"] = cfg.remat
    if cfg.stem != "keras":
        if "resnet" not in cfg.model:
            raise ValueError(
                f"--stem applies to the resnet family, not {cfg.model!r}"
            )
        model_kwargs["stem"] = cfg.stem
    if cfg.bn_momentum is not None:
        if "resnet" not in cfg.model:
            raise ValueError(
                f"--bn-momentum applies to the resnet family (the only "
                f"BatchNorm models), not {cfg.model!r}"
            )
        model_kwargs["bn_momentum"] = cfg.bn_momentum
    model = registry.get_model(cfg.model, **model_kwargs)

    lr = cfg.learning_rate
    if cfg.scale_lr:  # Horovod's 0.1*size (imagenet-resnet50-hvd.py:99)
        lr = strategy.scale_learning_rate(lr)

    schedule_options = dict(cfg.lr_schedule_options)
    if cfg.lr_schedule and "decay_steps" not in schedule_options:
        if cfg.steps_per_epoch:
            # Default horizon: the full run, counted in OPTIMIZER updates —
            # with --grad-accum k, optax.MultiSteps advances the schedule
            # once per k micro-batches, so the micro-step total over-counts
            # the horizon k-fold.
            accum = cfg.gradient_accumulation_steps or 1
            schedule_options["decay_steps"] = max(
                1, cfg.steps_per_epoch * cfg.epochs // accum
            )
        elif cfg.lr_schedule not in ("constant", "piecewise"):
            # Fail here with guidance, not deep inside optax: with real
            # data the per-epoch step count isn't known until iteration.
            raise ValueError(
                f"--lr-schedule {cfg.lr_schedule} needs a decay horizon: "
                "pass --lr-decay-steps, or set --steps-per-epoch so it "
                "defaults to epochs*steps_per_epoch"
            )

    if _is_lm(cfg.model):
        # Language models: token batches, no image augmentation.
        trainer = Trainer(
            model, optimizer=cfg.optimizer, learning_rate=lr,
            strategy=strategy, seed=cfg.seed,
            input_key="tokens", target_key="targets",
            metrics=["accuracy", "perplexity"],  # the standard LM pair
            lr_schedule=cfg.lr_schedule,
            lr_schedule_options=schedule_options,
            ema_decay=cfg.ema_decay,
            gradient_accumulation_steps=cfg.gradient_accumulation_steps,
            param_update=cfg.param_update,
        )
    else:
        # Crop never exceeds the input (the reference's RandomCrop(244) on
        # 224 inputs is the documented bug we deliberately fix — SURVEY.md
        # §0); a preset crop (hvd: 160) shrinks proportionally if
        # image_size is overridden smaller.
        crop = min(cfg.crop or cfg.image_size, cfg.image_size)
        trainer = Trainer(
            model,
            optimizer=cfg.optimizer,
            learning_rate=lr,
            strategy=strategy,
            seed=cfg.seed,
            augment=standard_augment(crop=crop, flip=cfg.flip),
            eval_transform=standard_eval_transform(crop=crop),
            lr_schedule=cfg.lr_schedule,
            lr_schedule_options=schedule_options,
            ema_decay=cfg.ema_decay,
            gradient_accumulation_steps=cfg.gradient_accumulation_steps,
            param_update=cfg.param_update,
        )

    callbacks = []
    # A compiled schedule owns the LR; callback-driven LR control would be
    # overwritten every step, so it is disabled alongside one.
    if cfg.reduce_lr_on_plateau and not cfg.lr_schedule:  # reference's (:64)
        callbacks.append(cb.ReduceLROnPlateau())
    if cfg.early_stopping:  # (:65)
        callbacks.append(cb.EarlyStopping())
    if cfg.warmup_epochs and not cfg.lr_schedule:
        callbacks.append(cb.LearningRateWarmup(warmup_epochs=cfg.warmup_epochs))
    if cfg.verbose:
        # The reference's rank-0 print(model.summary())
        # (imagenet-resnet50-hvd.py:95-96), for every preset.
        callbacks.append(cb.ModelSummary())
    callbacks.append(cb.Timing())
    if cfg.profile_dir:
        from pddl_tpu.utils.profiling import Profiler

        callbacks.append(Profiler(cfg.profile_dir))
    if cfg.checkpoint_dir:
        # Writers only — restore is fit(resume=...)'s job (wired in
        # run_experiment), which restores the newest VERIFIED save and
        # repositions the data stream mid-epoch; a second restoring
        # callback could resurrect a corrupt latest save the resume
        # path deliberately skipped. Keep >= 2 saves so the torn-latest
        # fallback always has somewhere to land.
        # Cloud-TPU preemption (SIGTERM) -> consistent save + clean
        # stop; the next --resume run continues from it.
        from pddl_tpu.utils.preemption import PreemptionCheckpoint

        if cfg.checkpoint_every_steps:
            # Step-granular verified saves subsume the epoch backup —
            # two managers retaining different step lists on one
            # directory would race each other's GC — and the grace
            # save DELEGATES to the same manager for the same reason.
            from pddl_tpu.ckpt import CheckpointEveryN

            cen = CheckpointEveryN(
                cfg.checkpoint_dir,
                every_n_steps=cfg.checkpoint_every_steps)
            callbacks.append(cen)
            callbacks.append(PreemptionCheckpoint(delegate=cen))
        else:
            from pddl_tpu.ckpt import ModelCheckpoint

            mc = ModelCheckpoint(cfg.checkpoint_dir, max_to_keep=2)
            callbacks.append(mc)
            callbacks.append(PreemptionCheckpoint(delegate=mc))
    return trainer, callbacks


def _is_lm(model_name: str) -> bool:
    """Language-model registry names (token batches, no augmentation).

    Exact membership in the registry's ``is_lm`` set — never substring
    matching, so a future vision entry whose name merely contains 'gpt'
    can't silently be fed token batches (ADVICE r3)."""
    from pddl_tpu.models.registry import LM_MODELS

    return model_name in LM_MODELS


def _strategy_options(cfg: ExperimentConfig) -> dict:
    """``cfg.strategy_options``, with the family-correct TP rule table.

    The Llama family's SwiGLU/embed leaves live under their own names
    (``mlp_gate``/``mlp_up``/``mlp_down``, ``embed``), which the default
    ``VIT_TP_RULES`` never match — a tensor-parallel Llama would silently
    replicate the bulk of each block. Explicit ``rules`` in the config
    still win.
    """
    opts = dict(cfg.strategy_options)
    if (cfg.strategy == "tensor_parallel" and "llama" in cfg.model
            and "rules" not in opts):
        from pddl_tpu.parallel.tensor_parallel import LLAMA_TP_RULES

        opts["rules"] = LLAMA_TP_RULES
    return opts


def build_data(cfg: ExperimentConfig, strategy):
    """Train/val iterables: real ImageNet when ``data_dir`` is set, else
    synthetic (same shapes/dtypes)."""
    global_batch = strategy.scale_batch_size(cfg.per_replica_batch)
    val_global = strategy.scale_batch_size(
        cfg.val_per_replica_batch or cfg.per_replica_batch
    )
    if _is_lm(cfg.model):
        if cfg.data_dir:
            from pddl_tpu.data.text import load_token_corpus, read_meta

            n_procs = strategy.data_process_count
            corpus = load_token_corpus(
                cfg.data_dir, seq_len=cfg.seq_len,
                train_batch_size=global_batch, val_batch_size=val_global,
                seed=cfg.seed,
                process_index=strategy.process_index if n_procs > 1 else 0,
                process_count=n_procs,
            )
            # Check AFTER loading: first runs from a raw train.txt only
            # have a meta.json once preparation wrote it. A .bin dropped
            # in without a sidecar is bounded by scanning its ids once.
            meta = read_meta(cfg.data_dir)
            vocab = (meta["vocab_size"] if meta and "vocab_size" in meta
                     else corpus[0].max_token() + 1)
            if vocab > cfg.num_classes:
                raise ValueError(
                    f"corpus vocab size {vocab} exceeds model vocab "
                    f"(--num-classes {cfg.num_classes})"
                )
            return corpus
        from pddl_tpu.data.synthetic import SyntheticLanguageModeling

        n_procs = strategy.data_process_count
        common = dict(
            seq_len=cfg.seq_len, vocab_size=cfg.num_classes or 64,
            seed=cfg.seed,
            process_index=strategy.process_index if n_procs > 1 else 0,
            process_count=n_procs,
        )
        return (SyntheticLanguageModeling(batch_size=global_batch, **common),
                SyntheticLanguageModeling(batch_size=val_global,
                                          index_offset=1 << 20, **common))
    if cfg.data_dir:
        from pddl_tpu.data.imagenet import load_imagenet

        return load_imagenet(
            cfg.data_dir,
            train_batch_size=global_batch,
            val_batch_size=val_global,
            shard=cfg.data_shard,
            process_index=strategy.process_index,
            process_count=strategy.data_process_count,
            image_size=cfg.image_size,
            seed=cfg.seed,
        )
    from pddl_tpu.data.synthetic import SyntheticImageClassification

    n_procs = strategy.data_process_count
    train = SyntheticImageClassification(
        batch_size=global_batch, image_size=cfg.image_size,
        num_classes=cfg.num_classes, seed=cfg.seed,
        signal_strength=cfg.synthetic_signal,
        process_index=strategy.process_index if n_procs > 1 else 0,
        process_count=n_procs,
    )
    val = SyntheticImageClassification(
        batch_size=val_global, image_size=cfg.image_size,
        num_classes=cfg.num_classes, seed=cfg.seed,
        signal_strength=cfg.synthetic_signal,
        process_index=strategy.process_index if n_procs > 1 else 0,
        process_count=n_procs, index_offset=1 << 20,
    )
    return train, val


def run_experiment(cfg: ExperimentConfig, steps_per_epoch: Optional[int] = None,
                   validation_steps: Optional[int] = None):
    """The whole reference-script skeleton (SURVEY.md §0 steps 1-5):
    data → model → strategy → fit(callbacks) → save. Returns the History."""
    from pddl_tpu.train.loop import Trainer  # noqa: F401 (import check)

    # weights='imagenet' mode: an explicit local .h5 wins; otherwise the
    # preset's weights="imagenet" resolves the official keras-applications
    # file for cfg.model from the cache (ckpt/fetch.py — download only on
    # explicit opt-in, with the offline procedure in the error text
    # otherwise). Resolved FIRST: a missing file must fail in under a
    # second, not after minutes of multi-host mesh/data setup.
    h5_path = cfg.pretrained_h5
    if not h5_path and cfg.weights == "imagenet":
        from pddl_tpu.ckpt.fetch import fetch_keras_resnet50_weights

        h5_path = fetch_keras_resnet50_weights(
            model=cfg.model, download=cfg.download_weights
        )

    # The strategy bootstraps FIRST: jax.distributed.initialize (inside
    # setup) must run before anything that can initialize the XLA backend,
    # and build_trainer's checkpoint-callback branch imports orbax, which
    # does. Caught by the multi-process kill/resume test.
    from pddl_tpu.parallel.base import get_strategy

    strategy = get_strategy(cfg.strategy, **_strategy_options(cfg))
    strategy.setup()
    trainer, callbacks = build_trainer(cfg, strategy)
    train, val = build_data(cfg, strategy)

    if h5_path:
        _load_pretrained(trainer, cfg, train, h5_path)

    # Crash-resume is fit(resume=...): restores the newest VERIFIED
    # checkpoint (torn/corrupt latest skipped), repositions the data
    # stream from the saved loader metadata, and continues MID-epoch.
    # An empty checkpoint directory starts fresh, so the same --resume
    # command line serves the first launch and every restart.
    resume = cfg.checkpoint_dir if (cfg.resume and cfg.checkpoint_dir) \
        else None

    spe = steps_per_epoch or cfg.steps_per_epoch
    if cfg.data_dir is None and spe is None:
        raise ValueError(
            "synthetic data is an infinite stream: set --steps-per-epoch "
            "(or provide --data-dir for a finite ImageNet epoch)"
        )
    history = trainer.fit(
        train,
        epochs=cfg.epochs,
        steps_per_epoch=spe,
        validation_data=val,
        validation_steps=validation_steps or (spe and max(1, spe // 4)),
        callbacks=callbacks,
        verbose=cfg.verbose,
        resume=resume,
    )

    if cfg.save_path and strategy.is_coordinator:
        # Final save, the model.save moment (imagenet-resnet50.py:69-72) —
        # with the Horovod script's rank-gating (and its str+int crash :127
        # fixed by construction).
        from pddl_tpu.ckpt.keras_import import export_keras_style_h5

        # With EMA enabled, the shadow weights are what eval ran on —
        # export those (standard EMA serving practice), together with the
        # EMA-shadowed BN statistics they were evaluated against.
        use_ema = (trainer.state.ema_params is not None
                   and trainer.eval_with_ema)
        export_params = (
            trainer.state.ema_params if use_ema else trainer.state.params
        )
        export_stats = (
            trainer.state.ema_batch_stats
            if use_ema and trainer.state.ema_batch_stats is not None
            else trainer.state.batch_stats
        )
        if cfg.save_path.endswith(".shlo"):
            # Serialized StableHLO inference artifact (ckpt/export.py):
            # the compiled forward itself, loadable by any XLA runtime.
            import jax

            from pddl_tpu.ckpt.export import save_inference_artifact

            if _is_lm(cfg.model):
                shape: tuple = (1, cfg.seq_len)
                dtype = "int32"
            else:
                shape = (1, cfg.image_size, cfg.image_size, 3)
                dtype = "float32"
            save_inference_artifact(
                cfg.save_path, trainer.model,
                jax.device_get(export_params), shape, input_dtype=dtype,
                batch_stats=jax.device_get(export_stats),
            )
        elif cfg.save_path.endswith(".h5") and cfg.model.startswith("resnet"):
            variables = {"params": export_params,
                         "batch_stats": export_stats}
            export_keras_style_h5(cfg.save_path, variables)
        else:
            from pddl_tpu.ckpt.checkpoint import save_params_npz

            save_params_npz(cfg.save_path, export_params)
    return history


def _load_pretrained(trainer, cfg: ExperimentConfig, train_data,
                     h5_path: str) -> None:
    """Init state then overwrite backbone params from the Keras .h5."""
    import jax

    from pddl_tpu.ckpt import load_keras_resnet50_h5

    first = next(iter(train_data))
    trainer.init_state(first)
    variables = {"params": trainer.state.params,
                 "batch_stats": trainer.state.batch_stats}
    # Block counts per family so resnet101/152 imports map the right tree
    # (models/resnet.py:208-209).
    stage_sizes = {
        "resnet101": (3, 4, 23, 3),
        "resnet152": (3, 8, 36, 3),
    }.get(cfg.model, (3, 4, 6, 3))
    loaded = load_keras_resnet50_h5(h5_path, variables,
                                    stage_sizes=stage_sizes)
    # Re-place with the strategy's shardings preserved.
    params = jax.tree.map(
        lambda new, old: jax.device_put(new, old.sharding),
        loaded["params"], trainer.state.params,
    )
    stats = jax.tree.map(
        lambda new, old: jax.device_put(new, old.sharding),
        loaded.get("batch_stats", {}), trainer.state.batch_stats,
    )
    # EMA shadows must restart from the loaded weights, not the random
    # init they were seeded with (eval/export run on the shadows) — the
    # batch_stats shadow likewise, or EMA eval pairs imported weights
    # with mean=0/var=1 init statistics.
    ema = trainer.state.ema_params
    if ema is not None:
        ema = jax.tree.map(
            lambda new, old: jax.device_put(new, old.sharding), params, ema
        )
    ema_bs = trainer.state.ema_batch_stats
    if ema_bs is not None:
        ema_bs = jax.tree.map(
            lambda new, old: jax.device_put(new, old.sharding), stats, ema_bs
        )
    trainer.state = trainer.state.replace(params=params, batch_stats=stats,
                                          ema_params=ema,
                                          ema_batch_stats=ema_bs)


def main(argv=None) -> int:
    # Honor the standard JAX_PLATFORMS env contract even when a site
    # plugin (e.g. a test-harness sitecustomize) pinned jax_platforms in
    # config at interpreter boot — config beats env in jax, so without
    # this a worker launched with JAX_PLATFORMS=cpu silently lands on the
    # pinned platform, with the wrong device count AND process_index=0 on
    # every host (which breaks any primary-host-gated coordination, e.g.
    # orbax checkpoint finalization). Must run before backend init.
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    p = argparse.ArgumentParser(
        prog="pddl_tpu",
        description="TPU-native ResNet/ImageNet distributed training "
                    "(presets mirror the 8 reference scripts)",
    )
    p.add_argument("--preset", choices=sorted(PRESETS), default="single")
    p.add_argument("--data-dir", default=None, help="ImageNet root (TFDS/"
                   "TFRecords/folders); omit for --synthetic")
    p.add_argument("--synthetic", action="store_true",
                   help="force synthetic data even if --data-dir is set")
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--steps-per-epoch", type=int, default=None)
    p.add_argument("--batch", type=int, default=None, help="per-replica batch")
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--lr-schedule", default=None,
                   choices=["cosine", "warmup_cosine", "exponential",
                            "linear", "piecewise", "constant"],
                   help="compiled step->LR schedule (disables plateau/"
                        "warmup callbacks); decay horizon = "
                        "--lr-decay-steps, or epochs*steps_per_epoch when "
                        "--steps-per-epoch is set")
    p.add_argument("--lr-decay-steps", type=int, default=None,
                   help="schedule horizon in OPTIMIZER updates (with "
                        "--grad-accum k that is one per k micro-batches); "
                        "includes --lr-warmup-steps")
    p.add_argument("--lr-warmup-steps", type=int, default=None,
                   help="linear warmup, in optimizer updates; counted "
                        "inside --lr-decay-steps")
    p.add_argument("--lr-boundaries", default=None,
                   help="piecewise schedule: comma-separated step:scale "
                        "pairs, e.g. 30000:0.1,60000:0.1")
    p.add_argument("--grad-accum", type=int, default=None,
                   help="average gradients over k micro-batches per "
                        "optimizer update (large effective batch)")
    p.add_argument("--ema-decay", type=float, default=None,
                   help="exponential moving average of params; eval/"
                        "export use the shadow weights")
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--synthetic-signal", type=float, default=None,
                   help="synthetic image task: class-mean separation in "
                        "noise-std units (default 1.0; raise so val "
                        "metrics track learning, not memorization)")
    p.add_argument("--bn-momentum", type=float, default=None,
                   help="resnet family: BatchNorm moving-average "
                        "momentum (default Keras-parity 0.99; lower for "
                        "short runs so eval stats converge)")
    p.add_argument("--crop", type=int, default=None)
    p.add_argument("--num-classes", type=int, default=None)
    p.add_argument("--seq-len", type=int, default=None,
                   help="LM sequence length (token-window size)")
    p.add_argument("--vocab-multiple", type=int, default=None,
                   help="pad the LM vocab dim to a multiple (enables "
                        "vocab-parallel TP on real vocab sizes)")
    p.add_argument("--param-update", default=None,
                   choices=["plain", "stochastic_round", "f32_master"],
                   help="update rule for bf16 param storage "
                        "(train/mixed_precision.py); ignored for f32")
    p.add_argument("--remat", default=None, choices=["none", "dots", "full"],
                   help="activation rematerialization for transformer "
                        "models (trade recompute for HBM)")
    p.add_argument("--model", default=None)
    p.add_argument("--stem", default=None,
                   choices=["keras", "space_to_depth"],
                   help="resnet stem variant: exact keras.applications "
                        "shape, or the MLPerf-style space-to-depth "
                        "throughput form (same function)")
    p.add_argument("--strategy", default=None,
                   choices=["single", "mirrored", "multiworker", "ps",
                            "tensor_parallel", "expert_parallel"])
    # (pipeline parallelism needs a stage-stacked model — GPipeViT — which
    # carries its mesh; it is a library-API construction, see README.)
    p.add_argument("--model-parallel", type=int, default=None,
                   help="TP degree (tensor_parallel/expert_parallel only)")
    p.add_argument("--expert-parallel", type=int, default=None,
                   help="EP degree (expert_parallel only)")
    p.add_argument("--pretrained-h5", default=None,
                   help="local keras-style weight .h5; overrides the "
                        "preset's weights='imagenet' cache lookup")
    p.add_argument("--download-weights", action="store_true",
                   help="allow fetching the official keras-applications "
                        "weight file into the cache when absent "
                        "(ckpt/fetch.py; off by default — TPU hosts may "
                        "have no egress)")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every-steps", type=int, default=None,
                   help="step-granular verified checkpoint cadence "
                        "(CheckpointEveryN); a --resume restart then "
                        "continues MID-epoch from the newest verified "
                        "save instead of replaying the epoch")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--save", dest="save_path", default=None)
    p.add_argument("--profile-dir", default=None,
                   help="write jax.profiler traces here (view in "
                        "TensorBoard's profile plugin)")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--verbose", type=int, default=None)
    args = p.parse_args(argv)

    overrides = {}
    mapping = {
        "data_dir": args.data_dir, "epochs": args.epochs,
        "steps_per_epoch": args.steps_per_epoch,
        "per_replica_batch": args.batch, "learning_rate": args.lr,
        "image_size": args.image_size, "crop": args.crop,
        "synthetic_signal": args.synthetic_signal,
        "bn_momentum": args.bn_momentum,
        "num_classes": args.num_classes, "seq_len": args.seq_len,
        "vocab_multiple": args.vocab_multiple,
        "remat": args.remat, "stem": args.stem,
        "param_update": args.param_update,
        "model": args.model, "strategy": args.strategy,
        "pretrained_h5": args.pretrained_h5,
        "checkpoint_dir": args.checkpoint_dir,
        "checkpoint_every_steps": args.checkpoint_every_steps,
        "save_path": args.save_path, "seed": args.seed,
        "verbose": args.verbose, "profile_dir": args.profile_dir,
        "lr_schedule": args.lr_schedule, "ema_decay": args.ema_decay,
        "gradient_accumulation_steps": args.grad_accum,
    }
    for field, value in mapping.items():
        if value is not None:
            overrides[field] = value
    schedule_opts = {}
    if args.lr_decay_steps is not None:
        schedule_opts["decay_steps"] = args.lr_decay_steps
    if args.lr_warmup_steps is not None:
        schedule_opts["warmup_steps"] = args.lr_warmup_steps
    if args.lr_boundaries:
        try:
            schedule_opts["boundaries_and_scales"] = {
                int(pair.split(":")[0]): float(pair.split(":")[1])
                for pair in args.lr_boundaries.split(",")
            }
        except (ValueError, IndexError):
            p.error("--lr-boundaries must be step:scale[,step:scale...]")
    if schedule_opts:
        overrides["lr_schedule_options"] = schedule_opts
    if args.resume:
        overrides["resume"] = True
    if args.download_weights:
        overrides["download_weights"] = True
    if args.synthetic:
        overrides["data_dir"] = None

    # Degree flags only apply to the strategies whose constructors take
    # them; reject mismatches here instead of a TypeError deep inside.
    if args.model_parallel is not None and args.strategy not in (
            "tensor_parallel", "expert_parallel"):
        p.error("--model-parallel requires --strategy tensor_parallel "
                "or expert_parallel")
    if args.expert_parallel is not None and args.strategy != "expert_parallel":
        p.error("--expert-parallel requires --strategy expert_parallel")
    if args.strategy == "expert_parallel" and args.expert_parallel is None:
        p.error("--strategy expert_parallel needs --expert-parallel N")
    strategy_options = {}
    if args.model_parallel is not None:
        strategy_options["model_parallel"] = args.model_parallel
    if args.expert_parallel is not None:
        strategy_options["expert_parallel"] = args.expert_parallel
    if strategy_options:
        overrides["strategy_options"] = strategy_options

    cfg = get_preset(args.preset, **overrides)
    run_experiment(cfg)
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
