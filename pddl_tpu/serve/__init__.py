"""Online serving: continuous batching over the compiled decode path.

`engine.py` is the step loop (slot pool, fused per-slot decode tick),
`scheduler.py` the admission policy (FCFS + load shedding + prefill
budget), `request.py` the per-request lifecycle, `metrics.py` the
telemetry, `kvcache/` the prefix-aware KV reuse layer (radix index +
device block pool). See `docs/SERVING.md` § "Online serving".
"""

from pddl_tpu.serve.engine import ServeEngine
from pddl_tpu.serve.kvcache import RadixPrefixCache
from pddl_tpu.serve.metrics import ServeMetrics
from pddl_tpu.serve.request import (
    FinishReason,
    QueueFull,
    Request,
    RequestHandle,
    RequestState,
    SamplingParams,
)
from pddl_tpu.serve.scheduler import FCFSScheduler

__all__ = [
    "FCFSScheduler",
    "FinishReason",
    "QueueFull",
    "RadixPrefixCache",
    "Request",
    "RequestHandle",
    "RequestState",
    "SamplingParams",
    "ServeEngine",
    "ServeMetrics",
]
