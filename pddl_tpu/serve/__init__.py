"""Online serving: continuous batching over the compiled decode path.

`engine.py` is the step loop (slot pool, fused per-slot decode tick,
chunked-prefill time slicing), `scheduler.py` the admission policy
(priority classes + EDF + anti-starvation aging, load shedding,
deadline shed, prefill budget), `request.py` the per-request lifecycle,
`metrics.py` the telemetry, `kvcache/` the prefix-aware KV reuse layer
(radix index + device block pool), `faults.py` seeded deterministic
fault injection, `drain.py` the SIGTERM drain/restore snapshot,
`fleet/` the multi-replica tier (health-checked router, replica
failover, live request migration), `tenant/` the multi-tenant layer
(paged per-request LoRA adapters + grammar-constrained decoding). See
`docs/SERVING.md` § "Online serving", § "Serving fleet" and
§ "Multi-tenant serving", and `docs/OPERATIONS.md` § "Failure modes &
recovery (serving)", § "Fleet runbook" and § "Adapter pool sizing".
"""

from pddl_tpu.serve.engine import ServeEngine
from pddl_tpu.serve.faults import (
    FaultKind,
    FaultPlan,
    FaultSpec,
    InjectedResourceExhausted,
    InjectedTransientError,
    KillPoint,
)
from pddl_tpu.serve.kvcache import RadixPrefixCache
from pddl_tpu.serve.metrics import ServeMetrics
from pddl_tpu.serve.request import (
    AdmissionRejected,
    FinishReason,
    Priority,
    QueueFull,
    Request,
    RequestHandle,
    RequestState,
    SamplingParams,
)
from pddl_tpu.serve.scheduler import FCFSScheduler, SLOScheduler
from pddl_tpu.serve.tenant import AdapterRegistry, TenantConfig

__all__ = [
    "AdapterRegistry",
    "AdmissionRejected",
    "TenantConfig",
    "FCFSScheduler",
    "Priority",
    "SLOScheduler",
    "FaultKind",
    "FaultPlan",
    "FaultSpec",
    "FinishReason",
    "InjectedResourceExhausted",
    "InjectedTransientError",
    "KillPoint",
    "QueueFull",
    "RadixPrefixCache",
    "Request",
    "RequestHandle",
    "RequestState",
    "SamplingParams",
    "ServeEngine",
    "ServeMetrics",
]
