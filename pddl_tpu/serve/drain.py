"""Drain & restore: the serving analog of checkpoint-on-SIGTERM.

The training side already survives Cloud-TPU preemption
(`utils/preemption.py`: SIGTERM → flag → save a consistent TrainState
at the next batch boundary → ``--resume``). The serving side loses the
whole queue on the same signal — unless in-flight requests are
snapshotted and resumed. This module is that snapshot's serialization:
``ServeEngine.drain()`` collects every queued + running request's HOST
state (prompt, tokens generated so far, sampling params, deadline
budget), these helpers write/read it, and ``ServeEngine.restore()``
resubmits the lot into a fresh engine where the replay path rebuilds
each running request's KV token-exactly (prompt re-prefilled, known
tokens re-fed through the normal fused tick).

Why JSON and not the orbax ``ckpt`` machinery: the snapshot contains NO
device state. KV caches are deliberately excluded — they are pure
functions of (params, tokens), recomputing them costs one replay
prefill per request, and shipping them would tie the snapshot to one
cache layout/shape config. What this file DOES reuse from the ckpt
discipline is crash-safety: the snapshot is written to a temp file and
atomically renamed (the same torn-write rule `ckpt/checkpoint.py`
enforces via orbax's tmp-dir protocol), so a kill mid-drain leaves
either the old snapshot or the new one, never a half-written file.

The same wire format is the fleet's LIVE-MIGRATION carrier, in three
escalating uses: failover (r11 — a dying replica's snapshot restores
on survivors), graceful fleet drain (`FleetRouter.drain`), and — since
the elastic autoscaler (`serve/fleet/autoscaler.py`) — scheduled
scale-down retirement, where the snapshot path is the NORMAL case
rather than the lucky one: `FleetRouter.scale_down` captures the
victim's queued+running streams here and restores them on survivors
before the process exits, which is what makes "zero lost requests" a
property of the wire format, not of timing.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

from pddl_tpu.serve.request import (
    Priority,
    Request,
    RequestHandle,
    RequestState,
    SamplingParams,
)

# Version 2 added the per-request ``priority`` field (ISSUE 7's SLO
# classes). Version-1 snapshots — taken by a pre-priority engine —
# still restore: an absent priority defaults to ``interactive``, the
# class every pre-SLO request implicitly was.
# Version 3 (paged attention): the snapshot header carries ``paged``
# and each RUNNING request its slot's block table — postmortem context
# only (which pool blocks the stream occupied, how much was shared).
# Restore NEVER reads the tables: pool storage dies with the process
# and KV is a pure function of (params, tokens), so every version —
# v2 copy-engine snapshots included — restores through the same
# replay/prefill path, into either engine mode.
# Version 4 (multi-tenant serving, ISSUE 9): each entry carries the
# request's ``adapter`` name and ``constraint`` spec dict (both
# ``None`` for plain requests). Restore semantics: v1-v3 entries have
# neither key and decode to "no adapter, unconstrained" — every older
# snapshot restores into a tenant-capable engine unchanged, in either
# engine mode; adapter weights are NEVER snapshotted (the registry is
# deployment config, FSM state a pure function of the emitted tokens),
# so the replay path rebuilds tenant streams exactly like KV. Future
# versions still refuse below.
# Version 5 (speculative serving, ISSUE 12): the header carries
# ``spec_k`` (the drafting config the streams ran under) and each
# entry a ``spec`` dict — the stream's lifetime ``{drafted, accepted}``
# draft accounting, so a migrated speculative stream keeps honest
# acceptance telemetry on its new replica. Neither is a restore INPUT
# beyond the counters: KV, FSM state, and every drafter's state are
# pure functions of (params, tokens), so v1-v5 snapshots all restore
# through the same replay path into ANY engine — speculative or not,
# row or paged (a speculative engine merely re-feeds the known tokens
# spec_k+1 per verify window instead of one per tick). Future versions
# still refuse below.
SNAPSHOT_VERSION = 5
_READABLE_VERSIONS = frozenset({1, 2, 3, 4, 5})

# Machine-checked wire manifest (graftlint `snapshot-hygiene`,
# docs/ANALYSIS.md): the exact entry keys ``_encode_core``/
# ``encode_handle`` emit at the CURRENT snapshot version. Changing the
# entry shape requires bumping SNAPSHOT_VERSION, renaming this tuple to
# ENTRY_KEYS_V<new>, and extending the compat pins in the same commit —
# the static checker fails the tree otherwise, which is what turns
# "remembered to bump" into "cannot forget to bump".
ENTRY_KEYS_V5 = ("prompt", "max_new_tokens", "sampling", "deadline_s",
                 "priority", "adapter", "constraint", "elapsed_s",
                 "tokens", "ttft_s", "spec", "block_table")


def encode_sampling(sampling: SamplingParams) -> Dict[str, object]:
    """The one wire shape for sampling params — shared by snapshot
    entries here and the fleet's submit protocol
    (`serve/fleet/replica.py`), so a new sampling field is added in
    exactly one encode/decode pair."""
    return {
        "temperature": float(sampling.temperature),
        "top_k": int(sampling.top_k) if sampling.top_k is not None else None,
        "top_p": (float(sampling.top_p)
                  if sampling.top_p is not None else None),
    }


def decode_sampling(d) -> SamplingParams:
    d = d or {}
    return SamplingParams(temperature=float(d.get("temperature", 0.0)),
                          top_k=d.get("top_k"), top_p=d.get("top_p"))


def encode_spec(handle: RequestHandle) -> Dict[str, object]:
    """The v5 per-entry speculative accounting (one encode/decode pair
    like :func:`encode_sampling`): the stream's lifetime drafted/
    accepted counters, zeros on non-speculative engines."""
    return {"drafted": int(getattr(handle, "spec_drafted", 0)),
            "accepted": int(getattr(handle, "spec_accepted", 0))}


def encode_handle(handle: RequestHandle, now_s: float,
                  block_table=None) -> Dict[str, object]:
    """One request's restorable host state. ``elapsed_s`` (age at drain
    time) rather than an absolute arrival lets the restoring engine —
    whose clock has a different epoch — keep deadline semantics: the
    wall budget already consumed stays consumed. ``block_table`` (a
    paged engine's per-slot pool block ids, running requests only) is
    v3 postmortem context — see the version note above."""
    entry = _encode_core(handle, now_s)
    if block_table is not None:
        entry["block_table"] = [int(b) for b in block_table]
    return entry


def _encode_core(handle: RequestHandle, now_s: float) -> Dict[str, object]:
    return {
        "prompt": [int(t) for t in handle.request.prompt],
        "max_new_tokens": int(handle.request.max_new_tokens),
        "sampling": encode_sampling(handle.request.sampling),
        "deadline_s": (float(handle.request.deadline_s)
                       if handle.request.deadline_s is not None else None),
        "priority": handle.request.priority.value,
        # v4 tenant fields (both None for plain requests — and absent
        # entirely from v1-v3 entries, which decode to the same).
        "adapter": (str(handle.request.adapter)
                    if handle.request.adapter is not None else None),
        "constraint": handle.request.constraint,
        "elapsed_s": max(0.0, float(now_s - handle.arrival_s)),
        "tokens": [int(t) for t in handle.tokens],
        "ttft_s": (float(handle.ttft_s)
                   if handle.ttft_s is not None else None),
        # v5: the stream's lifetime draft accounting (zeros on
        # non-speculative engines and for never-served requests) — the
        # acceptance telemetry follows the stream across migrations.
        "spec": encode_spec(handle),
    }


def decode_handle(entry: Dict[str, object], now_s: float) -> RequestHandle:
    """Rebuild a QUEUED handle from a snapshot entry. A non-empty
    ``tokens`` list marks it for the engine's replay admission (KV
    rebuilt from prompt + tokens, stream continued token-exactly); an
    empty one re-enters as a fresh request."""
    req = Request(
        prompt=[int(t) for t in entry["prompt"]],
        max_new_tokens=int(entry["max_new_tokens"]),
        sampling=decode_sampling(entry.get("sampling")),
        deadline_s=entry.get("deadline_s"),
        # Version-1 entries predate priority classes: default to
        # interactive (what every pre-SLO request implicitly was)
        # instead of raising on the missing key.
        priority=Priority(entry.get("priority",
                                    Priority.INTERACTIVE.value)),
        # v1-v3 entries predate tenancy: absent keys restore as "no
        # adapter, unconstrained" (what every pre-tenant request was).
        adapter=entry.get("adapter"),
        constraint=entry.get("constraint"),
    )
    handle = RequestHandle(
        req, arrival_s=float(now_s) - float(entry.get("elapsed_s", 0.0)))
    handle.tokens = [int(t) for t in entry.get("tokens", [])]
    handle.ttft_s = entry.get("ttft_s")
    # v1-v4 entries predate speculation: absent decodes as zeros (the
    # accounting every pre-speculative stream implicitly had).
    spec = entry.get("spec") or {}
    handle.spec_drafted = int(spec.get("drafted", 0))
    handle.spec_accepted = int(spec.get("accepted", 0))
    handle.state = RequestState.QUEUED
    return handle


def kv_chain_to_wire(tokens: List[int], blocks) -> Dict[str, object]:
    """The replica-to-replica prefix-transfer wire entry (ISSUE 13):
    a cached chain's token ids plus each block's per-leaf K/V payload,
    JSON-safe (raw bytes base64'd with shape/dtype), riding the same
    JSON-line transports the drain snapshot rides. NOT a snapshot
    entry — chains are cache contents, not requests — so it shares the
    snapshot's encoding discipline (one encode/decode pair, here)
    without touching the versioned entry manifest."""
    import base64

    import numpy as np

    return {
        "tokens": [int(t) for t in tokens],
        "blocks": [
            {key: {"shape": list(arr.shape), "dtype": str(arr.dtype),
                   "b64": base64.b64encode(
                       np.ascontiguousarray(arr).tobytes()).decode()}
             for key, arr in block.items()}
            for block in blocks],
    }


def kv_chain_from_wire(entry: Dict[str, object]):
    """Decode :func:`kv_chain_to_wire`: ``(tokens, blocks)`` with each
    block a ``{leaf_key: np.ndarray}`` dict. Shape/dtype are restored
    verbatim; VALIDATION is the importer's job (the engine's host tier
    checks every payload against its own leaf spec and refuses
    mismatches, so a foreign-config chain degrades to a no-op)."""
    import base64

    import numpy as np

    tokens = [int(t) for t in entry.get("tokens", [])]
    blocks = []
    for block in entry.get("blocks", []):
        decoded = {}
        for key, leaf in block.items():
            arr = np.frombuffer(base64.b64decode(leaf["b64"]),
                                dtype=np.dtype(leaf["dtype"]))
            decoded[key] = arr.reshape([int(s) for s in leaf["shape"]])
        blocks.append(decoded)
    return tokens, blocks


def save_snapshot(snapshot: Dict[str, object], path: str) -> None:
    """Atomic write (tmp + rename): a kill mid-drain must leave either
    the previous snapshot or this one, never a torn file."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snapshot, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_snapshot(path: str) -> Dict[str, object]:
    with open(path) as f:
        snapshot = json.load(f)
    version = snapshot.get("version")
    if version not in _READABLE_VERSIONS:
        raise ValueError(
            f"serve drain snapshot version {version!r} unsupported "
            f"(this build reads versions "
            f"{sorted(_READABLE_VERSIONS)})")
    return snapshot


def restored_handles(snapshot: Dict[str, object],
                     now_s: float) -> List[RequestHandle]:
    """Decode every request of a snapshot, preserving its order (the
    drain writes running-first FCFS order, so restore admission keeps
    the original service order).

    Restore reads ONLY ``requests``: the snapshot's optional
    ``telemetry`` block (the draining engine's ring summary,
    `obs/ring.py` — tick-wall percentiles, retries, degraded ticks in
    the final window) is postmortem context for a human reading the
    file, never an input to the fresh engine."""
    return [decode_handle(e, now_s) for e in snapshot["requests"]]
