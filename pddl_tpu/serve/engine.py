"""Continuous-batching online serving engine over the decode path.

`docs/SERVING.md` measured a strong SINGLE-request path (decode scan,
speculative decoding, int8); the ROADMAP's north star is heavy traffic
from many users. The gap between those is this engine: Orca-style
iteration-level scheduling (OSDI '22) — requests join and leave the
running batch at TOKEN granularity instead of waiting for the slowest
member of a fixed batch, which is worth roughly an order of magnitude
of aggregate tokens/s at realistic request mixes (vLLM, SOSP '23).

The slot model, under JAX's fixed-shape discipline:

- ONE resident compiled decode program with a fixed pool of ``S``
  batch slots: the pooled KV cache is ``[S, H_kv, L, D]`` per layer
  with PER-SLOT position counters (``[S]`` int32 — the vector-index
  decode path in `ops/attention.py` / the model families), so every
  slot advances at its own depth inside one fused tick.
- Each ``step()``: (a) ADMIT queued requests into free slots — a
  batch-1 prefill over the right-padded prompt
  (:func:`~pddl_tpu.models.gpt.prefill_row`), inserted into the slot
  (:func:`~pddl_tpu.models.gpt.insert_cache_slot`), first token
  sampled immediately (that's TTFT); (b) one fused DECODE TICK for all
  live slots with per-slot sampling params as batched runtime arrays
  (:func:`~pddl_tpu.models.gpt.sample_logits_batched`); (c) EVICT
  finished slots (eos / length / cancel / deadline) host-side — the
  next admit overwrites the whole cache row, so stale K/V is
  unreachable by construction.
- Exactly FOUR compiled programs (prefill, insert, tick, first-token
  sample), each traced once at ``warmup()`` and never again: prompt
  lengths enter as a traced ``length`` over one fixed padded width,
  slots/positions/sampling params are runtime arrays, and the pooled
  cache is DONATED through insert and tick so the resident buffers are
  reused in place. ``compile_counts()`` exposes the executable counts;
  the suite pins them at 1 after a mixed workload.

Dead slots tick too (fixed shapes — their writes land at parked
position 0 and are overwritten by the next admit); the cost is one
batch row of compute, which is what buys zero recompiles.

Prefix-aware KV reuse (`pddl_tpu/serve/kvcache/`): production traffic
is dominated by shared prompt prefixes (system prompts, few-shot
templates — the vLLM/SGLang observation), so admission consults a
host-side radix index over token ids (`kvcache/radix.py`) backed by a
device-resident pool of fixed-size KV token blocks
(`kvcache/block_pool.py`). On a hit, the matched chain's blocks are
GATHERED (copied) into the request's fresh row cache and only the
uncached SUFFIX is prefilled — in fixed-width chunks, so compute and
the admission budget both scale with the suffix, not the prompt. After
prefill, the prompt's uncovered full blocks are DONATED (copied) back
into the pool under refcounts; both directions copy, so a concurrent
hit never aliases a live slot and LRU eviction never reaches under a
decoding request. Token-exactness is structural: both families' caches
are position-absolute (GPT adds position embeddings before the blocks;
Llama caches post-RoPE keys), so a shared-prefix block is bit-valid
for every request with those prompt tokens.

int8 serving composes exactly like ``generate()``: pass
``param_transform=pddl_tpu.ops.quant.dequantize`` and the int8 tensors
are what lives in HBM, dequantized inside the compiled programs (the
prefix-cache programs included — what the pool stores is K/V, which
int8 weight storage never touches).

Ring-cache (rolling SWA) models are refused for now: slot reuse over a
ring whose slots already wrapped needs per-slot wrap bookkeeping this
engine doesn't carry yet. Full-length-cache models (GPT, Llama, SWA
with ``window >= max_len``) are all eligible.

Fault tolerance (`serve/faults.py`, `serve/drain.py`,
`docs/OPERATIONS.md` § "Failure modes & recovery"): every device
dispatch goes through one guarded boundary. Transient device errors
retry with bounded exponential backoff; when retries run out (or a
real error may have consumed a donated buffer) the affected slots'
KV is declared LOST and the requests REPLAY — the prompt re-prefills
through the normal admission path and the already-emitted tokens are
re-fed one per fused tick (known token in, sampled output discarded)
until the stream's live edge is rebuilt, which is token-exact because
the caches are position-absolute and costs no new compiled program in
either prefix mode. RESOURCE_EXHAUSTED flips the engine DEGRADED:
prefix-cache donations stop, unpinned pool blocks flush, serving
continues on the cold path, and the cache re-arms after a cool-down.
A request whose replays exceed ``max_replays`` fails terminally
(``FinishReason.ERROR``) instead of crash-looping the engine. SIGTERM
(via ``install_drain_handler``) stops admission and snapshots every
queued + running request's host state to disk; a fresh engine
``restore()``s the snapshot and resumes each stream token-exactly
through the same replay machinery.

Speculative serving (``spec_k > 0``; ISSUE 12 / ROADMAP item 3):
the fused tick becomes a per-slot DRAFT/VERIFY window — Leviathan et
al.'s speculative decoding lifted into Orca-style iteration-level
scheduling. Each step a ``draft`` program proposes up to ``spec_k``
tokens per slot (the shared n-gram drafter from
`models/speculative.py` by default — zero extra weights — or a small
draft model whose KV rides the same paged block pool as a second
cache tree), and ONE batched ``verify`` dispatch runs the target
model over the ``[S, spec_k+1]`` block at per-slot positions through
the same multi-token machinery chunked prefill uses. Greedy slots
accept the longest matching draft prefix — up to ``spec_k+1`` tokens
from one tick, each the argmax of the true model given the true
prefix, so the stream is token-exact vs non-speculative greedy —
while sampled slots accept zero drafts and tick one token exactly as
before. Accepted length comes back as a runtime ``[S]`` int32 array:
mixed accept counts across the batch are DATA, never a recompile,
exactly the invariant the grammar masks and LoRA ids already hold.
Rejected draft suffixes roll back by stamping the host-side position
counters (and, paged, by the table discipline): the stale K/V sits
beyond the counter where the prefix-bounded sweep never reads it and
the next window overwrites it — a rewind is a counter stamp, never a
KV copy. Grammar-constrained slots speculate under the same FSM
tables (per-position masks over the draft path; the per-slot FSM
advances by the ACCEPTED length only), and replaying slots re-feed
known tokens ``spec_k+1`` per window, so fault recovery and
drain/restore/migration of speculative streams stay token-exact AND
speed up by the same factor.

Tiered KV cache (``host_tier=...``; ISSUE 13 / ROADMAP item 4,
`serve/kvcache/hosttier.py`): millions of users means the warm prefix
working set exceeds HBM by orders of magnitude, and the radix index's
LRU reclaim used to answer that by freeing — the fleet re-prefilled
any prefix that fell out of the pool. With a host tier armed, eviction
becomes a POLICY DECISION: reuse-worthy victims (scored by chain
length; recency rides the LRU order itself) spill their K/V D2H into a
byte-budgeted pinned-host pool under a second token-keyed index, and
an admission that misses HBM but hits the host tier PROMOTES the chain
back — one ``host_promote`` H2D scatter (riding
``ops.attention.cache_blocks_scatter`` over the donated pool, fixed
padded shapes, zero recompiles) charged against the prefill-token
budget through the scheduler's tenancy-aware ``cost_fn`` exactly like
a cold adapter load, with fault/cancel/preempt unwind releasing the
host-tier pins through the same discipline device chains use. The
demotion D2H rides an eager ``cache_blocks_gather`` of the one dying
block; degraded (post-OOM) mode bypasses the tier in BOTH directions
(spilling during an OOM response would defeat the shedding). A
disabled tier (``host_tier=None`` or byte budget 0) leaves the engine
bit-identical to the untiered one — same programs, same tokens.
"""

from __future__ import annotations

import signal
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pddl_tpu.models.gpt import (
    _decode_cache_shapes,
    insert_cache_slot,
    lm_head_logits,
    prefill_row,
    prefill_row_features,
    prefill_row_from,
    sample_logits_batched,
    set_cache_block_tables,
    set_cache_positions,
    slot_decode_cache,
)
from pddl_tpu.models.speculative import ngram_drafts
from pddl_tpu.obs.ring import TelemetryRing
from pddl_tpu.ops.attention import cache_blocks_gather, cache_blocks_scatter
from pddl_tpu.ops.lora import adapter_pool_load, batched_lora_delta
from pddl_tpu.obs.trace import NULL_TRACER
from pddl_tpu.serve import drain as drain_io
from pddl_tpu.serve.faults import (
    InjectedResourceExhausted,
    InjectedTransientError,
    classify,
)
from pddl_tpu.serve.kvcache import (
    HostTierCache,
    HostTierConfig,
    RadixPrefixCache,
    donate_prefix_blocks,
    gather_prefix_into_row,
    kv_block_pool,
    paged_decode_cache,
    pool_nbytes,
)
from pddl_tpu.serve.metrics import ServeMetrics
from pddl_tpu.serve.request import (
    FinishReason,
    Priority,
    QueueFull,
    Request,
    RequestHandle,
    RequestState,
    SamplingParams,
)
from pddl_tpu.serve.scheduler import SLOScheduler
from pddl_tpu.serve.tenant import (
    AdapterPool,
    AdapterPoolExhausted,
    AdapterRegistry,
    compile_constraint,
    constraint_key,
)


class _SlotStateLost(RuntimeError):
    """Internal escalation: a device call outlasted its retry budget
    (or failed in a way that may have consumed a donated buffer), so
    whatever slot/row state it touched must be rebuilt, not reused.
    Never escapes the engine — admission turns it into a request
    replay/failure, the tick into a full live-slot replay.
    ``consumed`` names the resident resource (``cache``/``row``/
    ``pool``) a REAL mid-dispatch error may have eaten through
    donation; ``None`` for injected faults, which fire before the
    program runs and consume nothing."""

    def __init__(self, site: str, cause: BaseException,
                 consumed: Optional[str] = None):
        self.site = site
        self.consumed = consumed
        super().__init__(f"device call {site!r} lost after retries: {cause}")


# Which resident donated tree each site's program consumes on dispatch
# (prefill and sample_first donate nothing). A REAL error from one of
# these can leave the donated input deleted, so it is never re-dispatched
# — the escalation path rebuilds the resource instead.
_DONATED_BY_SITE = {
    "tick": "cache", "insert": "cache",
    "gather": "row", "chunk_prefill": "row", "chunk_prefill_wide": "row",
    "donate": "pool",
}

# The PAGED engine's site map: the pool IS the cache, and every paged
# program (tick and both chunk widths) donates it — a real mid-dispatch
# error from any of them may have consumed the one tree holding every
# live stream's KV, so recovery is always the full pool rebuild + live
# -slot replay.
_PAGED_DONATED_BY_SITE = {
    "tick": "pool", "chunk_prefill": "pool", "chunk_prefill_wide": "pool",
}

# Speculative-engine additions (`spec_k > 0`): the ``verify`` program
# replaces ``tick`` and donates the same resident tree; the draft-MODEL
# program and its admission chunk donate the draft cache tree, which in
# paged mode lives in the same block-id space as the pool — a consumed
# draft tree therefore recovers exactly like a consumed pool (full
# paged-world rebuild + live-slot replay). The n-gram ``draft`` program
# donates nothing and is deliberately absent here — a lost draft call
# degrades to fallback drafts, never to a KV rebuild — so the ``draft``
# entry is stamped PER ENGINE (only when a draft model is drafting).
_SPEC_DONATED_ROW = {"verify": "cache"}
_SPEC_DONATED_PAGED = {"verify": "pool", "draft_prefill": "pool"}


class ServeEngine:
    """Online multiplexer of generate requests onto one decode program.

    Args:
      model: a non-decode GPT/Llama (anything ``generate()``-compatible
        with a full-length KV cache); the decode twin is cloned here.
      variables: ``{"params": ...}`` — kept on device, always a jit
        ARGUMENT (new same-shape checkpoints never recompile).
      max_slots: the batch-slot pool size ``S`` — the max concurrent
        requests in one fused tick.
      prefill_len: the fixed padded prompt width (every prompt must fit;
        one compiled prefill serves all lengths). Defaults to
        ``model.max_len // 2``.
      max_queue_depth / prefill_token_budget / aging_s: admission
        knobs, see `scheduler.py` — the scheduler pops priority-first
        (interactive > batch > best_effort), EDF within a class, with
        ``aging_s`` of queue wait promoting a request one class (the
        anti-starvation bound).
      prefill_slice_tokens: chunked-prefill FAIRNESS — when set, an
        admission prefills at most this many prompt tokens per
        ``step()`` (narrow chunks only; the wide program is skipped)
        and the fused decode tick runs between slices, so one 32k cold
        prompt is time-sliced against the running streams instead of
        stalling every next token behind its whole prefill. Requires
        the prefix-cache engine (the chunk programs ARE the slicing
        mechanism); ``None`` (default) keeps whole-prompt admission.
      eos_token: optional stop token (included in the stream when hit).
      param_transform: the ``generate()`` int8 hook — applied INSIDE the
        compiled programs (:mod:`pddl_tpu.ops.quant`).
      rng: sampling key, split once per tick and per admission (the
        fused tick draws for every row and greedy rows discard the
        draw — fixed work, no recompile — so the key stream advances
        even for an all-greedy workload).
      clock: injectable monotonic clock (tests drive deadlines with a
        fake one).
      prefix_cache_blocks: KV block-pool size (block 0 is a reserved
        scratch sink). ``None`` (default) auto-sizes to hold about two
        full prompts per slot — or disables caching cleanly when no
        block can ever fit (``prefix_block_size >= prefill_len``, e.g.
        very short engines; check ``prefix_cache_enabled``). ``0``
        disables prefix caching entirely (the original four-program
        engine). An EXPLICIT size demands a workable config: it
        requires ``prefill_len + prefix_chunk <= max_len`` (chunk
        positions must never clamp) and a usable block size —
        violations then raise rather than silently degrade.
      prefix_block_size: tokens per shared KV block — the reuse (and
        radix-tree) granularity. Smaller blocks match more of a prefix
        but cost more pool rows per prompt.
      prefix_chunk: suffix-prefill chunk width (one compiled program;
        admission prefills ``ceil(suffix/chunk)`` chunks, so prefill
        work scales with the UNCACHED suffix). Default
        ``max(prefix_block_size, prefill_len // 4)``.
      paged: TRUE PAGED ATTENTION (vLLM PagedAttention / SGLang
        RadixAttention composed): the resident slot cache disappears —
        every stream's K/V lives in the block pool and decode reads it
        through a per-slot ``[S, T]`` block table
        (:func:`~pddl_tpu.ops.attention.paged_decode_attention`; the
        Pallas kernel on TPU, the chunked jnp oracle elsewhere). A
        prefix hit PINS the matched blocks in place instead of
        copying them into a row (admission cost loses the pool→slot
        gather and the insert copy), donation becomes a pure refcount
        hand-off of blocks the prefill already wrote, and a shared
        prefix's KV exists ONCE in HBM no matter how many live slots
        reference it — which is what roughly doubles effective cache
        capacity at high prefix sharing. Requires the prefix machinery
        (``prefix_cache_blocks != 0``); with ``None`` the pool
        auto-sizes to hold every slot at ``max_len`` plus shared
        headroom, and an explicit size must cover
        ``max_slots * ceil(max_len/block_size) + 1`` so a live stream
        can never starve for a writable block. Token-exact against the
        resident-row engine (the oracle) for every family/quant
        config; same drain/replay/chaos contracts.
      host_tier: TIERED KV CACHE (module docstring, ISSUE 13): a
        :class:`~pddl_tpu.serve.kvcache.HostTierConfig` (or a plain
        int byte budget) arming the host-RAM spill tier under the
        radix index — LRU eviction demotes reuse-worthy chains D2H
        instead of freeing them, and admission promotes host-tier hits
        back through the ``host_promote`` program, charged against the
        prefill budget at ``promote_tokens_per_block`` per block.
        Requires the prefix machinery; refused (for now) alongside
        ``spec_draft_model`` — a promoted block carries target K/V
        only, and the draft tree's twin block would be junk. ``None``
        (default) or byte budget 0 disables the tier with a
        bit-identical engine (same compiled-program set, same tokens —
        the cold-path contract `tests/test_kv_tier.py` pins).
      fault_plan: optional :class:`~pddl_tpu.serve.faults.FaultPlan`
        consulted before every device dispatch (chaos tests, fault
        benches). ``None`` in production — real device errors take the
        same recovery paths, the plan only makes them injectable.
      max_retries: transient-error retries per device call before the
        touched slot state is declared lost and requests replay.
      retry_backoff_s: base of the bounded exponential backoff
        (``base * 2**attempt``) between retries.
      backoff_sleep: how the backoff waits (default ``time.sleep``;
        tests pass a no-op or a fake-clock advancer).
      max_replays: slot-state rebuilds per request before it fails
        terminally with ``FinishReason.ERROR``.
      degraded_cooldown_s: how long an OOM keeps the prefix cache
        degraded (donations off) before re-arming; a repeat OOM inside
        the window pushes the re-arm out again.
      preempt_cap: times one BEST_EFFORT stream may be parked (slot
        evicted, requeued, later resumed token-exactly via replay
        admission) to free a slot for queued ``interactive`` work;
        ``0`` disables preemption. The cap is what keeps a paused
        stream from thrashing forever under sustained pressure.
      tenant: optional :class:`~pddl_tpu.serve.tenant.TenantConfig` —
        MULTI-TENANT serving (ISSUE 9, `serve/tenant/`): per-request
        LoRA adapters from a paged device pool (per-slot int32 adapter
        ids gathered inside the fused tick — one compiled program for
        every tenant mix; admission pins the adapter row like a prefix
        chain and charges a cold load against the prefill budget) and
        grammar/JSON-schema-constrained decoding (a host-side token
        FSM per request whose per-state allow mask is stamped as a
        runtime ``[S, V]`` array ahead of the batched sampler; FSM
        state re-derives from emitted tokens, so replay/drain/
        migration stay token-exact). The v1 adaptation target is the
        LM HEAD, which keeps KV adapter-invariant — prefix/paged KV
        sharing stays valid ACROSS tenants. ``None`` (default) compiles
        the plain programs: a non-tenant engine pays nothing.
      spec_k: SPECULATIVE SERVING (module docstring, ISSUE 12): draft
        up to ``spec_k`` tokens per engaged slot per step and verify
        them in one batched ``[S, spec_k+1]`` wide-logits dispatch —
        greedy slots emit up to ``spec_k + 1`` tokens per tick,
        token-exact vs the non-speculative greedy stream; sampled
        slots keep ticking one token. ``0`` (default) compiles the
        classic one-token tick — a non-speculative engine pays
        nothing. Accepted lengths are runtime ``[S]`` data, so mixed
        accept counts never recompile. Replays/restores re-feed known
        tokens ``spec_k + 1`` per window through the same machinery.
      spec_ngram: the n-gram drafter's lookup key length (the shared
        :func:`~pddl_tpu.models.speculative.ngram_drafts` definition —
        one drafter for the one-shot and serving paths).
      spec_draft_model / spec_draft_variables: optional DRAFT MODEL
        (paged engines only): a small ``generate()``-compatible model
        whose per-slot KV rides the same block pool as a second cache
        tree — same block ids, same tables, same radix sharing/dedup
        (draft K/V is position-absolute and token-pure exactly like
        the target's, so shared-prefix blocks stay bit-valid for both
        trees). Admission chunk-prefills the prompt through it
        (``draft_prefill`` site, narrow chunks); each step it drafts
        ``spec_k`` tokens autoregressively (known replay tokens are
        teacher-forced so its cache stays exact through recovery).
        ``None`` keeps the zero-weight n-gram drafter.
      tracer: optional per-request tracer
        (:class:`~pddl_tpu.obs.trace.RequestTracer`); ``None`` installs
        the no-op :data:`~pddl_tpu.obs.trace.NULL_TRACER` — tracing
        disabled costs nothing (no per-tick allocation, no device
        sync, pinned by `tests/test_obs.py`). Swap at runtime with
        :meth:`set_tracer`.
      telemetry_capacity: per-tick telemetry ring size
        (:class:`~pddl_tpu.obs.ring.TelemetryRing` on
        ``self.telemetry``): one record per ``step()`` with occupancy,
        queue depth, tokens, retries, and per-site dispatch wall time;
        the oldest record is overwritten, so memory is bounded forever.
    """

    def __init__(self, model, variables, *, max_slots: int = 8,
                 prefill_len: Optional[int] = None,
                 max_queue_depth: int = 64,
                 prefill_token_budget: Optional[int] = None,
                 aging_s: Optional[float] = 30.0,
                 prefill_slice_tokens: Optional[int] = None,
                 eos_token: Optional[int] = None,
                 param_transform=None, rng=None,
                 clock=time.monotonic,
                 prefix_cache_blocks: Optional[int] = None,
                 prefix_block_size: int = 8,
                 prefix_chunk: Optional[int] = None,
                 paged: bool = False,
                 host_tier=None,
                 fault_plan=None, max_retries: int = 3,
                 retry_backoff_s: float = 0.02,
                 backoff_sleep=time.sleep,
                 max_replays: int = 3,
                 degraded_cooldown_s: float = 5.0,
                 preempt_cap: int = 2,
                 tenant=None,
                 spec_k: int = 0, spec_ngram: int = 3,
                 spec_draft_model=None, spec_draft_variables=None,
                 tracer=None, telemetry_capacity: int = 512):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if getattr(model, "uses_ring_cache", False):
            raise NotImplementedError(
                "the serving engine needs full-length KV caches; "
                f"sliding_window={model.sliding_window} allocates a "
                "rolling ring cache whose slot reuse is not supported yet")
        self.model = model
        self.max_slots = int(max_slots)
        self.prefill_len = int(prefill_len if prefill_len is not None
                               else model.max_len // 2)
        if not 1 <= self.prefill_len <= model.max_len:
            raise ValueError(
                f"prefill_len {self.prefill_len} outside [1, "
                f"{model.max_len}]")
        self.eos_token = eos_token
        self._clock = clock
        self._params = variables["params"]
        self._dec = model.clone(decode=True)
        self._rng = rng if rng is not None else jax.random.key(0)
        self.scheduler = SLOScheduler(
            max_queue_depth=max_queue_depth,
            prefill_token_budget=prefill_token_budget,
            aging_s=aging_s)
        self.metrics = ServeMetrics()

        # Observability (`pddl_tpu/obs/`): the tracer defaults to the
        # shared no-op object, so a disabled engine pays one method
        # call per hook and allocates nothing; the telemetry ring is
        # always on (a dict of scalars per tick, bounded capacity).
        self._tracer = NULL_TRACER
        self.telemetry = TelemetryRing(telemetry_capacity)
        self._site_wall: Dict[str, float] = {}
        self._last_wall_s = 0.0
        self._cur_step = 0

        # Resilience state (`serve/faults.py` taxonomy; docs/OPERATIONS
        # § "Failure modes & recovery").
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        if max_replays < 0:
            raise ValueError(f"max_replays must be >= 0, got {max_replays}")
        self._faults = fault_plan
        self._max_retries = int(max_retries)
        self._retry_backoff_s = float(retry_backoff_s)
        self._backoff_sleep = backoff_sleep
        self._max_replays = int(max_replays)
        self._degraded_cooldown_s = float(degraded_cooldown_s)
        self._degraded = False
        self._degraded_entered_s = 0.0
        self._degraded_until_s = 0.0
        self._step_idx = 0
        # Handles popped from the queue but not yet slotted: a kill
        # mid-admission must not lose them from the drain snapshot.
        self._admitting: Deque[RequestHandle] = deque()
        self._drain_flag = False
        self._drained = False
        self._drain_path: Optional[str] = None
        self._snapshot: Optional[Dict[str, object]] = None
        self._prev_handlers: Dict[int, object] = {}

        # Prefix-cache configuration (static — the compiled programs'
        # shapes derive from these).
        bs = int(prefix_block_size)
        if bs < 1:
            raise ValueError(
                f"prefix_block_size must be >= 1, got {bs}")
        # A prefix hit must leave >= 1 suffix token to produce the
        # sampled-from logits, so the longest matchable chain is
        # (prefill_len - 1) tokens, floor-blocked.
        self._match_cap = (self.prefill_len - 1) // bs
        self._donate_cap = self.prefill_len // bs
        chunk = (int(prefix_chunk) if prefix_chunk is not None
                 else max(bs, self.prefill_len // 4))
        self._paged = bool(paged)
        # Paged mode: T table entries cover every position a stream can
        # reach; the pool must hold at least one writable block per
        # live position-block plus the scratch sink, or a decode tick
        # could starve mid-stream.
        self._table_width = -(-model.max_len // bs)
        paged_floor = self.max_slots * self._table_width + 1
        if prefix_cache_blocks is None:
            if self._paged:
                # Live worst case + the same shared-cache headroom the
                # copy engine's default bought (two prompts per slot).
                pool_blocks = (paged_floor
                               + 2 * self.max_slots * max(self._donate_cap,
                                                          1))
            else:
                pool_blocks = (2 * self.max_slots * max(self._donate_cap, 1)
                               + 1) if self._match_cap >= 1 else 0
        else:
            pool_blocks = int(prefix_cache_blocks)
        self._prefix_on = pool_blocks > 0
        if self._paged:
            if not self._prefix_on:
                raise ValueError(
                    "paged=True needs the block-pool machinery; "
                    "prefix_cache_blocks=0 disables it")
            if pool_blocks < paged_floor:
                raise ValueError(
                    f"paged=True needs prefix_cache_blocks >= "
                    f"{paged_floor} (max_slots * ceil(max_len/"
                    f"block_size) + scratch) so live streams can never "
                    f"starve for a writable block; got {pool_blocks}")
        if self._prefix_on:
            if self._match_cap < 1:
                raise ValueError(
                    f"prefix_block_size {bs} leaves no cacheable block "
                    f"under prefill_len {self.prefill_len} (need "
                    f"block_size < prefill_len); pass "
                    "prefix_cache_blocks=0 to disable prefix caching")
            if not 1 <= chunk or self.prefill_len + chunk > model.max_len:
                raise ValueError(
                    f"prefix_chunk {chunk} needs 1 <= chunk and "
                    f"prefill_len + chunk <= max_len "
                    f"({self.prefill_len} + {chunk} > {model.max_len}): "
                    "a chunk starting at the deepest cached offset would "
                    "clamp its positions")
            if pool_blocks < 2:
                raise ValueError(
                    f"prefix_cache_blocks must be >= 2 (block 0 is the "
                    f"reserved scratch sink), got {pool_blocks}")
        self.prefix_block_size = bs
        self._chunk = chunk

        # Chunked-prefill fairness: at most `prefill_slice_tokens` of
        # prompt prefill per step(), the decode tick interleaved
        # between slices. One slice in flight at a time (the resident
        # row cache is the single admission pipeline); `_slice` holds
        # its resumable state across steps.
        if prefill_slice_tokens is not None:
            if not self._prefix_on:
                raise ValueError(
                    "prefill_slice_tokens requires the prefix-cache "
                    "engine (its chunk programs are the slicing "
                    "mechanism); leave prefix_cache_blocks enabled or "
                    "unset prefill_slice_tokens")
            if prefill_slice_tokens < 1:
                raise ValueError(
                    f"prefill_slice_tokens must be >= 1, got "
                    f"{prefill_slice_tokens}")
        self._slice_tokens = (int(prefill_slice_tokens)
                              if prefill_slice_tokens is not None else None)
        self._slice: Optional[Dict[str, object]] = None
        self._slice_budget_left = 0
        if preempt_cap < 0:
            raise ValueError(f"preempt_cap must be >= 0, got {preempt_cap}")
        self._preempt_cap = int(preempt_cap)

        # Speculative serving (module docstring): static draft config —
        # the verify width spec_k+1 is a compiled shape, everything
        # per-slot (drafts, accepted lengths, caps, forced re-feeds)
        # is runtime data.
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        self._spec_k = int(spec_k)
        self._spec_on = self._spec_k > 0
        self._spec_ngram = int(spec_ngram)
        self._draft_on = spec_draft_model is not None
        if self._spec_on:
            if self._spec_ngram < 1:
                raise ValueError(
                    f"spec_ngram must be >= 1, got {spec_ngram}")
            # The host-side token history every drafter reads: prompt +
            # emitted tokens per slot, the serving twin of the one-shot
            # path's token buffer (positions past the live edge hold
            # junk, which verification rejects by construction).
            self._hist = np.zeros((self.max_slots, model.max_len),
                                  np.int32)
        if self._draft_on:
            if not self._spec_on:
                raise ValueError(
                    "spec_draft_model needs spec_k >= 1 (the draft "
                    "model only exists to fill the verify window)")
            if not self._paged:
                raise ValueError(
                    "spec_draft_model rides the paged KV block pool as "
                    "a second cache tree; pass paged=True (the n-gram "
                    "drafter serves resident-row engines)")
            if spec_draft_variables is None:
                raise ValueError(
                    "spec_draft_model needs spec_draft_variables "
                    "({'params': ...})")
            if spec_draft_model.vocab_size != model.vocab_size:
                raise ValueError(
                    f"draft model vocab {spec_draft_model.vocab_size} "
                    f"!= target vocab {model.vocab_size}")
            if spec_draft_model.max_len < model.max_len:
                raise ValueError(
                    f"draft model max_len {spec_draft_model.max_len} < "
                    f"target max_len {model.max_len}: the draft cache "
                    "must cover every position a stream can reach")
            if getattr(spec_draft_model, "uses_ring_cache", False):
                raise NotImplementedError(
                    "draft models with rolling ring caches are not "
                    "supported (same slot-reuse constraint as the "
                    "target)")
            self._ddec = spec_draft_model.clone(decode=True)
            self._dparams = spec_draft_variables["params"]
        elif spec_draft_variables is not None:
            raise ValueError(
                "spec_draft_variables without spec_draft_model")

        # Multi-tenant state (`serve/tenant/`): the host-side adapter
        # pool bookkeeping, the device factor pools, per-slot adapter
        # rows, per-slot grammar masks, and the FSM cache. All absent
        # (None) on a plain engine — tenancy is opt-in per engine, so
        # existing deployments compile the exact same programs.
        self._tenant = tenant
        self._tenant_on = tenant is not None
        if self._tenant_on:
            registry = tenant.registry
            if registry is None:
                registry = AdapterRegistry(model.embed_dim,
                                           model.vocab_size)
                tenant.registry = registry
            if (registry.embed_dim != model.embed_dim
                    or registry.vocab_size != model.vocab_size):
                raise ValueError(
                    f"adapter registry shape ({registry.embed_dim}, "
                    f"{registry.vocab_size}) does not match the model "
                    f"({model.embed_dim}, {model.vocab_size})")
            if tenant.token_strings is not None \
                    and len(tenant.token_strings) != model.vocab_size:
                raise ValueError(
                    f"token_strings has {len(tenant.token_strings)} "
                    f"entries; the grammar vocabulary must cover every "
                    f"token id (vocab_size {model.vocab_size})")
            pool_rows = (int(tenant.adapter_pool_slots)
                         if tenant.adapter_pool_slots is not None
                         else self.max_slots + 4)
            if pool_rows < self.max_slots + 1:
                raise ValueError(
                    f"adapter_pool_slots {pool_rows} is below the live-"
                    f"mix floor max_slots + 1 = {self.max_slots + 1} "
                    "(every slot on a distinct adapter plus the "
                    "identity row 0); see docs/OPERATIONS.md 'Adapter "
                    "pool sizing'")
            self._registry = registry
            self._apool = AdapterPool(pool_rows)
            self._apool_a = jnp.zeros(
                (pool_rows, model.embed_dim, registry.rank), jnp.float32)
            self._apool_b = jnp.zeros(
                (pool_rows, registry.rank, model.vocab_size), jnp.float32)
            self._arow = np.zeros(self.max_slots, np.int32)
            self._masks = np.ones((self.max_slots, model.vocab_size),
                                  np.bool_)
            # The tick's mask arg stays DEVICE-resident and restages
            # only when a host-side row changed (`_masks_dirty`): an
            # adapters-only tenant mix (or idle constraints) then pays
            # zero per-tick mask transfer — at a real vocab the [S, V]
            # bool array is hundreds of KB per step otherwise.
            self._masks_dev = None
            self._masks_dirty = True
            # Speculative engines additionally carry PER-POSITION masks
            # [S, spec_k+1, V] for the verify block (the FSM states
            # along each slot's draft path, stamped by the host walk
            # each tick); same device-staging discipline as `_masks`.
            self._masks_w = (np.ones(
                (self.max_slots, self._spec_k + 1, model.vocab_size),
                np.bool_) if self._spec_on else None)
            self._masks_w_dev = None
            self._masks_w_dirty = True
            self._fsms: List[Optional[tuple]] = [None] * self.max_slots
            self._fsm_cache: Dict[str, object] = {}
        else:
            self._registry = None
            self._apool = None

        # One handle per occupied slot; all other per-slot state lives
        # in the arrays below (positions) or is derivable from the
        # handle (tokens emitted = len(handle.tokens)) — no duplicated
        # bookkeeping to keep in lockstep.
        self._slots: List[Optional[RequestHandle]] = [None] * self.max_slots
        # The radix node each occupied slot pinned at admission
        # (refcount released at evict).
        self._slot_nodes: List[Optional[object]] = [None] * self.max_slots
        # Engine-owned per-slot state, stamped into the programs each
        # tick (positions are authoritative HERE, not in the cache —
        # the tick program overwrites the cache's counters on entry).
        self._positions = np.zeros(self.max_slots, np.int32)
        self._tokens = np.zeros(self.max_slots, np.int32)
        self._temps = np.zeros(self.max_slots, np.float32)
        self._top_ks = np.zeros(self.max_slots, np.int32)
        self._top_ps = np.full(self.max_slots, 2.0, np.float32)

        dec, pt = self._dec, param_transform

        def _prefill(params, prompt, length):
            return prefill_row(dec, params, prompt, length,
                               param_transform=pt)

        def _gather(pool, block_ids, row):
            # Overwrite the RESIDENT row cache's prefix region
            # [0, match_cap*bs) with the matched chain (row donated —
            # the admission pipeline reuses one set of row buffers).
            # Everything beyond is stale: scratch-padded gather junk,
            # or the previous admission's K/V — all of it either
            # overwritten by the suffix chunks or parked beyond the
            # position counter the slot insert stamps, exactly the
            # invariant the padded one-shot prefill already relies on.
            return gather_prefix_into_row(pool, row, block_ids)

        def _chunk_prefill(params, row, tokens, length, start):
            # One fixed-width suffix chunk continuing the row cache at
            # global offset `start` (all of length/start runtime values).
            return prefill_row_from(dec, params, tokens, length, row,
                                    start, param_transform=pt)

        def _chunk_prefill_wide(params, row, tokens, length, start):
            # The same computation at the wide width — a DISTINCT
            # function object, so its jit cache (and compile_counts
            # entry) never shares entries with the narrow program's
            # (same reason _insert is a per-engine closure).
            return prefill_row_from(dec, params, tokens, length, row,
                                    start, param_transform=pt)

        def _donate(pool, row, block_ids, start_block):
            return donate_prefix_blocks(pool, row, block_ids, start_block)

        def _tick(params, cache, positions, tokens, temps, top_ks, top_ps,
                  rng):
            rng, sub = jax.random.split(rng)
            cache = set_cache_positions(cache, positions)
            logits, mutated = dec.apply(
                {"params": (pt(params) if pt is not None else params),
                 "cache": cache},
                tokens[:, None], train=False, mutable=["cache"])
            nxt = sample_logits_batched(
                sub, logits[:, -1], temperature=temps, top_k=top_ks,
                top_p=top_ps)
            return mutated["cache"], nxt, rng

        def _sample_first(logits, temp, top_k, top_p, rng):
            rng, sub = jax.random.split(rng)
            tok = sample_logits_batched(sub, logits, temperature=temp,
                                        top_k=top_k, top_p=top_p)
            return tok, rng

        def _insert(cache, row_cache, slot, position):
            # A per-engine closure (not the bare module-level function):
            # jax.jit keyed on the same function object would SHARE its
            # tracing cache across engines, making compile_counts()
            # report other instances' pool shapes.
            return insert_cache_slot(cache, row_cache, slot, position)

        # --- paged program bodies (see the `paged` arg docs) ---
        # Every paged program stamps the engine-owned positions/tables
        # on entry and restores CANONICAL placeholders (scalar counter,
        # [1,1] table) on exit, so the donated resident tree keeps one
        # structure across the fused tick and the batch-1 chunk widths
        # — shape-stable donation is what keeps the set at zero
        # recompiles.
        def _canon_paged(cache):
            cache = set_cache_positions(cache, jnp.zeros((), jnp.int32))
            return set_cache_block_tables(cache,
                                          jnp.zeros((1, 1), jnp.int32))

        def _tick_paged(params, cache, positions, tables, tokens, temps,
                        top_ks, top_ps, rng):
            rng, sub = jax.random.split(rng)
            cache = set_cache_positions(cache, positions)
            cache = set_cache_block_tables(cache, tables)
            logits, mutated = dec.apply(
                {"params": (pt(params) if pt is not None else params),
                 "cache": cache},
                tokens[:, None], train=False, mutable=["cache"])
            nxt = sample_logits_batched(
                sub, logits[:, -1], temperature=temps, top_k=top_ks,
                top_p=top_ps)
            return _canon_paged(mutated["cache"]), nxt, rng

        def _chunk_paged(params, cache, tokens, length, start, table):
            cache = set_cache_block_tables(cache, table)
            cache, logits = prefill_row_from(dec, params, tokens, length,
                                             cache, start,
                                             param_transform=pt)
            return _canon_paged(cache), logits

        def _chunk_paged_wide(params, cache, tokens, length, start, table):
            # Distinct function object for a distinct compile_counts
            # entry, like the row-mode wide chunk.
            cache = set_cache_block_tables(cache, table)
            cache, logits = prefill_row_from(dec, params, tokens, length,
                                             cache, start,
                                             param_transform=pt)
            return _canon_paged(cache), logits

        # --- tenant program bodies (the `tenant` arg docs) ---
        # Same SITES, swapped bodies: the model runs ``features_only``,
        # the LM head applies outside the module (`gpt.lm_head_logits`
        # — op-for-op identical, so a no-adapter slot is bit-exact vs
        # the base model), per-slot LoRA deltas gather from the device
        # factor pools by runtime int32 row ids, and grammar masks land
        # as a runtime [B, V] bool array right before the batched
        # sampler (all-True rows pass logits through bitwise). Nothing
        # here varies compiled-program shape — the zero-recompile pin
        # holds over every tenant mix.
        if self._tenant_on:
            def _sample_first_t(logits, mask, temp, top_k, top_p, rng):
                rng, sub = jax.random.split(rng)
                tok = sample_logits_batched(
                    sub, jnp.where(mask, logits, -jnp.inf),
                    temperature=temp, top_k=top_k, top_p=top_p)
                return tok, rng

            def _adapter_load(pool_a, pool_b, row, a, b):
                # Per-engine closure (the _insert rationale): a shared
                # module-level jit would mix pool shapes across engines
                # in compile_counts.
                return adapter_pool_load(pool_a, pool_b, row, a, b)

            def _tick_body(params, cache, tokens, temps, top_ks, top_ps,
                           masks, pool_a, pool_b, arows, sub):
                p2 = pt(params) if pt is not None else params
                feats, mutated = dec.apply(
                    {"params": p2, "cache": cache},
                    tokens[:, None], train=False, mutable=["cache"],
                    features_only=True)
                logits = lm_head_logits(dec, p2, feats)[:, -1]
                logits = logits + batched_lora_delta(
                    feats[:, -1], pool_a, pool_b, arows)
                nxt = sample_logits_batched(
                    sub, jnp.where(masks, logits, -jnp.inf),
                    temperature=temps, top_k=top_ks, top_p=top_ps)
                return mutated["cache"], nxt

            def _tick_t(params, cache, positions, tokens, temps, top_ks,
                        top_ps, masks, pool_a, pool_b, arows, rng):
                rng, sub = jax.random.split(rng)
                cache = set_cache_positions(cache, positions)
                cache, nxt = _tick_body(params, cache, tokens, temps,
                                        top_ks, top_ps, masks, pool_a,
                                        pool_b, arows, sub)
                return cache, nxt, rng

            def _tick_paged_t(params, cache, positions, tables, tokens,
                              temps, top_ks, top_ps, masks, pool_a,
                              pool_b, arows, rng):
                rng, sub = jax.random.split(rng)
                cache = set_cache_positions(cache, positions)
                cache = set_cache_block_tables(cache, tables)
                cache, nxt = _tick_body(params, cache, tokens, temps,
                                        top_ks, top_ps, masks, pool_a,
                                        pool_b, arows, sub)
                return _canon_paged(cache), nxt, rng

            def _lora1(last, last_feats, pool_a, pool_b, aid):
                return last + batched_lora_delta(
                    last_feats, pool_a, pool_b,
                    jnp.full((1,), aid, jnp.int32))

            def _prefill_t(params, prompt, length, aid, pool_a, pool_b):
                cache, last, lf = prefill_row_features(
                    dec, params, prompt, length, None, 0,
                    param_transform=pt)
                return cache, _lora1(last, lf, pool_a, pool_b, aid)

            def _chunk_t(params, row, tokens, length, start, aid,
                         pool_a, pool_b):
                row, last, lf = prefill_row_features(
                    dec, params, tokens, length, row, start,
                    param_transform=pt)
                return row, _lora1(last, lf, pool_a, pool_b, aid)

            def _chunk_wide_t(params, row, tokens, length, start, aid,
                              pool_a, pool_b):
                # Distinct function object (wide-program discipline).
                row, last, lf = prefill_row_features(
                    dec, params, tokens, length, row, start,
                    param_transform=pt)
                return row, _lora1(last, lf, pool_a, pool_b, aid)

            def _chunk_paged_t(params, cache, tokens, length, start,
                               table, aid, pool_a, pool_b):
                cache = set_cache_block_tables(cache, table)
                cache, last, lf = prefill_row_features(
                    dec, params, tokens, length, cache, start,
                    param_transform=pt)
                return _canon_paged(cache), _lora1(last, lf, pool_a,
                                                   pool_b, aid)

            def _chunk_paged_wide_t(params, cache, tokens, length, start,
                                    table, aid, pool_a, pool_b):
                cache = set_cache_block_tables(cache, table)
                cache, last, lf = prefill_row_features(
                    dec, params, tokens, length, cache, start,
                    param_transform=pt)
                return _canon_paged(cache), _lora1(last, lf, pool_a,
                                                   pool_b, aid)

        # --- speculative program bodies (the `spec_k` arg docs) ---
        # The VERIFY program replaces the fused tick: one apply over the
        # [S, spec_k+1] block at per-slot positions (the multi-token
        # vector-index write the model families grew for this), greedy
        # acceptance as cumprod-of-matches against the block's own draft
        # suffix, and the position-0 token through the SAME batched
        # sampler the plain tick used — a sampled row (cap 0) is the
        # old tick bit-for-bit in behavior. `caps` bounds acceptance per
        # row (spec_k for plain greedy, the grammar walk's legal-prefix
        # length for constrained rows, 0 for sampled rows); `forced >=
        # 0` pins the accepted length outright (replay re-feeds: tokens
        # known, model output discarded). Every one of them is [S]
        # runtime data — mixed accept counts never vary program shape.
        if self._spec_on:
            def _verify_core(logits, block, temps, top_ks, top_ps, caps,
                             forced, sub):
                y = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                match = (block[:, 1:] == y[:, :-1]).astype(jnp.int32)
                acc_model = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                acc = jnp.where(forced >= 0, forced,
                                jnp.minimum(acc_model, caps))
                first = sample_logits_batched(
                    sub, logits[:, 0], temperature=temps, top_k=top_ks,
                    top_p=top_ps)
                return y.at[:, 0].set(first), acc

            def _verify(params, cache, positions, block, temps, top_ks,
                        top_ps, caps, forced, rng):
                rng, sub = jax.random.split(rng)
                cache = set_cache_positions(cache, positions)
                logits, mutated = dec.apply(
                    {"params": (pt(params) if pt is not None else params),
                     "cache": cache},
                    block, train=False, mutable=["cache"])
                w, acc = _verify_core(logits, block, temps, top_ks,
                                      top_ps, caps, forced, sub)
                return mutated["cache"], w, acc, rng

            def _verify_paged(params, cache, positions, tables, block,
                              temps, top_ks, top_ps, caps, forced, rng):
                rng, sub = jax.random.split(rng)
                cache = set_cache_positions(cache, positions)
                cache = set_cache_block_tables(cache, tables)
                logits, mutated = dec.apply(
                    {"params": (pt(params) if pt is not None else params),
                     "cache": cache},
                    block, train=False, mutable=["cache"])
                w, acc = _verify_core(logits, block, temps, top_ks,
                                      top_ps, caps, forced, sub)
                return _canon_paged(mutated["cache"]), w, acc, rng

            if self._tenant_on:
                # Tenant verify: per-slot LoRA deltas over EVERY block
                # position (verification must judge drafts under the
                # ADAPTED model) and per-POSITION grammar masks
                # [S, W, V] — the draft path's FSM states, stamped by
                # the host walk each tick.
                def _verify_body_t(params, cache, block, temps, top_ks,
                                   top_ps, masks, pool_a, pool_b, arows,
                                   caps, forced, sub):
                    p2 = pt(params) if pt is not None else params
                    feats, mutated = dec.apply(
                        {"params": p2, "cache": cache},
                        block, train=False, mutable=["cache"],
                        features_only=True)
                    logits = lm_head_logits(dec, p2, feats)  # [S, W, V]
                    s_, w_, v_ = logits.shape
                    delta = batched_lora_delta(
                        feats.reshape(s_ * w_, -1), pool_a, pool_b,
                        jnp.repeat(arows, w_)).reshape(s_, w_, v_)
                    logits = jnp.where(masks, logits + delta, -jnp.inf)
                    w, acc = _verify_core(logits, block, temps, top_ks,
                                          top_ps, caps, forced, sub)
                    return mutated["cache"], w, acc

                def _verify_t(params, cache, positions, block, temps,
                              top_ks, top_ps, masks, pool_a, pool_b,
                              arows, caps, forced, rng):
                    rng, sub = jax.random.split(rng)
                    cache = set_cache_positions(cache, positions)
                    cache, w, acc = _verify_body_t(
                        params, cache, block, temps, top_ks, top_ps,
                        masks, pool_a, pool_b, arows, caps, forced, sub)
                    return cache, w, acc, rng

                def _verify_paged_t(params, cache, positions, tables,
                                    block, temps, top_ks, top_ps, masks,
                                    pool_a, pool_b, arows, caps, forced,
                                    rng):
                    rng, sub = jax.random.split(rng)
                    cache = set_cache_positions(cache, positions)
                    cache = set_cache_block_tables(cache, tables)
                    cache, w, acc = _verify_body_t(
                        params, cache, block, temps, top_ks, top_ps,
                        masks, pool_a, pool_b, arows, caps, forced, sub)
                    return _canon_paged(cache), w, acc, rng

            spec_kk, spec_ng = self._spec_k, self._spec_ngram

            def _draft_ngram(toks, positions):
                # THE shared drafter definition (`models/speculative.py`
                # — the one-shot loop compiles the same function with a
                # scalar position; equivalence is pinned by test).
                return ngram_drafts(toks, positions, spec_ng, spec_kk)

            if self._draft_on:
                ddec = self._ddec

                def _draft_model_fn(dparams, dcache, positions, tables,
                                    cur, forced, n_forced):
                    dcache = set_cache_positions(dcache, positions)
                    dcache = set_cache_block_tables(dcache, tables)
                    tok = cur
                    outs = []
                    for j in range(spec_kk):
                        logits, mutated = ddec.apply(
                            {"params": dparams, "cache": dcache},
                            tok[:, None], train=False, mutable=["cache"])
                        dcache = mutated["cache"]
                        nxt = jnp.argmax(logits[:, -1],
                                         axis=-1).astype(jnp.int32)
                        # Teacher-force known replay tokens: the draft
                        # cache must hold the TRUE stream's K/V (not the
                        # draft model's own guesses) through recovery.
                        nxt = jnp.where(j < n_forced, forced[:, j], nxt)
                        outs.append(nxt)
                        tok = nxt
                    # One extra apply writes the FINAL draft's K/V (its
                    # logits are discarded): a fully-accepted window
                    # would otherwise leave a one-position hole in the
                    # draft cache and degrade every later draft.
                    _, mutated = ddec.apply(
                        {"params": dparams, "cache": dcache},
                        tok[:, None], train=False, mutable=["cache"])
                    return (_canon_paged(mutated["cache"]),
                            jnp.stack(outs, axis=1))

                def _draft_chunk(dparams, dcache, tokens, length, start,
                                 table):
                    dcache = set_cache_block_tables(dcache, table)
                    dcache, _ = prefill_row_from(ddec, dparams, tokens,
                                                 length, dcache, start)
                    return _canon_paged(dcache)

        # The resident programs (four without prefix caching; gather /
        # chunk-prefill / donate replace the one-shot prefill with it
        # on; in PAGED mode the set shrinks to tick + chunk widths +
        # sample_first — no gather, no insert, no donate scatter: the
        # prefill writes K/V in place and sharing is pure host
        # bookkeeping). Donation discipline: the pooled slot cache (or
        # the paged pool tree) is donated through every program that
        # touches it — the engine always adopts the returned trees, so
        # the resident HBM buffers are reused in place and a stale
        # reference can never be used by mistake.
        self._donated_by_site = dict(_PAGED_DONATED_BY_SITE if self._paged
                                     else _DONATED_BY_SITE)
        if self._spec_on:
            self._donated_by_site.update(
                _SPEC_DONATED_PAGED if self._paged else _SPEC_DONATED_ROW)
            if self._draft_on:
                # The draft-MODEL program donates the draft tree (the
                # n-gram program donates nothing, so this entry exists
                # only with a draft model): a REAL mid-dispatch error
                # must never re-dispatch the consumed dcache — it
                # escalates straight to the pool-class rebuild, which
                # reconstructs both trees.
                self._donated_by_site["draft"] = "pool"
        ten = self._tenant_on
        self._sample_first_p = jax.jit(_sample_first_t if ten
                                       else _sample_first)
        # The adapter-load program copies (never donates — see
        # ops/lora.adapter_pool_load), so a faulted load retries
        # against the intact pool like any transient site.
        self._adapter_load_p = jax.jit(_adapter_load) if ten else None
        if self._paged:
            self._insert_p = None
            self._tick_p = jax.jit(_tick_paged_t if ten else _tick_paged,
                                   donate_argnums=(1,))
            self._gather_p = None
            self._chunk_p = jax.jit(_chunk_paged_t if ten else _chunk_paged,
                                    donate_argnums=(1,))
            self._has_wide = (
                self._chunk < self.prefill_len
                and self.prefill_len + self.prefill_len // 4
                <= model.max_len)
            self._chunk_wide_p = (jax.jit(_chunk_paged_wide_t if ten
                                          else _chunk_paged_wide,
                                          donate_argnums=(1,))
                                  if self._has_wide else None)
            self._donate_p = None
            self._pool = None
            self._prefix = RadixPrefixCache(bs, pool_blocks)
            self._row = None
            self._cache = paged_decode_cache(dec, pool_blocks, bs)
            # Host-authoritative per-slot block tables (scratch-filled
            # for parked slots) and the private (not-yet-shared) block
            # ids each slot owns.
            self._tables = np.zeros(
                (self.max_slots, self._table_width), np.int32)
            self._private: List[List[int]] = [
                [] for _ in range(self.max_slots)]
            # KV bytes one token occupies across every leaf — what one
            # avoided gather copy is worth (`copy_bytes_avoided`).
            kv_bytes = sum(
                int(leaf.size) * leaf.dtype.itemsize
                for path, leaf in jax.tree_util.tree_leaves_with_path(
                    self._cache)
                if leaf.ndim > 2)
            self._kv_token_bytes = kv_bytes // (pool_blocks * bs)
            self._verify_p = self._draft_p = self._dchunk_p = None
            self._draft_model_p = None
            self._dcache = None
            if self._spec_on:
                self._verify_p = jax.jit(
                    _verify_paged_t if ten else _verify_paged,
                    donate_argnums=(1,))
                if self._draft_on:
                    # A DISTINCT attribute from the (non-donating)
                    # n-gram program: this one donates the draft tree.
                    self._draft_model_p = jax.jit(_draft_model_fn,
                                                  donate_argnums=(1,))
                    self._dchunk_p = jax.jit(_draft_chunk,
                                             donate_argnums=(1,))
                    # The second cache tree riding the same pool: one
                    # block-id space, one table, two KV trees (target +
                    # draft) — sharing, dedup, flush, and reset all act
                    # on both through the same ids.
                    self._dcache = paged_decode_cache(self._ddec,
                                                      pool_blocks, bs)
                else:
                    self._draft_p = jax.jit(_draft_ngram)
            self._init_host_tier(host_tier)
            self._warm = False
            if tracer is not None:
                self.set_tracer(tracer)
            return
        self._insert_p = jax.jit(_insert, donate_argnums=(0,))
        self._tick_p = jax.jit(_tick_t if ten else _tick,
                               donate_argnums=(1,))
        if self._prefix_on:
            self._prefill_p = None
            self._gather_p = jax.jit(_gather, donate_argnums=(2,))
            self._chunk_p = jax.jit(_chunk_t if ten else _chunk_prefill,
                                    donate_argnums=(1,))
            # A second, WIDE chunk program (full prefill_len) for cold /
            # barely-cached prompts: one fixed per-apply cost instead of
            # ceil(plen/chunk) of them, so enabling the prefix cache
            # never slows a cold admission below the one-shot prefill.
            # Two separate jits (not two shapes through one jit) keep
            # the one-executable-per-program pin meaningful. The wide
            # program can start as deep as prefill_len/4 (the width
            # policy's threshold), so it also needs its positions to
            # stay in range at that offset.
            self._has_wide = (
                self._chunk < self.prefill_len
                and self.prefill_len + self.prefill_len // 4
                <= model.max_len)
            self._chunk_wide_p = (jax.jit(_chunk_wide_t if ten
                                          else _chunk_prefill_wide,
                                          donate_argnums=(1,))
                                  if self._has_wide else None)
            self._donate_p = jax.jit(_donate, donate_argnums=(0,))
            self._pool = kv_block_pool(dec, pool_blocks, bs)
            self._prefix = RadixPrefixCache(bs, pool_blocks)
            # The resident admission row cache: donated through gather
            # and every chunk, adopted back each time — one set of
            # batch-1 buffers serves every admission.
            self._row = jax.tree.map(
                lambda sd: jnp.zeros(sd.shape, sd.dtype),
                _decode_cache_shapes(dec, 1))
        else:
            self._prefill_p = jax.jit(_prefill_t if ten else _prefill)
            self._gather_p = self._chunk_p = self._donate_p = None
            self._chunk_wide_p = None
            self._has_wide = False
            self._pool = None
            self._prefix = None
            self._row = None

        self._verify_p = self._draft_p = self._dchunk_p = None
        self._draft_model_p = None
        self._dcache = None
        if self._spec_on:
            self._verify_p = jax.jit(_verify_t if ten else _verify,
                                     donate_argnums=(1,))
            self._draft_p = jax.jit(_draft_ngram)
        self._cache = slot_decode_cache(dec, self.max_slots)
        self._init_host_tier(host_tier)
        self._warm = False
        if tracer is not None:
            self.set_tracer(tracer)

    def _init_host_tier(self, host_tier) -> None:
        """Arm the host-RAM spill tier (the ``host_tier`` arg docs):
        build the byte-budgeted :class:`HostTierCache` with this
        engine's per-leaf block spec, compile the ONE promotion program
        (``host_promote`` — a :func:`cache_blocks_scatter` per KV leaf
        over the donated pool tree, fixed padded shapes), and install
        the demotion hook on the radix index's eviction path. A
        ``None``/zero-budget config installs NOTHING: the engine stays
        bit-identical to an untiered one."""
        self._host = None
        self._promote_p = None
        self._demote_p = None
        self._host_promote_tokens = 0
        if host_tier is None:
            return
        cfg = (host_tier if isinstance(host_tier, HostTierConfig)
               else HostTierConfig(byte_budget=int(host_tier)))
        if cfg.byte_budget == 0:
            return
        if not self._prefix_on:
            raise ValueError(
                "host_tier needs the prefix-cache machinery (the radix "
                "eviction path is what demotes); leave "
                "prefix_cache_blocks enabled or pass host_tier=None")
        if self._draft_on:
            raise NotImplementedError(
                "host_tier with spec_draft_model is not supported yet: "
                "a promoted block carries target K/V only, and the "
                "draft tree's twin block would be junk — mirroring the "
                "second cache tree through the tier is follow-on work")
        target = self._cache if self._paged else self._pool
        spec = {}
        for path, leaf in jax.tree_util.tree_leaves_with_path(target):
            if leaf.ndim < 3:
                continue
            spec[jax.tree_util.keystr(path)] = (
                (1,) + tuple(leaf.shape[1:-2])
                + (self.prefix_block_size, leaf.shape[-1]),
                np.dtype(leaf.dtype))
        self._host = HostTierCache(
            self.prefix_block_size, cfg.byte_budget,
            min_chain_blocks=cfg.min_chain_blocks, leaf_spec=spec)
        self._host_promote_tokens = int(cfg.promote_tokens_per_block)

        def _host_promote(pool, rows, ids):
            # The H2D rides the SAME primitive donation rides
            # (`ops.attention.cache_blocks_scatter`): one scatter per
            # KV leaf over the donated pool tree, no model compute;
            # padded ids land their junk in the scratch sink, and
            # non-KV leaves (counters, tables) pass through untouched
            # so the paged tree keeps its canonical placeholders.
            def _s(path, pool_leaf, row_leaf):
                if pool_leaf.ndim < 3:
                    return pool_leaf
                return cache_blocks_scatter(pool_leaf, row_leaf, ids, 0)
            return jax.tree_util.tree_map_with_path(_s, pool, rows)

        self._promote_p = jax.jit(_host_promote, donate_argnums=(0,))
        # A REAL mid-dispatch promotion error may have consumed the
        # donated pool tree — recovery is the pool-class rebuild
        # (paged: the full live-slot replay), like donate/chunk.
        self._donated_by_site["host_promote"] = "pool"

        def _host_demote(pool, ids):
            # The D2H read, same primitive as the admission gather
            # (`ops.attention.cache_blocks_gather`) but jitted over
            # the whole tree at a FIXED scratch-padded id width: the
            # reclaim batch becomes ONE dispatch that traces once
            # (per-leaf eager gathers re-specialize per batch width
            # — mid-run backend compiles — and their dispatch
            # overhead dominated the admission path). Read-only: no
            # donation, no fault site — a failed read degrades to
            # the old free-and-recompute path in `_demote_blocks`.
            out = {}
            for path, leaf in jax.tree_util.tree_leaves_with_path(pool):
                if leaf.ndim < 3:
                    continue
                out[jax.tree_util.keystr(path)] = cache_blocks_gather(
                    leaf, ids)
            return out

        self._demote_p = jax.jit(_host_demote)
        self._prefix.on_evict = self._demote_blocks

    # ----------------------------------------------------- observability
    @property
    def tracer(self):
        """The installed tracer (the shared no-op object when tracing
        is disabled — check ``tracer.enabled``)."""
        return self._tracer

    def set_tracer(self, tracer) -> None:
        """Install (or, with ``None``, remove) a per-request tracer.

        Also wires the fault plan's injection observer so every
        injected fault surfaces as an engine event with the same
        ``(step, site)`` coordinates the plan fired at — including
        LATENCY faults, which raise nothing and would otherwise be
        invisible to the engine."""
        self._tracer = NULL_TRACER if tracer is None else tracer
        if self._faults is not None:
            self._faults.on_inject = (
                self._tracer.on_fault_injected if self._tracer.enabled
                else None)

    # -------------------------------------------------------- submission
    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               sampling: Optional[SamplingParams] = None,
               deadline_s: Optional[float] = None,
               priority: Priority = Priority.INTERACTIVE,
               adapter: Optional[str] = None,
               constraint: Optional[dict] = None) -> RequestHandle:
        """Queue one request; returns its streaming handle.

        Raises :class:`~pddl_tpu.serve.request.QueueFull` when the
        admission-control queue is at depth (the metrics count the
        rejection either way); the raised instance carries a
        ``retry_after_s`` hint — the queue this PRIORITY would wait
        behind (its own and every more urgent class) x the recent
        per-admission interval — once the engine has admitted enough
        traffic to estimate one, so a ``best_effort`` reject honestly
        hints a longer wait than an ``interactive`` one. After
        :meth:`drain` the engine accepts nothing (the process is on
        its way out).

        Tenant fields (need ``tenant=TenantConfig(...)``): ``adapter``
        names a registered LoRA adapter (``None`` = base model);
        ``constraint`` is a grammar/schema spec dict
        (``{"kind": "regex", "pattern": ...}`` or ``{"kind":
        "json_schema", "schema": {...}}``) compiled HERE — a malformed
        spec rejects the request loudly, never faults a tick."""
        if self._drained:
            raise RuntimeError(
                "engine is drained (snapshot taken, admission stopped); "
                "restore the snapshot into a fresh engine")
        priority = Priority(priority)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if prompt.size > self.prefill_len:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the engine's "
                f"prefill_len {self.prefill_len}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt.size + max_new_tokens > self.model.max_len:
            raise ValueError(
                f"prompt + new tokens {prompt.size + max_new_tokens} "
                f"exceed max_len {self.model.max_len}")
        if (adapter is not None or constraint is not None) \
                and not self._tenant_on:
            raise ValueError(
                "adapter/constraint need a tenant-enabled engine "
                "(ServeEngine(..., tenant=TenantConfig(...)))")
        if adapter is not None and adapter not in self._registry:
            raise ValueError(
                f"adapter {adapter!r} is not registered "
                f"(known: {self._registry.names})")
        if constraint is not None:
            self._compiled_fsm(constraint)  # validate + warm the cache
        req = Request(prompt=prompt.tolist(),
                      max_new_tokens=int(max_new_tokens),
                      sampling=sampling or SamplingParams(),
                      deadline_s=deadline_s, priority=priority,
                      adapter=adapter, constraint=constraint)
        handle = RequestHandle(req, arrival_s=self._clock())
        try:
            self.scheduler.submit(handle)
        except QueueFull as e:
            self.metrics.record_rejected(priority.value)
            # Re-raise with the polite-backpressure hint the scheduler
            # cannot compute (it has no latency telemetry). The depth
            # priced in is what THIS class waits behind — its own and
            # every more urgent class — so lower classes get longer,
            # honest hints.
            raise QueueFull(
                e.queue_depth, e.max_queue_depth,
                retry_after_s=self.metrics.estimate_retry_after_s(
                    self.scheduler.depth_at_or_above(priority)),
                priority=priority) from None
        except Exception:
            self.metrics.record_rejected(priority.value)
            raise
        if constraint is not None:
            self.metrics.record_constrained()
        self._tracer.on_submit(handle, self.scheduler.depth)
        return handle

    # ---------------------------------------------------------- plumbing
    def warmup(self) -> None:
        """Trace/compile every resident program before traffic (one
        dummy admission into slot 0 + one all-dead tick; the junk K/V
        lands at parked positions and is overwritten by the first real
        admit — the dummy gather/donate use only the scratch block, so
        the radix index stays empty). Implicit on the first ``step()``
        if not called."""
        if self._warm:
            return
        first_mask = self._first_mask_args(None)  # () on a plain engine
        if self._tenant_on:
            # Warm the adapter-load program by writing zeros into the
            # identity row — content unchanged (row 0 IS the zero
            # adapter), program traced once.
            self._apool_a, self._apool_b = self._adapter_load_p(
                self._apool_a, self._apool_b, np.int32(0),
                np.zeros((self.model.embed_dim, self._registry.rank),
                         np.float32),
                np.zeros((self._registry.rank, self.model.vocab_size),
                         np.float32))
        if self._paged:
            # All-scratch tables: every warmup write lands in the junk
            # sink, the radix index stays empty, and every program
            # traces once with its serving shapes.
            t1 = np.zeros((1, self._table_width), np.int32)
            self._cache, logits = self._chunk_p(
                self._params, self._cache,
                np.zeros((1, self._chunk), np.int32), np.int32(1),
                np.int32(0), t1, *self._chunk_extra(0))
            if self._has_wide:
                self._cache, logits = self._chunk_wide_p(
                    self._params, self._cache,
                    np.zeros((1, self.prefill_len), np.int32), np.int32(1),
                    np.int32(0), t1, *self._chunk_extra(0))
            tok, self._rng = self._sample_first_p(
                logits, *first_mask, np.float32(0.0), np.int32(0),
                np.float32(2.0), self._rng)
            if self._host is not None:
                # All-scratch promote: junk lands in the sink, the
                # host tier stays empty, the program traces once —
                # and the demote gather's one program likewise.
                self._cache = self._promote_p(
                    self._cache, self._assemble_promote_rows([]),
                    np.zeros(self._match_cap, np.int32))
                self._demote_p(self._cache,
                               np.zeros(self._match_cap, np.int32))
            if self._spec_on:
                nxt = self._warm_spec()
            else:
                self._cache, nxt, self._rng = self._tick_p(
                    self._params, self._cache, self._positions,
                    self._tables, self._tokens, self._temps,
                    self._top_ks, self._top_ps, *self._tick_extra(),
                    self._rng)
            jax.block_until_ready((tok, nxt))
            self._warm = True
            return
        if self._prefix_on:
            row = self._gather_p(
                self._pool, np.zeros(self._match_cap, np.int32),
                self._row)
            row, logits = self._chunk_p(
                self._params, row, np.zeros((1, self._chunk), np.int32),
                np.int32(1), np.int32(0), *self._chunk_extra(0))
            if self._has_wide:
                row, logits = self._chunk_wide_p(
                    self._params, row,
                    np.zeros((1, self.prefill_len), np.int32),
                    np.int32(1), np.int32(0), *self._chunk_extra(0))
            self._pool = self._donate_p(
                self._pool, row, np.zeros(self._donate_cap, np.int32),
                np.int32(0))
            self._row = row
            if self._host is not None:
                # All-scratch promote (the paged branch's twin): the
                # host_promote program traces once at warmup too,
                # and the demote gather's one program likewise.
                self._pool = self._promote_p(
                    self._pool, self._assemble_promote_rows([]),
                    np.zeros(self._match_cap, np.int32))
                self._demote_p(self._pool,
                               np.zeros(self._match_cap, np.int32))
        else:
            dummy = np.zeros((1, self.prefill_len), np.int32)
            row, logits = self._prefill_p(self._params, dummy, 1,
                                          *self._chunk_extra(0))
        self._cache = self._insert_p(self._cache, row, 0, 0)
        tok, self._rng = self._sample_first_p(
            logits, *first_mask, np.float32(0.0), np.int32(0),
            np.float32(2.0), self._rng)
        if self._spec_on:
            nxt = self._warm_spec()
        else:
            self._cache, nxt, self._rng = self._tick_p(
                self._params, self._cache, self._positions, self._tokens,
                self._temps, self._top_ks, self._top_ps,
                *self._tick_extra(), self._rng)
        jax.block_until_ready((tok, nxt))
        self._warm = True

    def _warm_spec(self):
        """Trace the draft/verify pair (and the draft model's admission
        chunk) with all-dead inputs: caps 0 + forced -1 accept nothing,
        junk writes land at parked positions (row mode) or the scratch
        sink (paged all-scratch tables), so warmup leaves no trace in
        any live state. Returns the verify window for the caller's
        block_until_ready."""
        s, k = self.max_slots, self._spec_k
        forced_tok = np.zeros((s, k), np.int32)
        forced_n = np.full(s, -1, np.int32)
        if self._draft_on:
            t1 = np.zeros((1, self._table_width), np.int32)
            self._dcache = self._dchunk_p(
                self._dparams, self._dcache,
                np.zeros((1, self._chunk), np.int32), np.int32(1),
                np.int32(0), t1)
            self._dcache, drafts = self._draft_model_p(
                self._dparams, self._dcache, self._positions,
                self._tables, self._tokens, forced_tok, forced_n)
        else:
            drafts = self._draft_p(self._hist, self._positions)
        block = np.zeros((s, k + 1), np.int32)
        caps = np.zeros(s, np.int32)
        if self._paged:
            self._cache, w, acc, self._rng = self._verify_p(
                self._params, self._cache, self._positions, self._tables,
                block, self._temps, self._top_ks, self._top_ps,
                *self._verify_extra(), caps, forced_n, self._rng)
        else:
            self._cache, w, acc, self._rng = self._verify_p(
                self._params, self._cache, self._positions, block,
                self._temps, self._top_ks, self._top_ps,
                *self._verify_extra(), caps, forced_n, self._rng)
        jax.block_until_ready(drafts)
        return w

    def compile_counts(self) -> Dict[str, int]:
        """Compiled-executable count per resident program (the
        zero-recompiles-after-warmup contract: every entry stays at 1).
        With prefix caching on, admission runs gather → N×chunk-prefill
        → donate instead of the one-shot prefill — chunk width, block-id
        vector lengths, and every offset/length are fixed shapes or
        runtime values, so the program set stays closed here too."""
        if self._paged:
            counts = {
                "sample_first": self._sample_first_p._cache_size(),
                "chunk_prefill": self._chunk_p._cache_size(),
            }
            if self._spec_on:
                # Speculative engines swap the one-token tick for the
                # draft/verify pair (+ the draft model's admission
                # chunk) — the site vocabulary graftlint keeps in
                # lockstep with FaultPlan.SITES.
                counts["verify"] = self._verify_p._cache_size()
                counts["draft"] = (self._draft_model_p if self._draft_on
                                   else self._draft_p)._cache_size()
                if self._draft_on:
                    counts["draft_prefill"] = \
                        self._dchunk_p._cache_size()
            else:
                counts["tick"] = self._tick_p._cache_size()
            if self._has_wide:
                counts["chunk_prefill_wide"] = \
                    self._chunk_wide_p._cache_size()
            if self._tenant_on:
                counts["adapter_load"] = \
                    self._adapter_load_p._cache_size()
            if self._host is not None:
                counts["host_promote"] = self._promote_p._cache_size()
            return counts
        counts = {
            "insert": self._insert_p._cache_size(),
            "sample_first": self._sample_first_p._cache_size(),
        }
        if self._spec_on:
            counts["verify"] = self._verify_p._cache_size()
            counts["draft"] = self._draft_p._cache_size()
        else:
            counts["tick"] = self._tick_p._cache_size()
        if self._tenant_on:
            counts["adapter_load"] = self._adapter_load_p._cache_size()
        if self._prefix_on:
            counts["gather"] = self._gather_p._cache_size()
            counts["chunk_prefill"] = self._chunk_p._cache_size()
            if self._has_wide:
                counts["chunk_prefill_wide"] = \
                    self._chunk_wide_p._cache_size()
            counts["donate"] = self._donate_p._cache_size()
            if self._host is not None:
                counts["host_promote"] = self._promote_p._cache_size()
        else:
            counts["prefill"] = self._prefill_p._cache_size()
        return counts

    @property
    def prefix_cache_enabled(self) -> bool:
        return self._prefix_on

    @property
    def paged(self) -> bool:
        """True when decode reads K/V straight from the block pool
        through per-slot block tables (no resident slot cache)."""
        return self._paged

    @property
    def host_tier_enabled(self) -> bool:
        """True when the host-RAM spill tier is armed (module
        docstring; ``host_tier=`` with a nonzero byte budget)."""
        return self._host is not None

    @property
    def host_tier_bytes_resident(self) -> int:
        """Host bytes the spill tier currently holds (0 with the tier
        off) — the gauge the sizing runbook watches against the byte
        budget (docs/OPERATIONS.md § "Host tier sizing")."""
        return self._host.bytes_resident if self._host is not None else 0

    @property
    def host_tier_blocks_resident(self) -> int:
        """Demoted blocks currently resident in the host tier."""
        return (self._host.blocks_resident if self._host is not None
                else 0)

    @property
    def spec_enabled(self) -> bool:
        """True when this engine compiled the speculative draft/verify
        program pair (``spec_k > 0``; module docstring)."""
        return self._spec_on

    @property
    def spec_k(self) -> int:
        """Drafted tokens per slot per step (0 = classic tick)."""
        return self._spec_k

    @property
    def spec_draft_model_enabled(self) -> bool:
        """True when a draft model (second paged cache tree) drafts;
        False means the zero-weight n-gram drafter (or spec off)."""
        return self._draft_on

    # ----------------------------------------------------------- tenancy
    @property
    def tenant_enabled(self) -> bool:
        """True when this engine compiled the multi-tenant program set
        (per-slot LoRA adapters + grammar masks; `serve/tenant/`)."""
        return self._tenant_on

    @property
    def adapter_registry(self):
        """The engine's :class:`~pddl_tpu.serve.tenant.AdapterRegistry`
        (``None`` on a plain engine). Adapters registered here become
        submittable immediately — residency is handled at admission."""
        return self._registry

    @property
    def adapter_pool_resident(self) -> int:
        """Adapters currently device-resident (0 on a plain engine)."""
        return self._apool.resident if self._tenant_on else 0

    def _compiled_fsm(self, spec):
        """Compile (or fetch) the token FSM for a constraint spec dict.
        Cached by canonical spec key — N requests under one schema
        share one automaton and one mask table."""
        key = constraint_key(spec)
        fsm = self._fsm_cache.get(key)
        if fsm is None:
            if self._tenant.token_strings is None:
                raise ValueError(
                    "constrained decoding needs TenantConfig."
                    "token_strings (the token-id -> string vocabulary "
                    "grammar compilation maps masks through)")
            fsm = compile_constraint(spec, self._tenant.token_strings)
            # Bounded like the process-wide cache it fronts
            # (`grammar._FSM_CACHE`): client-supplied specs (e.g. a
            # per-request ID baked into a pattern) must not grow host
            # memory forever in a long-lived engine.
            if len(self._fsm_cache) >= 256:
                self._fsm_cache.pop(next(iter(self._fsm_cache)))
            self._fsm_cache[key] = fsm
        # Engine-specific (eos-dependent) viability, checked per call
        # because the FSM cache is engine-agnostic: a constraint whose
        # START state allows no token and has no eos escape (it matches
        # only the empty string — e.g. "x*" over a vocabulary with no
        # 'x') could never sample a first token; rejecting HERE fails
        # the request at submit (or via the replay budget at restore)
        # instead of crashing the step for everyone.
        if fsm.is_dead_end(fsm.start, self.eos_token):
            raise ValueError(
                "constraint admits no first token over this engine's "
                "vocabulary (it matches only the empty string, and the "
                "engine has no eos token to emit)")
        return fsm

    def _acquire_adapter(self, name: str, fresh: bool = True) -> int:
        """Resolve an adapter name to a PINNED device pool row, loading
        the factors on a cold miss (LRU-evicting an unpinned row under
        pressure — the prefix-chain discipline applied to weights).
        ``fresh=False`` marks a replay/resume re-admission (pool
        traffic counted, per-tenant request volume not). Escalates
        unresolvable shortfalls as :class:`_SlotStateLost` so admission
        charges a replay instead of crashing the step."""
        row = self._apool.lookup(name)
        if row is not None:
            self.metrics.record_adapter_hit(name, self._apool.resident,
                                            fresh=fresh)
            self._apool.pin(row)
            return row
        try:
            adapter = self._registry.get(name)
        except KeyError as e:
            # Permanently unserveable here (e.g. a migrated stream
            # whose adapter this deployment never registered): the
            # replay budget turns it into a terminal ERROR.
            raise _SlotStateLost("adapter_admit", e) from e
        try:
            row = self._apool.assign(name)
        except AdapterPoolExhausted as e:
            raise _SlotStateLost("adapter_admit", e) from e
        try:
            self._apool_a, self._apool_b = self._device_call(
                "adapter_load", self._adapter_load_p,
                self._apool_a, self._apool_b, np.int32(row),
                adapter.a, adapter.b)
        except _SlotStateLost:
            self._apool.unassign(row)
            raise
        self.metrics.record_adapter_load(name, self._apool.resident,
                                         self._apool.evictions,
                                         fresh=fresh)
        self._apool.pin(row)
        return row

    def _release_adapter(self, row) -> None:
        """Unpin a slot's (or a failed admission's) adapter row; row 0
        (identity / no adapter) is a no-op."""
        if self._tenant_on and int(row) != 0:
            self._apool.unpin(int(row))

    def _tenant_admit(self, handle):
        """The tenant half of one admission: ``(pinned_adapter_row,
        compiled_fsm_or_None)``. Raises :class:`_SlotStateLost` (self-
        unwound — nothing left pinned) on unresolvable specs/pools."""
        if not self._tenant_on:
            return 0, None
        req = handle.request
        fsm = None
        if req.constraint is not None:
            try:
                fsm = self._compiled_fsm(req.constraint)
            except ValueError as e:
                # submit() validates, so this is the restore/migration
                # path seeing a spec this engine cannot compile: fail
                # the REQUEST (via replay budget), not the engine.
                raise _SlotStateLost("constraint_admit", e) from e
        # "Fresh" means this request's FIRST service, not merely
        # zero tokens: a pre-first-token replay (prefill faulted past
        # the retry budget) has empty tokens but a replay charge, and
        # must not double-count the capacity-planning series.
        fresh = not handle.tokens and not handle.replays
        arow = (self._acquire_adapter(req.adapter, fresh=fresh)
                if req.adapter is not None else 0)
        return arow, fsm

    def _chunk_extra(self, aid):
        """Extra prefill-program args in tenant mode (adapter id +
        factor pools); empty on a plain engine."""
        return ((np.int32(aid), self._apool_a, self._apool_b)
                if self._tenant_on else ())

    def _tick_extra(self):
        """Extra fused-tick args in tenant mode (grammar masks + factor
        pools + per-slot adapter rows); empty on a plain engine. The
        mask ships as one device-resident array restaged only on
        change."""
        if not self._tenant_on:
            return ()
        if self._masks_dev is None or self._masks_dirty:
            self._masks_dev = jnp.asarray(self._masks)
            self._masks_dirty = False
        return (self._masks_dev, self._apool_a, self._apool_b,
                self._arow)

    def _verify_extra(self):
        """Extra verify-program args in tenant mode (per-POSITION
        grammar masks ``[S, spec_k+1, V]`` + factor pools + per-slot
        adapter rows); empty on a plain engine. Same restage-on-change
        staging as the tick masks."""
        if not self._tenant_on:
            return ()
        if self._masks_w_dev is None or self._masks_w_dirty:
            self._masks_w_dev = jnp.asarray(self._masks_w)
            self._masks_w_dirty = False
        return (self._masks_w_dev, self._apool_a, self._apool_b,
                self._arow)

    def _first_mask_args(self, fsm):
        """The sample-first mask arg (``[1, V]``) in tenant mode: the
        FSM's start-state allow row for constrained requests, all-True
        (a bitwise logits pass-through) otherwise."""
        if not self._tenant_on:
            return ()
        if fsm is None:
            return (np.ones((1, self.model.vocab_size), np.bool_),)
        return (fsm.allow_row(fsm.start, self.eos_token)[None],)

    @property
    def blocks_shared(self) -> int:
        """Pool blocks referenced by MORE THAN ONE live slot's block
        table right now — each is one block of KV the copy engine
        would have duplicated per referencing slot. 0 outside paged
        mode."""
        if not self._paged:
            return 0
        live = [sid for sid, h in enumerate(self._slots) if h is not None]
        if len(live) < 2:
            return 0
        # One vectorized pass (this gauge is stamped every tick): count
        # ids that appear in more than one row. Within a row ids are
        # unique by construction (each table entry is a distinct block
        # or scratch), so a >1 total count means >1 slot.
        rows = self._tables[live]
        ids, counts = np.unique(rows[rows != 0], return_counts=True)
        return int((counts > 1).sum())

    def resident_kv_report(self) -> Dict[str, int]:
        """Live-stream KV accounting, comparable across engine modes
        (the capacity half of `benchmarks/serve_bench.py --paged-only`):

        - ``tokens_resident``: summed depth of every live stream — the
          user-visible context currently held, identical for both
          modes at the same workload snapshot.
        - ``kv_bytes_used``: HBM actually holding that state. The
          resident-row engine pays each live slot's depth PRIVATELY
          plus one pool copy of every cached block; the paged engine
          pays each DISTINCT referenced block once — shared prefixes
          collapse, which is the whole point.
        - ``kv_bytes_allocated``: the reserved footprint (slot cache +
          pool, or the paged pool tree).
        """
        live = [sid for sid, h in enumerate(self._slots) if h is not None]
        if self._paged:
            tokens = int(sum(int(self._positions[sid]) for sid in live))
            distinct = set()
            for sid in live:
                distinct.update(
                    int(b) for b in self._tables[sid] if b != 0)
            used = len(distinct) * self.prefix_block_size \
                * self._kv_token_bytes
            return {"tokens_resident": tokens, "kv_bytes_used": used,
                    "kv_bytes_allocated": pool_nbytes(self._cache)}
        cache_bytes = pool_nbytes(self._cache)
        token_bytes = cache_bytes // (self.max_slots * self.model.max_len)
        tokens = int(sum(int(self._positions[sid]) for sid in live))
        used = tokens * token_bytes
        allocated = cache_bytes
        if self._prefix_on:
            used += (self._prefix.blocks_live * self.prefix_block_size
                     * token_bytes)
            allocated += pool_nbytes(self._pool)
        return {"tokens_resident": tokens, "kv_bytes_used": used,
                "kv_bytes_allocated": allocated}

    @property
    def block_table_fill(self) -> float:
        """Mean fraction of live slots' table entries pointing at real
        (non-scratch) blocks — how much of the paged address space the
        current streams occupy. 0.0 with no live slots or outside
        paged mode."""
        if not self._paged:
            return 0.0
        live = [sid for sid, h in enumerate(self._slots) if h is not None]
        if not live:
            return 0.0
        rows = self._tables[live]
        return float((rows != 0).mean())

    @property
    def degraded(self) -> bool:
        """True while an OOM has the prefix cache shed and donations
        off (serving continues on the cold path); re-arms after
        ``degraded_cooldown_s`` without another OOM."""
        return self._degraded

    @property
    def prefix_pool_nbytes(self) -> int:
        """Device bytes the resident KV block pool holds (0 with the
        cache off) — in the copy engine the HBM degraded mode can
        shed; in PAGED mode the pool is the whole serving KV (live
        streams included), so only its unpinned cached fraction is
        sheddable (docs/OPERATIONS.md § "Failure modes & recovery")."""
        if self._paged:
            return pool_nbytes(self._cache)
        return pool_nbytes(self._pool) if self._prefix_on else 0

    @property
    def drained(self) -> bool:
        """True once :meth:`drain` snapshotted the engine: admission is
        stopped and ``step()`` is a no-op — restore into a fresh
        engine."""
        return self._drained

    @property
    def live_slots(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def has_work(self) -> bool:
        if self._drained:
            return False
        return (self.live_slots > 0 or self.scheduler.depth > 0
                or bool(self._admitting))

    def _free_slot_ids(self) -> List[int]:
        return [i for i, s in enumerate(self._slots) if s is None]

    def _evict(self, slot_id: int, state: RequestState,
               reason: FinishReason) -> None:
        handle = self._slots[slot_id]
        assert handle is not None
        handle.state = state
        handle.finish_reason = reason
        handle.finish_s = self._clock()
        self.metrics.record_finish(reason.value,
                                   handle.request.priority.value)
        self._tracer.on_finish(handle, reason.value)
        self._park_slot(slot_id)

    # --------------------------------------------------- fault handling
    def _device_call(self, site: str, fn, *args):
        """The ONE guarded device-dispatch boundary: consult the fault
        plan, classify failures, retry transients with bounded
        exponential backoff, flip degraded on OOM (no blind retry — an
        allocation failure won't pass until memory is shed, and the
        degraded flush plus the caller's rebuild IS the shedding), and
        escalate to :class:`_SlotStateLost` when the budget runs out.
        ``KillPoint`` is a BaseException — it passes through everything
        here, like the SIGKILL it stands for. Injected faults fire
        BEFORE ``fn`` runs, so retrying never touches a half-consumed
        donated buffer; a REAL error from a donated-buffer program is
        never re-dispatched (its donated input may already be deleted)
        — it escalates immediately, tagged with the consumed resource
        so the recovery path rebuilds it."""
        attempt = 0
        while True:
            try:
                if self._faults is not None:
                    self._faults.check(site)
                t0 = time.perf_counter()
                out = fn(*args)
                # Dispatch wall time (the programs are async — this is
                # host-side dispatch + any implicit transfer wait, never
                # an added device sync), accumulated per site for the
                # telemetry ring and handed to the tracer's
                # prefill-chunk events via `_last_wall_s`.
                dt = time.perf_counter() - t0
                self._site_wall[site] = self._site_wall.get(site, 0.0) + dt
                self._last_wall_s = dt
                return out
            except Exception as e:
                kind = classify(e)
                if kind is None:
                    raise  # not a device fault: bugs stay loud
                injected = isinstance(e, (InjectedTransientError,
                                          InjectedResourceExhausted))
                consumed = (None if injected
                            else self._donated_by_site.get(site))
                if kind == "oom":
                    self._enter_degraded()
                    raise _SlotStateLost(site, e, consumed) from e
                if consumed is not None:
                    raise _SlotStateLost(site, e, consumed) from e
                attempt += 1
                if attempt > self._max_retries:
                    raise _SlotStateLost(site, e) from e
                self.metrics.record_retry(site)
                self._tracer.on_retry(self._cur_step, site, attempt)
                self._backoff_sleep(
                    self._retry_backoff_s * (2 ** (attempt - 1)))

    def _enter_degraded(self) -> None:
        """OOM response: flush every unpinned prefix block (the one
        large sheddable HBM consumer), stop donations, keep serving on
        the cold path. Live slots' pinned chains stay — their gathered
        copies are private and their index entries must survive until
        unpin. A repeat OOM pushes the re-arm time out."""
        now = self._clock()
        if not self._degraded:
            self._degraded = True
            self._degraded_entered_s = now
            self.metrics.record_degraded_entry()
            self._tracer.on_degraded_entry(self._cur_step)
            if self._prefix_on:
                self._prefix.flush_unpinned()
        self._degraded_until_s = now + self._degraded_cooldown_s

    def _maybe_rearm_degraded(self) -> None:
        now = self._clock()
        if self._degraded and now >= self._degraded_until_s:
            self._degraded = False
            self.metrics.record_degraded_exit(now - self._degraded_entered_s)
            self._tracer.on_degraded_exit(
                self._cur_step, now - self._degraded_entered_s)

    def _reset_prefix_pool(self) -> None:
        """A REAL failure of the donating scatter may have consumed the
        resident pool buffers: reallocate them (same shapes — nothing
        recompiles) and start a fresh index, since every stored chain
        points into the dead storage. Live slots keep decoding — their
        gathered copies are private — and their pins die with the old
        tree."""
        if not self._prefix_on:
            return
        self._pool = kv_block_pool(self._dec, self._prefix.num_blocks,
                                   self.prefix_block_size)
        self._prefix = RadixPrefixCache(self.prefix_block_size,
                                        self._prefix.num_blocks)
        self._slot_nodes = [None] * self.max_slots
        if self._host is not None:
            # The old index died wholesale WITHOUT demotion (its
            # storage may be consumed); the fresh one demotes again.
            # Host-tier contents are independent host copies and
            # survive the rebuild — still promotable.
            self._prefix.on_evict = self._demote_blocks

    def _reset_paged_pool(self) -> None:
        """Rebuild the paged world after its one donated tree may have
        been consumed (or live KV presumed lost): fresh pool tree (same
        shapes — nothing recompiles), fresh index (every stored chain
        pointed into the dead storage), all tables back to scratch,
        all private ownership dropped. Callers park/replay the live
        slots FIRST — their KV lived here."""
        self._cache = paged_decode_cache(self._dec, self._prefix.num_blocks,
                                         self.prefix_block_size)
        if self._draft_on:
            # The draft tree shares the block-id space: a pool reset
            # retires its storage too (replay rebuilds both trees).
            self._dcache = paged_decode_cache(self._ddec,
                                              self._prefix.num_blocks,
                                              self.prefix_block_size)
        self._prefix = RadixPrefixCache(self.prefix_block_size,
                                        self._prefix.num_blocks)
        self._tables[:] = 0
        self._private = [[] for _ in range(self.max_slots)]
        self._slot_nodes = [None] * self.max_slots
        if self._host is not None:
            # Same rule as the row-mode reset: the dead index demoted
            # nothing, the fresh one does; host copies survive.
            self._prefix.on_evict = self._demote_blocks

    def _recover_consumed(self, lost: _SlotStateLost) -> None:
        """Rebuild whatever resident donated tree a real mid-dispatch
        error may have eaten (`_SlotStateLost.consumed`). The row cache
        is rebuilt unconditionally by the admission unwind; the slot
        pool rebuild doubles as a full live-slot replay. In PAGED mode
        every consuming site donates the ONE pool tree holding all
        live KV, so recovery is always the full live-slot replay
        (`_lose_live_slots` parks, resets the paged world, and
        requeues)."""
        if lost.consumed == "cache":
            self._lose_live_slots()
        elif lost.consumed == "pool":
            if self._paged:
                self._lose_live_slots()
            else:
                self._reset_prefix_pool()

    def _park_slot(self, slot_id: int) -> None:
        """Park a vacated row: position 0, greedy params. Its future
        junk writes land at position 0 and the next admit overwrites
        the whole cache row anyway (paged: the table row goes all-
        scratch, so junk lands in the sink, and the slot's PRIVATE
        blocks — tail + generated tokens, never shared — return to the
        free list; donated prompt blocks stay cached under the radix
        index, unpinned below)."""
        self._slots[slot_id] = None
        if self._tenant_on:
            # Release the slot's adapter pin (the weights stay resident
            # — that's the point — but become LRU-evictable once no
            # live slot needs them) and reset the grammar state: an
            # all-True mask is a bitwise logits pass-through, so the
            # parked row's junk tick behaves exactly as before.
            self._release_adapter(self._arow[slot_id])
            self._arow[slot_id] = 0
            if not self._masks[slot_id].all():
                self._masks[slot_id, :] = True
                self._masks_dirty = True
            if self._masks_w is not None \
                    and not self._masks_w[slot_id].all():
                self._masks_w[slot_id, :, :] = True
                self._masks_w_dirty = True
            self._fsms[slot_id] = None
        if self._paged:
            if self._private[slot_id]:
                self._prefix.release(self._private[slot_id])
                self._private[slot_id] = []
            self._tables[slot_id, :] = 0
        if self._slot_nodes[slot_id] is not None:
            # Release the request's pin on its prefix chain: the blocks
            # stay cached (that's the point) but become LRU-evictable
            # once no live slot or deeper chain needs them.
            self._prefix.unpin(self._slot_nodes[slot_id])
            self._slot_nodes[slot_id] = None
        self._positions[slot_id] = 0
        self._tokens[slot_id] = 0
        self._temps[slot_id] = 0.0
        self._top_ks[slot_id] = 0
        self._top_ps[slot_id] = 2.0

    def _mark_replay(self, handle: RequestHandle) -> bool:
        """Charge one replay against ``handle``; True = requeue it for
        a slot-state rebuild, False = replay budget exhausted, request
        settled FAILED/ERROR (the engine keeps serving everyone
        else)."""
        handle.replays += 1
        handle.replay_pending = []
        if handle.replays > self._max_replays:
            handle.state = RequestState.FAILED
            handle.finish_reason = FinishReason.ERROR
            handle.finish_s = self._clock()
            self.metrics.record_finish(FinishReason.ERROR.value,
                                       handle.request.priority.value)
            self._tracer.on_replay(handle, self._cur_step, False)
            self._tracer.on_finish(handle, FinishReason.ERROR.value)
            return False
        self.metrics.record_replay()
        self._tracer.on_replay(handle, self._cur_step, True)
        return True

    def _lose_live_slots(self) -> None:
        """The fused tick's retry budget ran out: every live slot's KV
        must be presumed gone (the pooled cache is donated through the
        tick). Reallocate the pool cache (same shapes — nothing
        recompiles), release every pin, and requeue the live requests
        FCFS-front for replay; each rebuilds token-exactly from prompt
        + emitted tokens at its re-admission."""
        lost = [(sid, h) for sid, h in enumerate(self._slots)
                if h is not None]
        requeue: List[RequestHandle] = []
        for sid, handle in lost:
            self._park_slot(sid)  # releases pins/private into the OLD index
            if self._mark_replay(handle):
                requeue.append(handle)
        if self._paged:
            # A parked mid-prefill slice holds private ids and a pinned
            # node of the index about to be retired: DROP it without
            # releasing (the whole old index dies with the reset — a
            # release would double-own the ids in the fresh free list).
            # Its handle is still at the head of `_admitting`, so the
            # next step re-admits it from scratch against the fresh
            # pool, token-exactly. Its ADAPTER pin is different: the
            # adapter pool does NOT die with the paged reset, so the
            # pin unwinds normally (re-admission re-acquires).
            if self._slice is not None:
                self._release_adapter(self._slice.get("arow", 0))
            self._slice = None
            # The pool held every live stream's KV (and the cached
            # chains): rebuild the whole paged world — same shapes,
            # nothing recompiles.
            self._reset_paged_pool()
        else:
            self._cache = slot_decode_cache(self._dec, self.max_slots)
        self.scheduler.requeue_front(requeue)

    def _expired(self, handle: RequestHandle, now: float) -> bool:
        return (handle.request.deadline_s is not None
                and now - handle.arrival_s > handle.request.deadline_s)

    def _reap(self) -> None:
        """Cancellations and deadlines, checked at tick granularity."""
        now = self._clock()
        for sid, handle in enumerate(self._slots):
            if handle is None:
                continue
            if handle.cancelled:
                self._evict(sid, RequestState.CANCELLED,
                            FinishReason.CANCELLED)
            elif self._expired(handle, now):
                self._evict(sid, RequestState.TIMED_OUT,
                            FinishReason.TIMED_OUT)

    def _match_blocks(self, prompt) -> int:
        """Cap on the matchable chain for one prompt (blocks): leave at
        least one suffix token, never exceed the gather vector."""
        return min(self._match_cap, (len(prompt) - 1) // self.prefix_block_size)

    def _prefill_cost(self, handle) -> int:
        """Admission-budget charge: the UNCACHED suffix length (a cached
        prefix costs no prefill work). A pop-time estimate — the match
        also refreshes the chain's LRU stamp, so a same-tick eviction
        stealing it needs a fully-pinned pool; if that happens the
        request simply re-prefills more than charged (see
        ``FCFSScheduler.admit``). Degraded mode charges the full prompt
        (the cache is not consulted on the cold path)."""
        prompt = handle.request.prompt
        if self._degraded or not self._prefix_on:
            cost = len(prompt)
        else:
            match = self._prefix.match(
                prompt, max_blocks=self._match_blocks(prompt))
            cost = len(prompt) - match.n_blocks * self.prefix_block_size
            # Tiered KV cache (ISSUE 13): blocks the host tier will
            # promote cost an H2D transfer, not a prefill — charge them
            # at promote_tokens_per_block instead of block_size tokens
            # (the adapter_load_tokens precedent: real admission-path
            # work, priced at what it actually is). Same pop-time-
            # estimate caveat as the prefix charge.
            if self._host is not None:
                h = self._host.match_depth(
                    prompt, match.n_blocks,
                    self._match_blocks(prompt) - match.n_blocks)
                if h > 0:
                    cost -= h * self.prefix_block_size
                    cost += h * self._host_promote_tokens
        # Tenancy-aware budget (ISSUE 9): a COLD adapter load is real
        # admission-path work (a host->device factor transfer), so it
        # charges like an uncached suffix; a resident adapter — like a
        # cached prefix — charges nothing. Pop-time estimate with the
        # same caveat as the prefix charge: a same-tick eviction can
        # make the real work exceed it, which costs latency, never
        # correctness.
        if (self._tenant_on and handle.request.adapter is not None
                and self._apool.row_of(handle.request.adapter) is None):
            cost += int(self._tenant.adapter_load_tokens)
        # Speculative engines charge a replay's catch-up re-feed against
        # the budget at the ACCEPTED token count — the emitted tokens
        # that really must re-enter the cache — never the drafted
        # (spec_k+1)-wide compute the verify window spends reaching
        # them (`scheduler.admit`'s accepted-not-drafted contract).
        if self._spec_on and handle.tokens:
            cost += len(handle.tokens)
        return cost

    # ---------------------------------------------------- tiered KV cache
    def _demote_blocks(self, victims) -> None:
        """``radix.on_evict`` hook — eviction becomes demotion (module
        docstring): spill the dying blocks' K/V D2H into the host tier
        when their chains are reuse-worthy. The whole reclaim pass
        moves through the jitted whole-tree gather (``_demote_p``,
        one dispatch + one device sync; a read — the pool is never
        copied, the one program traces at warmup, and demotion sits
        on the admission path, where per-block eager dispatches
        measured ~10x slower). Opportunistic by design: a refused or
        failed spill
        degrades to the old free-and-recompute path, never to an
        error, and degraded mode spills nothing (the OOM flush
        additionally bypasses this hook wholesale)."""
        if self._degraded:
            return
        keep: List[tuple] = []
        for node in victims:
            if not self._host.spill_worthy(self._prefix.chain_depth(node)):
                continue
            tokens = self._prefix.chain_tokens(node)
            if self._host.has_block(tokens):
                continue  # kept across a promotion: nothing to move
            keep.append((tokens, node.block_id))
        if not keep:
            return
        try:
            blocks = self._gather_blocks_host(
                [bid for _, bid in keep])
        except Exception as e:  # noqa: BLE001 - device faults only
            if classify(e) is None:
                raise  # not a device fault: bugs stay loud
            return
        for (tokens, _), data in zip(keep, blocks):
            if self._host.store(tokens, data):
                self.metrics.record_host_spill(self._host.bytes_resident)

    def _gather_blocks_host(self, block_ids) -> List[Dict[str, np.ndarray]]:
        """Pool blocks ``block_ids`` as per-block host payload dicts
        keyed by leaf path — the demotion (and chain-export) D2H read:
        the jitted whole-tree gather (``_demote_p``, fixed
        ``match_cap`` width, scratch-padded — one dispatch per chunk,
        traced once), one ``device_get`` for everything, then
        host-side splits (copies, so an evicted sibling cannot pin
        the batch buffer alive). Padded tail slices read scratch junk
        and are simply not taken."""
        target = self._cache if self._paged else self._pool
        bs = self.prefix_block_size
        w = self._match_cap
        n = len(block_ids)
        staged = []
        for c in range(0, n, w):
            ids = np.zeros(w, np.int32)
            chunk = block_ids[c:c + w]
            ids[:len(chunk)] = chunk
            staged.append(self._demote_p(target, ids))
        pulled = jax.device_get(staged)
        out: List[Dict[str, np.ndarray]] = []
        for c, st in zip(range(0, n, w), pulled):
            out.extend({key: arr[..., j * bs:(j + 1) * bs, :].copy()
                        for key, arr in st.items()}
                       for j in range(min(w, n - c)))
        return out

    def _assemble_promote_rows(self, blocks: List[Dict[str, np.ndarray]]):
        """The ``host_promote`` scatter's source tree: per KV leaf a
        host row ``[1, ..., match_cap*bs, D]`` with the promoted
        payloads at ``[0, k*bs)`` and ZEROS beyond — those positions
        scatter into padded scratch ids, and the paged scratch block
        must stay zero (an ``np.empty`` tail measurably corrupted
        paged streams whose tables park on the sink). Non-KV leaves
        are scalar placeholders. Fixed width, so the program traces
        once."""
        bs = self.prefix_block_size
        target = self._cache if self._paged else self._pool

        def _leaf(path, leaf):
            if leaf.ndim < 3:
                return np.zeros((), np.int32)
            row = np.zeros((1,) + tuple(leaf.shape[1:-2])
                           + (self._match_cap * bs, leaf.shape[-1]),
                           leaf.dtype)
            key = jax.tree_util.keystr(path)
            for j, b in enumerate(blocks):
                row[..., j * bs:(j + 1) * bs, :] = b[key]
            return row

        return jax.tree_util.tree_map_with_path(_leaf, target)

    def _promote_host_chain(self, prompt: np.ndarray, handle=None) -> int:
        """Promotion (module docstring): extend the device match with
        host-tier blocks — allocate device ids under the ANCHOR's pin,
        scatter the payloads H2D through the ``host_promote`` program,
        and attach the ids to the radix index, so the admission that
        follows simply matches a deeper chain. Self-unwinding: every
        exit (allocator shortfall, injected fault, real consumed-pool
        error) releases its ids and both pins exactly — the host-tier
        pin through the same discipline device chains use. Returns the
        promoted block count."""
        if self._degraded:
            return 0
        cap = self._match_blocks(prompt)
        match = self._prefix.match(prompt, max_blocks=cap)
        if match.n_blocks >= cap:
            return 0
        tip = self._host.pin_chain(prompt, match.n_blocks,
                                   cap - match.n_blocks)
        promoted = 0
        if tip is not None:
            try:
                promoted = self._promote_pinned(prompt, match, tip,
                                                handle)
            finally:
                self._host.unpin(tip)
        return promoted

    def _promote_pinned(self, prompt: np.ndarray, match, tip,
                        handle) -> int:
        """The H2D half of a promotion, under the caller's host-tier
        pin: allocate device ids beneath the ANCHOR's pin (eviction
        must not steal the chain the ids extend from), dispatch the
        scatter, attach the ids. Every failure path releases ids and
        the anchor pin exactly."""
        bs = self.prefix_block_size
        m = match.n_blocks
        anchor = match.node
        self._prefix.pin(anchor)
        try:
            ids = self._prefix.allocate(tip.depth - m)
            k = len(ids)
            if k == 0:
                self._prefix.release(ids)
                return 0
            node = tip
            while node.depth > m + k:  # allocator came up short:
                node = node.parent     # promote the prefix that fits
            rows = self._assemble_promote_rows(
                self._host.chain_data(node, k))
            dids = np.zeros(self._match_cap, np.int32)
            dids[:k] = ids
            target = self._cache if self._paged else self._pool
            try:
                out = self._device_call("host_promote", self._promote_p,
                                        target, rows, dids)
            except _SlotStateLost:
                self._prefix.release(ids)
                raise
            if self._paged:
                self._cache = out
            else:
                self._pool = out
            self._prefix.extend(anchor, prompt[m * bs:(m + k) * bs], ids)
            self.metrics.record_host_promotion(
                k, k * self._host_promote_tokens,
                self._host.bytes_resident)
            self._tracer.on_prefill_chunk(handle, "host_promote", m * bs,
                                          k * bs, self._last_wall_s)
            return k
        finally:
            self._prefix.unpin(anchor)

    def _prefill_into_row(self, prompt: np.ndarray, handle=None, aid=0):
        """Prefill one prompt into a row cache, reusing any cached
        prefix: gather the matched chain into the resident row buffers,
        chunk-prefill the suffix, donate the prompt's uncovered full
        blocks, pin the chain. ``handle`` is the admission's request
        (tracing only — each dispatch lands on its span); ``aid`` the
        tenant adapter pool row (0 = base model, ignored on a plain
        engine). Returns ``(row_cache, last_logits,
        pinned_node_or_None)``."""
        plen = prompt.size
        bs = self.prefix_block_size
        tr = self._tracer
        if not self._prefix_on:
            padded = np.zeros((1, self.prefill_len), np.int32)
            padded[0, :plen] = prompt
            row, logits = self._device_call(
                "prefill", self._prefill_p, self._params, padded, plen,
                *self._chunk_extra(aid))
            tr.on_prefill_chunk(handle, "prefill", 0, plen,
                                self._last_wall_s)
            return row, logits, None
        if self._host is not None:
            # Tiered admission: promote any host-tier continuation of
            # the device match FIRST, so the match below simply sees a
            # deeper chain (self-unwinding; a promotion fault escalates
            # exactly like any admission dispatch).
            self._promote_host_chain(prompt, handle)
        # Degraded mode (post-OOM cool-down): the cache is neither
        # consulted nor grown — a pure cold chunked prefill, so serving
        # continues while the pool stays shed.
        use_prefix = not self._degraded
        if use_prefix:
            match = self._prefix.match(prompt,
                                       max_blocks=self._match_blocks(prompt))
            n_cached = match.n_blocks * bs
            tr.on_prefix_match(handle, match.n_blocks, n_cached)
        else:
            match, n_cached = None, 0
        if n_cached > 0:
            ids = np.zeros(self._match_cap, np.int32)  # scratch-padded
            ids[:match.n_blocks] = match.block_ids
            row = self._device_call("gather", self._gather_p,
                                    self._pool, ids, self._row)
            tr.on_prefill_chunk(handle, "gather", 0, n_cached,
                                self._last_wall_s)
            self._row = row
        else:
            # Full miss: no gather dispatch — the chunks overwrite
            # [0, plen) of the resident row and everything beyond parks
            # past the position counter the insert stamps.
            row = self._row
        # Fixed-width chunks over the suffix (shared width policy —
        # :meth:`_chunk_loop`). The resident row is adopted after EVERY
        # dispatch (each chunk donates it), so a mid-chunk fault
        # escalation never leaves `self._row` pointing at a consumed
        # buffer.
        row_box = [row]

        def _dispatch(site, prog, chunk_toks, w, off):
            row_box[0], lg = self._device_call(
                site, prog, self._params, row_box[0], chunk_toks,
                np.int32(w), np.int32(off), *self._chunk_extra(aid))
            self._row = row_box[0]
            return lg

        logits = self._chunk_loop(prompt, n_cached, handle, _dispatch)
        row = row_box[0]
        if not use_prefix:
            return row, logits, None
        node = self._donate_tail(prompt, row, match, n_cached)
        # Adopt the row buffers for the next admission (the slot insert
        # COPIES the row, so reuse is safe and saves a fresh full-length
        # cache allocation per admission).
        self._row = row
        return row, logits, node

    def _donate_tail(self, prompt: np.ndarray, row, match,
                     n_cached: int):
        """Donate the prompt's uncovered FULL blocks and pin the chain;
        ``match`` must be CURRENT (the sliced path re-matches at finish
        time — ticks ran between its slices and an OOM flush could have
        detached a start-time node). First descend any chain ALREADY
        stored past the (capped) gather match — those chunks must not
        have fresh blocks allocated, or a full pool would evict useful
        blocks to supply ids the index hands straight back. Pin before
        allocating so this admission's own eviction pass can never free
        the blocks just gathered from. Donation order is
        write-then-index: the pool scatter runs BEFORE ``extend``
        attaches the ids, so a fault mid-donation can never leave the
        index pointing at blocks that hold junk — the unwind releases
        the unattached ids and the pin, restoring the pre-admission
        refcount baseline exactly. Returns the pinned node."""
        bs = self.prefix_block_size
        plen = len(prompt)
        node, stored_blocks = self._prefix.descend(
            match.node, prompt, match.n_blocks)
        self._prefix.pin(node)
        want = plen // bs - stored_blocks
        if want > 0:
            new_ids = self._prefix.allocate(min(want, self._donate_cap))
            if new_ids:
                dids = np.zeros(self._donate_cap, np.int32)
                dids[:len(new_ids)] = new_ids
                try:
                    self._pool = self._device_call(
                        "donate", self._donate_p, self._pool, row, dids,
                        np.int32(stored_blocks))
                except _SlotStateLost:
                    self._prefix.release(new_ids)
                    self._prefix.unpin(node)
                    raise
                tip = self._prefix.extend(
                    node,
                    prompt[stored_blocks * bs:
                           (stored_blocks + len(new_ids)) * bs],
                    new_ids)
                self._prefix.unpin(node)
                self._prefix.pin(tip)
                node = tip
        self.metrics.record_prefix_lookup(
            n_cached, blocks_live=self._prefix.blocks_live,
            evictions=self._prefix.evictions)
        return node

    def _chunk_loop(self, prompt: np.ndarray, off: int, handle,
                    dispatch):
        """The whole-prompt suffix chunk loop, ONE width policy for the
        row and paged admissions (coarse cost model — each apply pays a
        fixed dispatch cost plus per-token compute): a long remainder
        (>= 3/4 of the wide width) takes the WIDE program in one apply,
        so a cold prompt costs what the one-shot prefill did; short
        suffixes — the prefix-hit case — take narrow chunks and pay
        only for the uncached tail. ``dispatch(site, prog, chunk_toks,
        w, off)`` runs the program, adopts whatever resident tree it
        donated, and returns the logits."""
        plen = int(prompt.size)
        logits = None
        while off < plen:
            rem = plen - off
            if self._has_wide and 4 * rem >= 3 * self.prefill_len:
                width, prog = self.prefill_len, self._chunk_wide_p
                site = "chunk_prefill_wide"
            else:
                width, prog = self._chunk, self._chunk_p
                site = "chunk_prefill"
            w = min(width, rem)
            chunk_toks = np.zeros((1, width), np.int32)
            chunk_toks[0, :w] = prompt[off:off + w]
            logits = dispatch(site, prog, chunk_toks, w, off)
            self._tracer.on_prefill_chunk(handle, site, off, w,
                                          self._last_wall_s)
            off += w
        return logits

    def _draft_prefill_loop(self, prompt: np.ndarray, off: int,
                            table) -> None:
        """Chunk-prefill the prompt's uncached suffix through the DRAFT
        model into its pool tree (narrow chunks only — the draft model
        is small by design, so a wide twin would double the program set
        for marginal gain). Same offsets and blocks as the target's
        chunks: a donated shared-prefix block carries valid draft K/V
        for every future hit, exactly like the target K/V it sits
        beside."""
        plen = int(prompt.size)
        off = int(off)
        while off < plen:
            w = min(self._chunk, plen - off)
            chunk_toks = np.zeros((1, self._chunk), np.int32)
            chunk_toks[0, :w] = prompt[off:off + w]
            self._dcache = self._device_call(
                "draft_prefill", self._dchunk_p, self._dparams,
                self._dcache, chunk_toks, np.int32(w), np.int32(off),
                table)
            off += w

    # ------------------------------------------------- paged admission
    def _paged_match_and_allocate(self, prompt: np.ndarray, handle=None):
        """The shared front half of every paged admission (whole-prompt
        AND sliced): match → pin → allocate private suffix blocks →
        stamp the table row. ONE definition because the ordering is
        safety-critical — the pin must land BEFORE any allocation (with
        no private copy, an eviction stealing a matched block
        mid-admission would reach under this very request) and a
        shortfall must unwind pin + ids exactly. Degraded mode skips
        the index entirely (all blocks private). Returns
        ``(pinned_node_or_None, n_matched_blocks, table_row [T],
        private_ids)``; raises :class:`_SlotStateLost` unwound on
        shortfall."""
        plen = int(prompt.size)
        bs = self.prefix_block_size
        table_row = np.zeros(self._table_width, np.int32)
        node, m = None, 0
        if self._host is not None:
            # Tiered admission (the row path's twin): host-tier blocks
            # promote into the pool first, so the match below pins the
            # deeper chain in place.
            self._promote_host_chain(prompt, handle)
        if not self._degraded:
            match = self._prefix.match(
                prompt, max_blocks=self._match_blocks(prompt))
            m = match.n_blocks
            if m > 0:
                node = match.node
                self._prefix.pin(node)
                table_row[:m] = match.block_ids
            self._tracer.on_prefix_match(handle, m, m * bs)
        need = -(-plen // bs) - m
        private = list(self._prefix.allocate(need)) if need > 0 else []
        if len(private) < need:
            # Everything unpinned is already gone and it still doesn't
            # fit — undo and escalate; the unwind charges a replay.
            self._prefix.release(private)
            if node is not None:
                self._prefix.unpin(node)
            raise _SlotStateLost(
                "paged_alloc",
                RuntimeError(
                    f"block pool exhausted ({need} blocks needed, "
                    f"{len(private)} free/evictable)"))
        table_row[m:m + len(private)] = private
        return node, m, table_row, private

    def _prefill_paged(self, prompt: np.ndarray, handle=None, aid=0):
        """The paged twin of :meth:`_prefill_into_row`: a prefix hit
        PINS the matched chain and points the slot's block table at it
        in place (no gather copy), private blocks are allocated for the
        suffix, and the chunk programs write K/V straight into those
        pool blocks. ``aid`` as in :meth:`_prefill_into_row`. Returns
        ``(last_logits, pinned_node_or_None, table_row [T] np.int32,
        private_ids)``; raises :class:`_SlotStateLost` with its own
        resources unwound."""
        node, m, table_row, private = self._paged_match_and_allocate(
            prompt, handle)
        n_cached = m * self.prefix_block_size
        use_prefix = not self._degraded
        t1 = table_row[None]  # [1, T] — the chunk programs' view

        def _dispatch(site, prog, chunk_toks, w, off):
            self._cache, lg = self._device_call(
                site, prog, self._params, self._cache, chunk_toks,
                np.int32(w), np.int32(off), t1, *self._chunk_extra(aid))
            return lg

        try:
            logits = self._chunk_loop(prompt, n_cached, handle, _dispatch)
            if self._draft_on:
                self._draft_prefill_loop(prompt, n_cached, t1)
        except _SlotStateLost:
            # Injected faults consumed nothing: hand the resources
            # back. A REAL consumed-pool error resets the whole paged
            # world right after (the unwind's _recover_consumed), which
            # retires this index anyway — releasing first is harmless.
            self._prefix.release(private)
            if node is not None:
                self._prefix.unpin(node)
            raise
        if n_cached > 0:
            self.metrics.record_copy_avoided(
                n_cached * self._kv_token_bytes)
        if use_prefix:
            node = self._donate_tail_paged(prompt, node, table_row,
                                           private, m)
            self.metrics.record_prefix_lookup(
                n_cached, blocks_live=self._prefix.blocks_live,
                evictions=self._prefix.evictions)
        return logits, node, table_row, private

    def _donate_tail_paged(self, prompt: np.ndarray, node, table_row,
                           private: List[int], m: int):
        """Donation with ZERO copies: the prompt's full blocks are
        already written in the pool — hand their ownership to the radix
        index (they become the stored chain) and keep the slot's pin.
        When a chain segment is ALREADY stored (the block-aligned-tail
        case the copy engine deduped with `descend`), the slot's table
        is SWAPPED onto the stored blocks — token-identity implies
        bit-identical KV under the position-absolute cache contract —
        and the duplicate private blocks go back to the free list, so a
        repeat prompt holds the pool at its deduplicated size. Returns
        the pinned chain tip (or ``node`` unchanged when the prompt has
        no full blocks)."""
        bs = self.prefix_block_size
        plen = len(prompt)
        full = plen // bs
        anchor = node if node is not None else self._prefix.match(
            prompt, max_blocks=0).node
        deeper, stored = self._prefix.descend(anchor, prompt, m)
        if stored > m:
            chain = self._prefix.chain_ids(deeper)
            for j in range(m, stored):
                mine = int(table_row[j])
                table_row[j] = chain[j]
                private.remove(mine)
                self._prefix.release([mine])
        if deeper is not anchor or node is None:
            if node is not None:
                self._prefix.unpin(node)
            self._prefix.pin(deeper)
        node = deeper
        if full > stored:
            ids = [int(table_row[j]) for j in range(stored, full)]
            tip = self._prefix.extend(
                node, prompt[stored * bs:full * bs], ids)
            chain = self._prefix.chain_ids(tip)
            for j in range(stored, full):
                # extend normally attaches our block; on a (defensive)
                # dedup it freed ours — swap the table either way.
                private.remove(int(table_row[j]))
                table_row[j] = chain[j]
            self._prefix.unpin(node)
            self._prefix.pin(tip)
            node = tip
        return node

    def _admit(self) -> None:
        if self._slice_tokens is not None:
            # The per-STEP prefill allowance: every chunk dispatched on
            # behalf of admissions this step draws from it, so the
            # decode tick below is never more than one allowance away.
            self._slice_budget_left = self._slice_tokens
        if self._slice is not None:
            # A prefill is mid-flight from an earlier step: the resident
            # row is ITS pipeline — advance it first; only if it
            # finishes (or settles) may new admissions start.
            if not self._continue_slice():
                return
        free = self._free_slot_ids()
        if not free:
            free = self._preempt_for_interactive()
            if not free:
                return

        def _queued_cancel(handle):
            handle.finish_s = self._clock()
            self.metrics.record_finish(FinishReason.CANCELLED.value,
                                       handle.request.priority.value)
            self._tracer.on_finish(handle, FinishReason.CANCELLED.value)

        def _queued_expired(handle):
            # Died in the queue, shed by the scheduler at pop time:
            # never pay its prefill (the most expensive dispatch) nor
            # emit a post-deadline token — under sustained overload
            # this is exactly where deadlines earn their keep. The
            # slot stays free for the next admission.
            handle.finish_s = self._clock()
            self.metrics.record_finish(FinishReason.DEADLINE.value,
                                       handle.request.priority.value)
            self._tracer.on_deadline_shed(handle)
            self._tracer.on_finish(handle, FinishReason.DEADLINE.value)

        # The suffix-priced (and adapter-load-priced, and spec-replay-
        # priced) cost_fn walks the radix tree per pop; only pay that
        # when a budget actually consumes the result.
        use_cost = ((self._prefix_on or self._tenant_on or self._spec_on)
                    and self.scheduler.prefill_token_budget is not None)
        # A kill mid-admission can leave a handle parked in
        # `_admitting`; it owns the first free slot before anything new
        # is popped.
        self._admitting.extend(self.scheduler.admit(
            len(free) - len(self._admitting), on_cancelled=_queued_cancel,
            on_expired=_queued_expired, now_fn=self._clock,
            cost_fn=self._prefill_cost if use_cost else None))
        while self._admitting and free:
            if (self._slice_tokens is not None
                    and self._slice_budget_left <= 0):
                break  # this step's prefill allowance is spent
            handle = self._admitting[0]
            sid = free.pop(0)
            try:
                if self._slice_tokens is not None:
                    if not self._start_slice(sid, handle):
                        return  # pending: handle stays in _admitting
                else:
                    self._admit_one(sid, handle)
            except _SlotStateLost as lost:
                free.insert(0, sid)
                self._unwind_admission(lost, handle)
            self._admitting.popleft()

    def _paged_append_blocks(self) -> None:
        """Before a paged tick: every live slot about to write at a
        block boundary gets a fresh PRIVATE block appended to its
        table (block-table growth is a runtime-array update — the
        in-place append that replaces the copy engine's whole-row
        insert). Allocation LRU-evicts unpinned cached chains under
        pressure; with the pool at its validated floor it cannot fail
        for a live stream, but if a mis-sized explicit pool ever does,
        the slot is parked and REPLAYED rather than writing into a
        shared block."""
        # A speculative tick writes the whole verify window, positions
        # pos .. pos+spec_k: every block that extent touches must be
        # writable before the dispatch (writes past the table deflect
        # to scratch, so the extent clamps at the table edge).
        span = self._spec_k if self._spec_on else 0
        bs = self.prefix_block_size
        for sid, handle in enumerate(self._slots):
            if handle is None:
                continue
            lo = int(self._positions[sid]) // bs
            hi = min((int(self._positions[sid]) + span) // bs,
                     self._table_width - 1)
            need = [blk for blk in range(lo, hi + 1)
                    if blk >= 0 and self._tables[sid, blk] == 0]
            if not need:
                continue
            ids = self._prefix.allocate(len(need))
            if len(ids) < len(need):
                self._prefix.release(ids)
                self._park_slot(sid)
                if self._mark_replay(handle):
                    self.scheduler.requeue_front([handle])
                continue
            for blk, bid in zip(need, ids):
                self._tables[sid, blk] = bid
                self._private[sid].append(bid)

    def _preempt_for_interactive(self) -> List[int]:
        """Every slot is busy and ``interactive`` work is queued: park
        running BEST_EFFORT streams (fewest tokens first — the
        cheapest replay) and requeue them through the normal lane.
        The paused stream resumes token-exactly later via the replay
        admission (prompt re-prefilled, emitted tokens re-fed) — the
        fault-recovery machinery doing scheduling duty. A handle is
        preempted at most ``preempt_cap`` times, so a best_effort
        stream can stall under pressure but never thrash forever; only
        ACTUAL interactive submissions trigger this (aging promotions
        and replay-lane entries don't), so preemption cannot cascade.
        Returns the freed slot ids."""
        if self._preempt_cap < 1:
            return []
        want = self.scheduler.queued_of_class(Priority.INTERACTIVE)
        if want < 1:
            return []
        victims = sorted(
            ((sid, h) for sid, h in enumerate(self._slots)
             if h is not None
             and h.request.priority is Priority.BEST_EFFORT
             and h.preemptions < self._preempt_cap
             and not h.replay_pending),
            key=lambda p: len(p[1].tokens))
        freed: List[int] = []
        for sid, victim in victims[:want]:
            victim.preemptions += 1
            self.metrics.record_preemption()
            self._tracer.on_preempt(victim, self._cur_step)
            self._park_slot(sid)
            self.scheduler.requeue(victim)
            freed.append(sid)
        return freed

    def _unwind_admission(self, lost: _SlotStateLost,
                          handle: RequestHandle) -> None:
        """A dispatch died during this handle's admission. The
        per-request unwind already released any pin; the slot never
        became live. Rebuild the resident row buffers defensively (a
        real device error may have consumed them via donation) — same
        shapes, nothing recompiles — rebuild anything else the failed
        dispatch consumed (slot pool → live-slot replay; block pool →
        fresh pool + index), and charge the request a replay."""
        sl, self._slice = self._slice, None
        if sl is not None:
            # A parked slice owns its adapter pin (the whole-prompt
            # paths release their own before raising, and then
            # self._slice was never set). The adapter pool does NOT
            # die with any KV rebuild, so the pin must unwind exactly.
            self._release_adapter(sl.get("arow", 0))
        if self._paged:
            # A parked slice still owns its pin + private blocks (the
            # whole-prompt paged path releases its own before raising,
            # and then self._slice was never set).
            if sl is not None:
                if sl.get("private"):
                    self._prefix.release(sl["private"])
                if sl.get("node") is not None:
                    self._prefix.unpin(sl["node"])
        elif self._prefix_on:
            self._row = jax.tree.map(
                lambda sd: jnp.zeros(sd.shape, sd.dtype),
                _decode_cache_shapes(self._dec, 1))
        self._recover_consumed(lost)
        if self._mark_replay(handle):
            self.scheduler.requeue_front([handle])

    def _admit_one(self, sid: int, handle: RequestHandle) -> None:
        """Admit one popped handle into slot ``sid`` (the whole-prompt
        path; the sliced path is :meth:`_start_slice`). Tenant order:
        the adapter pin + FSM compile land FIRST (so a cold load or an
        unresolvable spec unwinds before any prefill work), and a
        prefill failure releases the pin before escalating — the
        install step owns the pin from there (its own failure path
        releases, its success hands ownership to the slot)."""
        replay = bool(handle.tokens)
        self._tracer.on_admit(handle, sid, replay)
        prompt = np.asarray(handle.request.prompt, np.int32)
        arow, fsm = self._tenant_admit(handle)
        if self._paged:
            try:
                logits, node, table_row, private = self._prefill_paged(
                    prompt, handle, arow)
            except _SlotStateLost:
                self._release_adapter(arow)
                raise
            self._install_slot(sid, handle, None, logits, node,
                               table_row=table_row, private=private,
                               arow=arow, fsm=fsm)
            return
        try:
            row, logits, node = self._prefill_into_row(prompt, handle,
                                                       arow)
        except _SlotStateLost:
            self._release_adapter(arow)
            raise
        self._install_slot(sid, handle, row, logits, node, arow=arow,
                           fsm=fsm)

    # ------------------------------------------------ sliced admission
    def _start_slice(self, sid: int, handle: RequestHandle) -> bool:
        """Begin a time-sliced admission: match + gather now (cheap,
        and the gathered KV copy is private — later evictions cannot
        reach it), then chunk-prefill under the per-step allowance.
        Returns True when the admission completed within this step's
        budget; False parks it in ``self._slice`` to resume next step
        — the decode tick runs in between, which is the whole point."""
        prompt = np.asarray(handle.request.prompt, np.int32)
        replay = bool(handle.tokens)
        self._tracer.on_admit(handle, sid, replay)
        arow, fsm = self._tenant_admit(handle)
        # Pin-ownership tracking: a failure BEFORE the slice dict
        # exists leaves the adapter pin with nobody else to unwind it;
        # once created, the slice (via `_unwind_admission`) or —
        # should the slice finish and the INSTALL fault — the install's
        # own failure path owns the release. `self._slice is None`
        # cannot distinguish "never created" from "created, finished,
        # install faulted" (both are None here), so track creation
        # explicitly or a refcount would underflow.
        created = False
        try:
            if self._paged:
                # Pin + allocate now (host-only, no gather dispatch —
                # the matched blocks are referenced in place); the pin
                # is what keeps the chain under this admission across
                # the decode ticks that run between slices.
                node, m, table_row, private = \
                    self._paged_match_and_allocate(prompt, handle)
                n_cached = m * self.prefix_block_size
                self._slice = {"handle": handle, "sid": sid,
                               "prompt": prompt, "off": n_cached,
                               "n_cached": n_cached, "logits": None,
                               "node": node, "table": table_row,
                               "private": private, "arow": arow,
                               "fsm": fsm}
                created = True
                return self._advance_slice(self._slice)
            if self._host is not None:
                # Tiered sliced admission: promote before the gather so
                # the matched chain below includes the host-tier blocks.
                self._promote_host_chain(prompt, handle)
            n_cached = 0
            if not self._degraded:
                match = self._prefix.match(
                    prompt, max_blocks=self._match_blocks(prompt))
                n_cached = match.n_blocks * self.prefix_block_size
                self._tracer.on_prefix_match(handle, match.n_blocks,
                                             n_cached)
            if n_cached > 0:
                ids = np.zeros(self._match_cap, np.int32)  # scratch-pad
                ids[:match.n_blocks] = match.block_ids
                self._row = self._device_call("gather", self._gather_p,
                                              self._pool, ids, self._row)
                self._tracer.on_prefill_chunk(handle, "gather", 0,
                                              n_cached,
                                              self._last_wall_s)
            self._slice = {"handle": handle, "sid": sid, "prompt": prompt,
                           "off": n_cached, "n_cached": n_cached,
                           "logits": None, "arow": arow, "fsm": fsm}
            created = True
            return self._advance_slice(self._slice)
        except _SlotStateLost:
            if not created:
                self._release_adapter(arow)
            raise

    def _continue_slice(self) -> bool:
        """Resume the parked prefill. Returns True when ``self._slice``
        settled (installed, expired, cancelled, or unwound) — admission
        may continue — and False while it still has chunks to go."""
        sl = self._slice
        handle = sl["handle"]
        now = self._clock()
        if handle.cancelled or self._expired(handle, now):
            # Not in a slot yet, so _reap cannot see it: settle here.
            # The partially-prefilled row is abandoned junk the next
            # admission overwrites (the padded-prefill invariant; in
            # paged mode the private blocks return to the free list,
            # where their junk is unreachable until reallocated and
            # fully rewritten).
            if self._paged:
                if sl.get("private"):
                    self._prefix.release(sl["private"])
                if sl.get("node") is not None:
                    self._prefix.unpin(sl["node"])
            self._release_adapter(sl.get("arow", 0))
            self._slice = None
            if handle.cancelled:
                handle.state = RequestState.CANCELLED
                handle.finish_reason = FinishReason.CANCELLED
            else:
                handle.state = RequestState.TIMED_OUT
                handle.finish_reason = FinishReason.TIMED_OUT
            handle.finish_s = now
            self.metrics.record_finish(handle.finish_reason.value,
                                       handle.request.priority.value)
            self._tracer.on_finish(handle, handle.finish_reason.value)
            self._admitting.popleft()
            return True
        try:
            done = self._advance_slice(sl)
        except _SlotStateLost as lost:
            self._unwind_admission(lost, handle)
            self._admitting.popleft()
            return True
        if done:
            self._admitting.popleft()
        return done

    def _advance_slice(self, sl: Dict[str, object]) -> bool:
        """Dispatch narrow suffix chunks until the prompt is fully
        prefilled or the step's allowance runs out (always at least one
        chunk — progress is guaranteed). The wide program is never used
        here: one huge dispatch is exactly the head-of-line block
        slicing exists to break up."""
        handle, prompt = sl["handle"], sl["prompt"]
        plen = int(prompt.size)
        spent = 0
        while sl["off"] < plen:
            if spent and self._slice_budget_left <= 0:
                return False
            off = int(sl["off"])
            w = min(self._chunk, plen - off)
            chunk_toks = np.zeros((1, self._chunk), np.int32)
            chunk_toks[0, :w] = prompt[off:off + w]
            extra = self._chunk_extra(sl.get("arow", 0))
            if self._paged:
                self._cache, sl["logits"] = self._device_call(
                    "chunk_prefill", self._chunk_p, self._params,
                    self._cache, chunk_toks, np.int32(w), np.int32(off),
                    sl["table"][None], *extra)
                if self._draft_on:
                    # The draft tree advances in lockstep with the
                    # slices (same chunk, same blocks), so fairness and
                    # the budget charge stay one number per slice.
                    self._dcache = self._device_call(
                        "draft_prefill", self._dchunk_p, self._dparams,
                        self._dcache, chunk_toks, np.int32(w),
                        np.int32(off), sl["table"][None])
            else:
                self._row, sl["logits"] = self._device_call(
                    "chunk_prefill", self._chunk_p, self._params,
                    self._row, chunk_toks, np.int32(w), np.int32(off),
                    *extra)
            self._tracer.on_prefill_chunk(handle, "chunk_prefill", off, w,
                                          self._last_wall_s)
            sl["off"] = off + w
            spent += w
            self._slice_budget_left -= w
        self._finish_slice(sl)
        return True

    def _finish_slice(self, sl: Dict[str, object]) -> None:
        """The prompt is fully in the row cache: donate/pin (off a
        FRESH match — decode ticks and possibly an OOM flush ran
        between slices, so a start-time node may be detached), then
        install the slot exactly like the whole-prompt path."""
        handle, sid = sl["handle"], sl["sid"]
        prompt = sl["prompt"]
        if self._paged:
            node = sl["node"]
            if int(sl["n_cached"]) > 0:
                # Recorded at FINISH like the whole-prompt path, so a
                # mid-slice unwind + replay can never double-count.
                self.metrics.record_copy_avoided(
                    int(sl["n_cached"]) * self._kv_token_bytes)
            if not self._degraded:
                # The start-time pin survived the interleaved ticks
                # (flush_unpinned spares pinned chains), so donation
                # descends from it directly. While degraded, the
                # matched blocks stay pinned-but-undonated: the table
                # references them in place, so the pin must outlive
                # the slot either way.
                node = self._donate_tail_paged(
                    prompt, node, sl["table"], sl["private"],
                    int(sl["n_cached"]) // self.prefix_block_size)
                self.metrics.record_prefix_lookup(
                    int(sl["n_cached"]),
                    blocks_live=self._prefix.blocks_live,
                    evictions=self._prefix.evictions)
            self._slice = None
            self._install_slot(sid, handle, None, sl["logits"], node,
                               table_row=sl["table"],
                               private=sl["private"],
                               arow=sl.get("arow", 0), fsm=sl.get("fsm"))
            return
        node = None
        if not self._degraded:
            match = self._prefix.match(
                prompt, max_blocks=self._match_blocks(prompt))
            node = self._donate_tail(prompt, self._row, match,
                                     int(sl["n_cached"]))
        self._slice = None
        self._install_slot(sid, handle, self._row, sl["logits"], node,
                           arow=sl.get("arow", 0), fsm=sl.get("fsm"))

    def _install_slot(self, sid: int, handle: RequestHandle, row, logits,
                      node, table_row=None, private=None, arow=0,
                      fsm=None) -> None:
        """Make a fully-prefilled row live in slot ``sid``. Two shapes:
        a FRESH request samples its first token from the prefill logits
        (that's TTFT); a REPLAYED one (``handle.tokens`` non-empty —
        fault recovery or drain/restore) rebuilt its KV from the
        prompt and re-feeds the emitted tokens through the coming
        ticks, so no token is ever re-sampled or double-streamed.

        Paged mode passes ``table_row``/``private`` instead of ``row``:
        the KV is already where it lives (the pool), so there is no
        insert dispatch at all — installation is the host-side table
        stamp.

        Tenant mode passes ``arow`` (the admission's pinned adapter
        pool row — ownership transfers to the slot here, or is
        released on this method's own failure) and ``fsm`` (the
        compiled constraint automaton): a fresh request samples its
        first token under the FSM's start-state mask and advances; a
        replayed one RE-DERIVES its FSM state from the emitted tokens
        (state, like KV, is a pure function of the stream)."""
        req = handle.request
        plen = len(req.prompt)
        replay = bool(handle.tokens)
        t, k, p = req.sampling.as_arrays()
        fsm_state = None
        if fsm is not None and replay:
            try:
                fsm_state = fsm.advance_many(handle.tokens,
                                             eos_token=self.eos_token)
            except ValueError as e:
                # A replayed stream the automaton rejects (corrupted
                # migration mirror): fail the REQUEST via the replay
                # budget, never the engine.
                self._release_adapter(arow)
                if self._paged and private:
                    self._prefix.release(private)
                if node is not None:
                    self._prefix.unpin(node)
                raise _SlotStateLost("constraint_admit", e) from e
        try:
            if not self._paged:
                self._cache = self._device_call(
                    "insert", self._insert_p, self._cache, row, sid, plen)
            if replay:
                first = handle.tokens[0]
                handle.replay_pending = list(handle.tokens[1:])
            else:
                tok, self._rng = self._device_call(
                    "sample_first", self._sample_first_p, logits,
                    *self._first_mask_args(fsm),
                    np.float32(t), np.int32(k), np.float32(p), self._rng)
                first = int(tok[0])
        except _SlotStateLost:
            if self._paged and private:
                self._prefix.release(private)
            if node is not None:
                self._prefix.unpin(node)
            self._release_adapter(arow)
            raise
        if self._paged:
            self._tables[sid] = table_row
            self._private[sid] = list(private)
        self._slot_nodes[sid] = node
        if not replay:
            now = self._clock()
            handle.tokens.append(first)
            handle.ttft_s = now - handle.arrival_s
            self.metrics.record_first_token(
                handle.ttft_s, handle.request.priority.value)
            self.metrics.record_admission(now)
            self._tracer.on_first_token(handle, handle.ttft_s)
        self._slots[sid] = handle
        self._positions[sid] = plen
        self._tokens[sid] = first
        self._temps[sid] = t
        self._top_ks[sid] = k
        self._top_ps[sid] = p
        if self._spec_on:
            # The drafter's token history: prompt + every emitted token
            # (one for a fresh admission, the full stream for a replay
            # — whose re-feed then drafts from complete history). The
            # row is zeroed first so a previous tenant's tail can never
            # leak into an n-gram match.
            self._hist[sid, :] = 0
            self._hist[sid, :plen] = np.asarray(req.prompt, np.int32)
            n = min(len(handle.tokens), self.model.max_len - plen)
            if n > 0:
                self._hist[sid, plen:plen + n] = handle.tokens[:n]
        if self._tenant_on:
            # The slot now owns the adapter pin (released at park) and
            # the grammar state/mask row the coming ticks read.
            self._arow[sid] = arow
            if fsm is not None:
                if not replay:
                    if self.eos_token is not None \
                            and first == self.eos_token:
                        fsm_state = fsm.start  # evicted as EOS below
                    else:
                        fsm_state = fsm.advance(fsm.start, first)
                        if fsm_state < 0:  # masked sample: impossible
                            raise RuntimeError(
                                "constrained first token escaped its "
                                "start-state mask (engine bug)")
                self._fsms[sid] = (fsm, fsm_state)
                self._masks[sid] = fsm.allow_row(fsm_state,
                                                 self.eos_token)
                self._masks_dirty = True
            else:
                self._fsms[sid] = None
                if not self._masks[sid].all():
                    self._masks[sid, :] = True
                    self._masks_dirty = True
        if replay:
            # Finish conditions were already evaluated for every
            # re-fed token before the fault — except possibly the LAST:
            # a fleet-migrated mirror can carry a token its dying
            # replica emitted without living to evict on, so re-check
            # the live edge alone (an in-engine replay can never be
            # complete — eviction beat it to the snapshot) or the first
            # post-replay tick samples one token past the stream's end.
            # Constrained streams add the grammar edge: a migrated
            # stream whose automaton has no continuation is COMPLETE.
            if (self.eos_token is not None
                    and handle.tokens[-1] == self.eos_token):
                self._evict(sid, RequestState.FINISHED, FinishReason.EOS)
            elif fsm is not None and fsm_state is not None \
                    and fsm.is_dead_end(fsm_state, self.eos_token):
                self._evict(sid, RequestState.FINISHED,
                            FinishReason.GRAMMAR)
            elif len(handle.tokens) >= req.max_new_tokens:
                self._evict(sid, RequestState.FINISHED,
                            FinishReason.LENGTH)
            return
        # A one-token request (or an immediate eos / an immediately
        # complete grammar) finishes at admission without ever joining
        # a tick.
        if self.eos_token is not None and first == self.eos_token:
            self._evict(sid, RequestState.FINISHED, FinishReason.EOS)
        elif fsm is not None and fsm.is_dead_end(fsm_state,
                                                 self.eos_token):
            self._evict(sid, RequestState.FINISHED, FinishReason.GRAMMAR)
        elif req.max_new_tokens == 1:
            self._evict(sid, RequestState.FINISHED, FinishReason.LENGTH)

    # ------------------------------------------------- speculative tick
    def _dispatch_draft(self, forced_tok, forced_n):
        """Run the draft program; returns host ``[S, spec_k]`` drafts.

        A draft failure is NEVER fatal to the streams: when the retry
        budget runs out without a consumed buffer (injected faults, or
        the weightless n-gram program, which donates nothing), the tick
        falls back to repeat-last-token drafts — the n-gram drafter's
        own no-match fallback — and pays acceptance, not correctness
        (verification is the oracle either way). Only a REAL error that
        may have consumed the donated draft tree escalates, and then it
        recovers exactly like a consumed pool: full live-slot replay."""
        try:
            if self._draft_on:
                self._dcache, drafts = self._device_call(
                    "draft", self._draft_model_p, self._dparams,
                    self._dcache, self._positions, self._tables,
                    self._tokens, forced_tok, forced_n)
            else:
                drafts = self._device_call(
                    "draft", self._draft_p, self._hist, self._positions)
            return np.asarray(drafts)
        except _SlotStateLost as lost:
            if lost.consumed is not None:
                raise
            return np.repeat(self._tokens[:, None], self._spec_k, axis=1)

    def _grammar_draft_walk(self, sid: int, fsm_entry, drafts_row):
        """Walk one constrained slot's FSM along its draft path: stamp
        the per-position allow masks the verify program samples under,
        and return the accept cap — the longest draft prefix that is a
        legal continuation (an allowed eos draft is itself acceptable
        and ends the walk; everything past an illegal draft is
        discarded by the cap, so its masks stay pass-through). The
        slot's LIVE FSM state is untouched here: it advances by the
        ACCEPTED length only, token by token, in the window loop."""
        fsm, state = fsm_entry
        mw = self._masks_w
        mw[sid, 0] = self._masks[sid]
        cap = 0
        for j in range(1, self._spec_k + 1):
            d = int(drafts_row[j - 1])
            if not mw[sid, j - 1][d]:
                mw[sid, j:, :] = True
                break
            if self.eos_token is not None and d == self.eos_token:
                cap = j  # an accepted eos finishes the stream in-window
                mw[sid, j:, :] = True
                break
            state = fsm.advance(state, d)
            mw[sid, j] = fsm.allow_row(state, self.eos_token)
            cap = j
        self._masks_w_dirty = True
        return cap

    def _spec_tick(self, cur: int, live) -> int:
        """One speculative fused step: draft → ONE batched verify over
        the ``[S, spec_k+1]`` window → host-side accept/evict. Returns
        tokens emitted (replay re-feeds emit nothing but advance up to
        ``spec_k+1`` known tokens per window). Raises
        :class:`_SlotStateLost` to the caller exactly like the plain
        tick — the caller's recovery is identical."""
        s, k = self.max_slots, self._spec_k
        w_width = k + 1
        forced_tok = np.zeros((s, k), np.int32)
        forced_n = np.full(s, -1, np.int32)
        for sid in live:
            pend = self._slots[sid].replay_pending
            if pend:
                # Re-feed known tokens through the window, leaving the
                # LAST one as next tick's cur (the non-spec invariant:
                # the live edge's K/V is written by the tick that
                # samples past it).
                j = min(k, len(pend) - 1)
                if j > 0:
                    forced_tok[sid, :j] = pend[:j]
                forced_n[sid] = j
        drafts = self._dispatch_draft(forced_tok, forced_n)
        block = np.zeros((s, w_width), np.int32)
        block[:, 0] = self._tokens
        block[:, 1:] = drafts
        caps = np.zeros(s, np.int32)
        drafted_tick = 0
        for sid in live:
            if forced_n[sid] >= 0:
                block[sid, 1:] = 0
                if forced_n[sid] > 0:
                    block[sid, 1:1 + int(forced_n[sid])] = \
                        forced_tok[sid, :int(forced_n[sid])]
                continue
            if self._temps[sid] > 0:
                # Sampled streams tick one exact token (cap 0): the
                # rejection-sampling verifier stays on the one-shot
                # path; serving exactness comes first. A CONSTRAINED
                # sampled row still draws that token under its FSM mask
                # — the plain tick's pre-masking, lifted to position 0
                # of the window (an unmasked draw could emit an illegal
                # token and crash the host FSM advance for everyone).
                if self._tenant_on and self._fsms[sid] is not None:
                    self._masks_w[sid, 0] = self._masks[sid]
                    self._masks_w[sid, 1:, :] = True
                    self._masks_w_dirty = True
                continue
            fsm_entry = self._fsms[sid] if self._tenant_on else None
            if fsm_entry is None:
                caps[sid] = k
            else:
                caps[sid] = self._grammar_draft_walk(sid, fsm_entry,
                                                     block[sid, 1:])
            drafted_tick += int(caps[sid])
        if self._paged:
            self._cache, win, acc, self._rng = self._device_call(
                "verify", self._verify_p, self._params, self._cache,
                self._positions, self._tables, block, self._temps,
                self._top_ks, self._top_ps, *self._verify_extra(),
                caps, forced_n, self._rng)
        else:
            self._cache, win, acc, self._rng = self._device_call(
                "verify", self._verify_p, self._params, self._cache,
                self._positions, block, self._temps, self._top_ks,
                self._top_ps, *self._verify_extra(), caps, forced_n,
                self._rng)
        win = np.asarray(win)  # per-tick host sync (streaming)
        acc = np.asarray(acc)
        new_tokens = 0
        accepted_tick = 0
        for sid in live:
            handle = self._slots[sid]
            if forced_n[sid] >= 0:
                j = int(forced_n[sid])
                del handle.replay_pending[:j]
                self._positions[sid] += j + 1
                self._tokens[sid] = handle.replay_pending.pop(0)
                continue
            n_emit = int(acc[sid]) + 1
            if caps[sid] > 0:
                accepted_tick += int(acc[sid])
                handle.spec_drafted += int(caps[sid])
                handle.spec_accepted += int(acc[sid])
            pos0 = int(self._positions[sid])
            # Write the WHOLE window into the drafter's history — the
            # rejected tail beyond the accepted length included, exactly
            # like the one-shot loop's token buffer. The tail is the
            # model's own next-token predictions: an n-gram continuation
            # that crosses the live edge then reads informed guesses
            # instead of zeros (zeros collapsed acceptance on looping
            # streams, found here), and the next window's write covers
            # the whole stale extent before the edge can reach it —
            # junk beyond the edge stays junk-safe, verification is
            # still the only oracle.
            end = min(pos0 + 1 + w_width, self._hist.shape[1])
            if end > pos0 + 1:
                self._hist[sid, pos0 + 1:end] = win[sid, :end - pos0 - 1]
            evicted = False
            for j in range(n_emit):
                tok = int(win[sid, j])
                handle.tokens.append(tok)
                new_tokens += 1
                self._tracer.on_token(handle, cur)
                fsm_entry = (self._fsms[sid] if self._tenant_on
                             else None)
                if self.eos_token is not None and tok == self.eos_token:
                    # Tokens past an in-window eos were never emitted —
                    # the loop stops here, exactly where the
                    # non-speculative stream would have stopped.
                    self._evict(sid, RequestState.FINISHED,
                                FinishReason.EOS)
                    evicted = True
                    break
                if fsm_entry is not None:
                    fsm, state = fsm_entry
                    state = fsm.advance(state, tok)
                    if state < 0:  # masked sample: impossible
                        raise RuntimeError(
                            "constrained token escaped its state mask "
                            "(engine bug)")
                    self._fsms[sid] = (fsm, state)
                    if fsm.is_dead_end(state, self.eos_token):
                        self._evict(sid, RequestState.FINISHED,
                                    FinishReason.GRAMMAR)
                        evicted = True
                        break
                    if len(handle.tokens) >= \
                            handle.request.max_new_tokens:
                        self._evict(sid, RequestState.FINISHED,
                                    FinishReason.LENGTH)
                        evicted = True
                        break
                    self._masks[sid] = fsm.allow_row(state,
                                                     self.eos_token)
                    self._masks_dirty = True
                elif len(handle.tokens) >= \
                        handle.request.max_new_tokens:
                    self._evict(sid, RequestState.FINISHED,
                                FinishReason.LENGTH)
                    evicted = True
                    break
            if not evicted:
                self._positions[sid] += n_emit
                self._tokens[sid] = int(win[sid, n_emit - 1])
        self.metrics.record_spec_tick(drafted_tick, accepted_tick)
        return new_tokens

    def step(self) -> int:
        """One engine tick: (drain check) → reap → admit → one fused
        decode tick for all live slots → evict finished. Returns tokens
        emitted this step (admission first-tokens included; replay
        re-feeds emit nothing — those tokens were already streamed).
        With ``spec_k > 0`` the decode tick is the speculative
        draft/verify window (:meth:`_spec_tick`) and may emit up to
        ``spec_k + 1`` tokens per slot. After a drain this is a no-op
        returning 0."""
        if not self._warm:
            self.warmup()
        if self._drain_flag and not self._drained:
            # SIGTERM arrived (flag set by the async-signal-safe
            # handler): snapshot and stop at this step boundary — the
            # serving analog of PreemptionCheckpoint's batch-boundary
            # save.
            self.drain(self._drain_path)
        if self._drained:
            return 0
        # The current step coordinate: the fault plan, the trace
        # events, and the telemetry-ring record all stamp this value,
        # so an injected fault and its observed recovery line up on
        # identical (step, site) coordinates.
        cur = self._step_idx
        self._cur_step = cur
        if self._faults is not None:
            self._faults.on_step(cur)
        self._step_idx = cur + 1
        self._site_wall = {}
        retries_before = self.metrics.retries
        t0 = self._clock()
        emitted_before = self.metrics.tokens_emitted
        self._maybe_rearm_degraded()
        self._reap()
        self._admit()
        if self._paged:
            self._paged_append_blocks()
        live = [i for i, s in enumerate(self._slots) if s is not None]
        new_tokens = 0
        if live and self._spec_on:
            try:
                new_tokens = self._spec_tick(cur, live)
            except _SlotStateLost:
                # The verify window donates the resident tree exactly
                # like the tick did (and a consumed draft tree shares
                # the paged pool's fate): every live slot replays.
                self._lose_live_slots()
        elif live:
            try:
                if self._paged:
                    self._cache, nxt, self._rng = self._device_call(
                        "tick", self._tick_p, self._params, self._cache,
                        self._positions, self._tables, self._tokens,
                        self._temps, self._top_ks, self._top_ps,
                        *self._tick_extra(), self._rng)
                else:
                    self._cache, nxt, self._rng = self._device_call(
                        "tick", self._tick_p, self._params, self._cache,
                        self._positions, self._tokens, self._temps,
                        self._top_ks, self._top_ps, *self._tick_extra(),
                        self._rng)
            except _SlotStateLost:
                self._lose_live_slots()
                nxt = None
            if nxt is not None:
                nxt = np.asarray(nxt)  # per-tick host sync (streaming)
                for sid in live:
                    handle = self._slots[sid]
                    if handle.replay_pending:
                        # Rebuilding lost KV: the tick just re-wrote
                        # this row's next known token — feed the
                        # following one, discard the sampled output
                        # (the caller already has these tokens).
                        self._tokens[sid] = handle.replay_pending.pop(0)
                        self._positions[sid] += 1
                        continue
                    tok = int(nxt[sid])
                    handle.tokens.append(tok)
                    new_tokens += 1
                    self._positions[sid] += 1
                    self._tokens[sid] = tok
                    self._tracer.on_token(handle, cur)
                    fsm_entry = (self._fsms[sid] if self._tenant_on
                                 else None)
                    if self.eos_token is not None and tok == self.eos_token:
                        # For a constrained slot the mask only ever
                        # allows eos in an ACCEPTING state, so this is
                        # simultaneously grammar acceptance.
                        self._evict(sid, RequestState.FINISHED,
                                    FinishReason.EOS)
                    elif fsm_entry is not None:
                        fsm, state = fsm_entry
                        state = fsm.advance(state, tok)
                        if state < 0:  # masked sample: impossible
                            raise RuntimeError(
                                "constrained token escaped its state "
                                "mask (engine bug)")
                        self._fsms[sid] = (fsm, state)
                        if fsm.is_dead_end(state, self.eos_token):
                            # No legal continuation: the output is a
                            # complete document (see FinishReason).
                            self._evict(sid, RequestState.FINISHED,
                                        FinishReason.GRAMMAR)
                        elif len(handle.tokens) >= \
                                handle.request.max_new_tokens:
                            self._evict(sid, RequestState.FINISHED,
                                        FinishReason.LENGTH)
                        else:
                            self._masks[sid] = fsm.allow_row(
                                state, self.eos_token)
                            self._masks_dirty = True
                    elif len(handle.tokens) >= handle.request.max_new_tokens:
                        self._evict(sid, RequestState.FINISHED,
                                    FinishReason.LENGTH)
        now = self._clock()
        self.metrics.record_tick(
            now, self.scheduler.depth, len(live), self.max_slots,
            new_tokens, now - t0)
        if self._paged:
            self.metrics.record_paged_gauges(self.blocks_shared,
                                             self.block_table_fill)
        emitted = self.metrics.tokens_emitted - emitted_before
        self.telemetry.append({
            "step": cur, "t_s": now,
            "queue_depth": self.scheduler.depth,
            "live_slots": len(live), "tokens": emitted,
            "tick_wall_s": now - t0,
            "retries": self.metrics.retries - retries_before,
            "degraded": self._degraded,
            "site_wall_s": self._site_wall,
        })
        self._tracer.on_tick(cur, self.scheduler.depth, len(live),
                             emitted, now - t0)
        return emitted

    def run(self, max_steps: Optional[int] = None) -> None:
        """Drive ``step()`` until queue and slots drain (or the step
        budget runs out) — the synchronous serving loop."""
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break

    # ----------------------------------------------------- drain/restore
    def install_drain_handler(self, path: Optional[str] = None,
                              signals=(signal.SIGTERM,)) -> None:
        """Arm checkpoint-on-SIGTERM for the serving side (the analog of
        `utils/preemption.PreemptionCheckpoint`): the handler only sets
        a flag (async-signal-safe); the actual :meth:`drain` — snapshot
        to ``path``, stop admission — happens at the next ``step()``
        boundary on the serving thread, so the snapshot is a consistent
        request set, never a torn mid-dispatch capture."""
        self._drain_path = path

        def _on_signal(signum, frame):  # flag only: async-signal-safe
            self._drain_flag = True

        for sig in signals:
            self._prev_handlers[sig] = signal.signal(sig, _on_signal)

    def uninstall_drain_handler(self) -> None:
        """Put the previous signal handlers back (tests; in production
        the process exits after the drain)."""
        for sig, old in self._prev_handlers.items():
            signal.signal(sig, old)
        self._prev_handlers.clear()

    def drain(self, path: Optional[str] = None) -> Dict[str, object]:
        """Snapshot every in-flight request's host state and stop.

        Running slots first (FCFS owes them the earliest re-admission),
        then any handle caught mid-admission, then the queue — each as
        (prompt, tokens generated so far, sampling params, remaining
        deadline budget). No device state is saved: KV is a pure
        function of (params, tokens) and the restore path recomputes it
        token-exactly via the replay machinery. Idempotent; with
        ``path`` the snapshot is also written atomically
        (`serve/drain.py`). After the drain the engine admits nothing
        and ``step()`` is a no-op."""
        if self._drained:
            return self._snapshot
        now = self._clock()
        # Slot index is reuse order, not arrival order — sort so the
        # restore really does re-admit the oldest stream first.
        handles = sorted((h for h in self._slots if h is not None),
                         key=lambda h: h.arrival_s)
        handles.extend(self._admitting)
        handles.extend(self.scheduler.drain())
        # Paged engines record each running slot's block table in the
        # v3 snapshot — postmortem context (which pool blocks the
        # stream occupied, how much was shared), never a restore input:
        # pool storage dies with the process and the restore path
        # rebuilds KV via replay exactly like a v2 snapshot.
        tables = {}
        if self._paged:
            for sid, h in enumerate(self._slots):
                if h is not None:
                    row = self._tables[sid]
                    tables[id(h)] = [int(b) for b in row[row != 0]]
        self._snapshot = {
            "version": drain_io.SNAPSHOT_VERSION,
            "drained_unix_s": time.time(),
            "paged": self._paged,
            # v5: the drafting config the streams ran under — postmortem
            # context (restore replays token-exactly into ANY engine,
            # speculative or not; KV and FSM state are pure functions of
            # the tokens, and so is every drafter).
            "spec_k": self._spec_k,
            "requests": [drain_io.encode_handle(h, now,
                                                block_table=tables.get(id(h)))
                         for h in handles],
            # Last-moments telemetry (`obs/ring.py` summary): what the
            # engine looked like going down — postmortem context the
            # restore path ignores (`serve/drain.py`).
            "telemetry": self.telemetry.summary(),
        }
        self._tracer.on_drain(self._cur_step, len(handles))
        self._drained = True
        self._drain_flag = True
        if path is not None:
            drain_io.save_snapshot(self._snapshot, path)
        return self._snapshot

    def restore(self, source) -> List[RequestHandle]:
        """Resubmit a drain snapshot (dict or path) into THIS engine —
        call on a fresh engine with the same model/config. Requests
        that were running resume token-exactly: their handles re-enter
        the queue with tokens-so-far attached, and replay admission
        rebuilds each one's KV from prompt + tokens before the stream
        continues (``handle.tokens`` of the returned handles already
        contains the pre-drain tokens, so a completed restore holds
        each request's FULL stream). Depth limits don't apply — every
        one of these was already admitted once. Returns the new
        handles in service order."""
        if isinstance(source, str):
            source = drain_io.load_snapshot(source)
        handles = drain_io.restored_handles(source, self._clock())
        if not self._tenant_on:
            # A tenant stream restored onto a plain engine would
            # silently serve the BASE model (wrong weights, no mask) —
            # refuse loudly instead. v1-v3 snapshots carry neither
            # field, so every pre-tenant snapshot restores here
            # unchanged.
            bad = [h for h in handles
                   if h.request.adapter is not None
                   or h.request.constraint is not None]
            if bad:
                raise ValueError(
                    f"snapshot carries {len(bad)} tenant request(s) "
                    "(adapter/constraint) but this engine has no "
                    "tenant=TenantConfig(...)")
        self.scheduler.restore(handles)
        for h in handles:
            # Open a span for each resumed stream — without this, a
            # migrated/hand-off stream's decode side traces nothing.
            self._tracer.on_restored(h, len(h.tokens))
        return handles

    # ------------------------------------------- cross-replica transfer
    def export_prefix_chain(self, tokens,
                            max_blocks: Optional[int] = None):
        """The replica-to-replica prefix-transfer EXPORT (ISSUE 13):
        the longest cached chain for ``tokens`` — device radix match
        first (read D2H in one batched eager gather, the same read
        demotion uses), host-tier blocks extending it (already host
        arrays, no transfer) — as a `serve/drain.py` chain wire entry
        (:func:`~pddl_tpu.serve.drain.kv_chain_to_wire`). ``None``
        when nothing is cached, the prefix machinery is off, the HOST
        TIER is off (the D2H read rides the tier's jitted gather, and
        a tier-less replica could not receive a peer's chain either —
        exporting is a tiered-fleet feature), or the engine is
        degraded (exporting from a shed cache would race the flush).
        The matched chain is pinned for exactly the read."""
        if (not self._prefix_on or self._host is None
                or self._degraded or self._drained):
            return None
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        cap = self._match_blocks(tokens)
        if max_blocks is not None:
            cap = min(cap, int(max_blocks))
        if cap < 1:
            return None
        match = self._prefix.match(tokens, max_blocks=cap)
        m = match.n_blocks
        blocks: List[Dict[str, np.ndarray]] = []
        if m > 0:
            self._prefix.pin(match.node)
            try:
                blocks = self._gather_blocks_host(match.block_ids)
            except Exception as e:  # noqa: BLE001 - device faults only
                if classify(e) is None:
                    raise
                blocks = []  # failed D2H: export nothing
            finally:
                self._prefix.unpin(match.node)
            if len(blocks) < m:
                return None
        if m < cap:  # the top guard ensured the tier is armed
            tip = self._host.pin_chain(tokens, m, cap - m)
            if tip is not None:
                try:
                    blocks.extend(
                        self._host.chain_data(tip, tip.depth - m))
                finally:
                    self._host.unpin(tip)
        if not blocks:
            return None
        bs = self.prefix_block_size
        return drain_io.kv_chain_to_wire(
            [int(t) for t in tokens[:len(blocks) * bs]], blocks)

    def import_prefix_chain(self, entry) -> int:
        """The transfer IMPORT: decoded chain blocks enter the HOST
        TIER (no device work on the routing path — the next admission
        for the prefix promotes them H2D through the normal
        budget-charged ``host_promote`` path, so a pulled chain pays
        admission exactly what a locally-spilled one pays). Payloads
        failing this engine's leaf spec are refused block-by-block
        (`HostTierCache.store` validates). Returns blocks stored;
        0 with the tier disabled."""
        if self._host is None:
            return 0
        tokens, blocks = drain_io.kv_chain_from_wire(entry)
        bs = self.prefix_block_size
        stored = 0
        for j, data in enumerate(blocks):
            chain = tokens[:(j + 1) * bs]
            if len(chain) < (j + 1) * bs:
                break
            if self._host.has_block(chain):
                continue
            if not self._host.store(chain, data):
                break  # refused (spec mismatch / budget): a hole here
                #        would end every deeper block's promotability
            stored += 1
            self.metrics.record_host_spill(self._host.bytes_resident)
        return stored
