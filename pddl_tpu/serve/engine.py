"""Continuous-batching online serving engine over the decode path.

`docs/SERVING.md` measured a strong SINGLE-request path (decode scan,
speculative decoding, int8); the ROADMAP's north star is heavy traffic
from many users. The gap between those is this engine: Orca-style
iteration-level scheduling (OSDI '22) — requests join and leave the
running batch at TOKEN granularity instead of waiting for the slowest
member of a fixed batch, which is worth roughly an order of magnitude
of aggregate tokens/s at realistic request mixes (vLLM, SOSP '23).

The slot model, under JAX's fixed-shape discipline:

- ONE resident compiled decode program with a fixed pool of ``S``
  batch slots: the pooled KV cache is ``[S, H_kv, L, D]`` per layer
  with PER-SLOT position counters (``[S]`` int32 — the vector-index
  decode path in `ops/attention.py` / the model families), so every
  slot advances at its own depth inside one fused tick.
- Each ``step()``: (a) ADMIT queued requests into free slots — a
  batch-1 prefill over the right-padded prompt
  (:func:`~pddl_tpu.models.gpt.prefill_row`), inserted into the slot
  (:func:`~pddl_tpu.models.gpt.insert_cache_slot`), first token
  sampled immediately (that's TTFT); (b) one fused DECODE TICK for all
  live slots with per-slot sampling params as batched runtime arrays
  (:func:`~pddl_tpu.models.gpt.sample_logits_batched`); (c) EVICT
  finished slots (eos / length / cancel / deadline) host-side — the
  next admit overwrites the whole cache row, so stale K/V is
  unreachable by construction.
- Exactly FOUR compiled programs (prefill, insert, tick, first-token
  sample), each traced once at ``warmup()`` and never again: prompt
  lengths enter as a traced ``length`` over one fixed padded width,
  slots/positions/sampling params are runtime arrays, and the pooled
  cache is DONATED through insert and tick so the resident buffers are
  reused in place. ``compile_counts()`` exposes the executable counts;
  the suite pins them at 1 after a mixed workload.

Dead slots tick too (fixed shapes — their writes land at parked
position 0 and are overwritten by the next admit); the cost is one
batch row of compute, which is what buys zero recompiles.

int8 serving composes exactly like ``generate()``: pass
``param_transform=pddl_tpu.ops.quant.dequantize`` and the int8 tensors
are what lives in HBM, dequantized inside the compiled programs.

Ring-cache (rolling SWA) models are refused for now: slot reuse over a
ring whose slots already wrapped needs per-slot wrap bookkeeping this
engine doesn't carry yet. Full-length-cache models (GPT, Llama, SWA
with ``window >= max_len``) are all eligible.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from pddl_tpu.models.gpt import (
    _decode_cache_shapes,
    insert_cache_slot,
    prefill_row,
    sample_logits_batched,
    set_cache_positions,
    slot_decode_cache,
)
from pddl_tpu.serve.metrics import ServeMetrics
from pddl_tpu.serve.request import (
    FinishReason,
    Request,
    RequestHandle,
    RequestState,
    SamplingParams,
)
from pddl_tpu.serve.scheduler import FCFSScheduler


class ServeEngine:
    """Online multiplexer of generate requests onto one decode program.

    Args:
      model: a non-decode GPT/Llama (anything ``generate()``-compatible
        with a full-length KV cache); the decode twin is cloned here.
      variables: ``{"params": ...}`` — kept on device, always a jit
        ARGUMENT (new same-shape checkpoints never recompile).
      max_slots: the batch-slot pool size ``S`` — the max concurrent
        requests in one fused tick.
      prefill_len: the fixed padded prompt width (every prompt must fit;
        one compiled prefill serves all lengths). Defaults to
        ``model.max_len // 2``.
      max_queue_depth / prefill_token_budget: admission knobs, see
        `scheduler.py`.
      eos_token: optional stop token (included in the stream when hit).
      param_transform: the ``generate()`` int8 hook — applied INSIDE the
        compiled programs (:mod:`pddl_tpu.ops.quant`).
      rng: sampling key, split once per tick and per admission (the
        fused tick draws for every row and greedy rows discard the
        draw — fixed work, no recompile — so the key stream advances
        even for an all-greedy workload).
      clock: injectable monotonic clock (tests drive deadlines with a
        fake one).
    """

    def __init__(self, model, variables, *, max_slots: int = 8,
                 prefill_len: Optional[int] = None,
                 max_queue_depth: int = 64,
                 prefill_token_budget: Optional[int] = None,
                 eos_token: Optional[int] = None,
                 param_transform=None, rng=None,
                 clock=time.monotonic):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if getattr(model, "uses_ring_cache", False):
            raise NotImplementedError(
                "the serving engine needs full-length KV caches; "
                f"sliding_window={model.sliding_window} allocates a "
                "rolling ring cache whose slot reuse is not supported yet")
        self.model = model
        self.max_slots = int(max_slots)
        self.prefill_len = int(prefill_len if prefill_len is not None
                               else model.max_len // 2)
        if not 1 <= self.prefill_len <= model.max_len:
            raise ValueError(
                f"prefill_len {self.prefill_len} outside [1, "
                f"{model.max_len}]")
        self.eos_token = eos_token
        self._clock = clock
        self._params = variables["params"]
        self._dec = model.clone(decode=True)
        self._rng = rng if rng is not None else jax.random.key(0)
        self.scheduler = FCFSScheduler(
            max_queue_depth=max_queue_depth,
            prefill_token_budget=prefill_token_budget)
        self.metrics = ServeMetrics()

        # One handle per occupied slot; all other per-slot state lives
        # in the arrays below (positions) or is derivable from the
        # handle (tokens emitted = len(handle.tokens)) — no duplicated
        # bookkeeping to keep in lockstep.
        self._slots: List[Optional[RequestHandle]] = [None] * self.max_slots
        # Engine-owned per-slot state, stamped into the programs each
        # tick (positions are authoritative HERE, not in the cache —
        # the tick program overwrites the cache's counters on entry).
        self._positions = np.zeros(self.max_slots, np.int32)
        self._tokens = np.zeros(self.max_slots, np.int32)
        self._temps = np.zeros(self.max_slots, np.float32)
        self._top_ks = np.zeros(self.max_slots, np.int32)
        self._top_ps = np.full(self.max_slots, 2.0, np.float32)

        dec, pt = self._dec, param_transform

        def _prefill(params, prompt, length):
            return prefill_row(dec, params, prompt, length,
                               param_transform=pt)

        def _tick(params, cache, positions, tokens, temps, top_ks, top_ps,
                  rng):
            rng, sub = jax.random.split(rng)
            cache = set_cache_positions(cache, positions)
            logits, mutated = dec.apply(
                {"params": (pt(params) if pt is not None else params),
                 "cache": cache},
                tokens[:, None], train=False, mutable=["cache"])
            nxt = sample_logits_batched(
                sub, logits[:, -1], temperature=temps, top_k=top_ks,
                top_p=top_ps)
            return mutated["cache"], nxt, rng

        def _sample_first(logits, temp, top_k, top_p, rng):
            rng, sub = jax.random.split(rng)
            tok = sample_logits_batched(sub, logits, temperature=temp,
                                        top_k=top_k, top_p=top_p)
            return tok, rng

        def _insert(cache, row_cache, slot, position):
            # A per-engine closure (not the bare module-level function):
            # jax.jit keyed on the same function object would SHARE its
            # tracing cache across engines, making compile_counts()
            # report other instances' pool shapes.
            return insert_cache_slot(cache, row_cache, slot, position)

        # The four resident programs. The pooled cache is donated
        # through insert and tick — the engine always adopts the
        # returned tree, so the resident HBM buffers are reused in
        # place and a stale reference can never be used by mistake.
        self._prefill_p = jax.jit(_prefill)
        self._insert_p = jax.jit(_insert, donate_argnums=(0,))
        self._tick_p = jax.jit(_tick, donate_argnums=(1,))
        self._sample_first_p = jax.jit(_sample_first)

        self._cache = slot_decode_cache(dec, self.max_slots)
        self._warm = False

    # -------------------------------------------------------- submission
    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               sampling: Optional[SamplingParams] = None,
               deadline_s: Optional[float] = None) -> RequestHandle:
        """Queue one request; returns its streaming handle.

        Raises :class:`~pddl_tpu.serve.request.QueueFull` when the
        admission-control queue is at depth (the metrics count the
        rejection either way)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if prompt.size > self.prefill_len:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the engine's "
                f"prefill_len {self.prefill_len}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt.size + max_new_tokens > self.model.max_len:
            raise ValueError(
                f"prompt + new tokens {prompt.size + max_new_tokens} "
                f"exceed max_len {self.model.max_len}")
        req = Request(prompt=prompt.tolist(),
                      max_new_tokens=int(max_new_tokens),
                      sampling=sampling or SamplingParams(),
                      deadline_s=deadline_s)
        handle = RequestHandle(req, arrival_s=self._clock())
        try:
            self.scheduler.submit(handle)
        except Exception:
            self.metrics.record_rejected()
            raise
        return handle

    # ---------------------------------------------------------- plumbing
    def warmup(self) -> None:
        """Trace/compile all four programs before traffic (one dummy
        admission into slot 0 + one all-dead tick; the junk K/V lands at
        parked positions and is overwritten by the first real admit).
        Implicit on the first ``step()`` if not called."""
        if self._warm:
            return
        dummy = np.zeros((1, self.prefill_len), np.int32)
        row, logits = self._prefill_p(self._params, dummy, 1)
        self._cache = self._insert_p(self._cache, row, 0, 0)
        tok, self._rng = self._sample_first_p(
            logits, np.float32(0.0), np.int32(0), np.float32(2.0),
            self._rng)
        self._cache, nxt, self._rng = self._tick_p(
            self._params, self._cache, self._positions, self._tokens,
            self._temps, self._top_ks, self._top_ps, self._rng)
        jax.block_until_ready((tok, nxt))
        self._warm = True

    def compile_counts(self) -> Dict[str, int]:
        """Compiled-executable count per resident program (the
        zero-recompiles-after-warmup contract: all four stay at 1)."""
        return {
            "prefill": self._prefill_p._cache_size(),
            "insert": self._insert_p._cache_size(),
            "tick": self._tick_p._cache_size(),
            "sample_first": self._sample_first_p._cache_size(),
        }

    @property
    def live_slots(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def has_work(self) -> bool:
        return self.live_slots > 0 or self.scheduler.depth > 0

    def _free_slot_ids(self) -> List[int]:
        return [i for i, s in enumerate(self._slots) if s is None]

    def _evict(self, slot_id: int, state: RequestState,
               reason: FinishReason) -> None:
        handle = self._slots[slot_id]
        assert handle is not None
        handle.state = state
        handle.finish_reason = reason
        handle.finish_s = self._clock()
        self.metrics.record_finish(reason.value)
        self._slots[slot_id] = None
        # Park the dead row: position 0, greedy params. Its future junk
        # writes land at position 0 and the next admit overwrites the
        # whole cache row anyway.
        self._positions[slot_id] = 0
        self._tokens[slot_id] = 0
        self._temps[slot_id] = 0.0
        self._top_ks[slot_id] = 0
        self._top_ps[slot_id] = 2.0

    def _expired(self, handle: RequestHandle, now: float) -> bool:
        return (handle.request.deadline_s is not None
                and now - handle.arrival_s > handle.request.deadline_s)

    def _reap(self) -> None:
        """Cancellations and deadlines, checked at tick granularity."""
        now = self._clock()
        for sid, handle in enumerate(self._slots):
            if handle is None:
                continue
            if handle.cancelled:
                self._evict(sid, RequestState.CANCELLED,
                            FinishReason.CANCELLED)
            elif self._expired(handle, now):
                self._evict(sid, RequestState.TIMED_OUT,
                            FinishReason.TIMED_OUT)

    def _admit(self) -> None:
        free = self._free_slot_ids()
        if not free:
            return

        def _queued_cancel(handle):
            handle.finish_s = self._clock()
            self.metrics.record_finish(FinishReason.CANCELLED.value)

        for handle in self.scheduler.admit(len(free),
                                           on_cancelled=_queued_cancel):
            if self._expired(handle, self._clock()):
                # Died in the queue: never pay its prefill (the most
                # expensive dispatch) nor emit a post-deadline token —
                # under sustained overload this is exactly where
                # deadlines earn their keep. The slot stays free for
                # the next admission.
                handle.state = RequestState.TIMED_OUT
                handle.finish_reason = FinishReason.TIMED_OUT
                handle.finish_s = self._clock()
                self.metrics.record_finish(FinishReason.TIMED_OUT.value)
                continue
            sid = free.pop(0)
            req = handle.request
            plen = len(req.prompt)
            padded = np.zeros((1, self.prefill_len), np.int32)
            padded[0, :plen] = req.prompt
            row, logits = self._prefill_p(self._params, padded, plen)
            self._cache = self._insert_p(self._cache, row, sid, plen)
            t, k, p = req.sampling.as_arrays()
            tok, self._rng = self._sample_first_p(
                logits, np.float32(t), np.int32(k), np.float32(p),
                self._rng)
            first = int(tok[0])
            now = self._clock()
            handle.tokens.append(first)
            handle.ttft_s = now - handle.arrival_s
            self.metrics.record_first_token(handle.ttft_s)
            self._slots[sid] = handle
            self._positions[sid] = plen
            self._tokens[sid] = first
            self._temps[sid] = t
            self._top_ks[sid] = k
            self._top_ps[sid] = p
            # A one-token request (or an immediate eos) finishes at
            # admission without ever joining a tick.
            if self.eos_token is not None and first == self.eos_token:
                self._evict(sid, RequestState.FINISHED, FinishReason.EOS)
            elif req.max_new_tokens == 1:
                self._evict(sid, RequestState.FINISHED, FinishReason.LENGTH)

    def step(self) -> int:
        """One engine tick: reap → admit → one fused decode tick for all
        live slots → evict finished. Returns tokens emitted this step
        (admission first-tokens included)."""
        if not self._warm:
            self.warmup()
        t0 = self._clock()
        emitted_before = self.metrics.tokens_emitted
        self._reap()
        self._admit()
        live = [i for i, s in enumerate(self._slots) if s is not None]
        if live:
            self._cache, nxt, self._rng = self._tick_p(
                self._params, self._cache, self._positions, self._tokens,
                self._temps, self._top_ks, self._top_ps, self._rng)
            nxt = np.asarray(nxt)  # the per-tick host sync (streaming)
            for sid in live:
                handle = self._slots[sid]
                tok = int(nxt[sid])
                handle.tokens.append(tok)
                self._positions[sid] += 1
                self._tokens[sid] = tok
                if self.eos_token is not None and tok == self.eos_token:
                    self._evict(sid, RequestState.FINISHED,
                                FinishReason.EOS)
                elif len(handle.tokens) >= handle.request.max_new_tokens:
                    self._evict(sid, RequestState.FINISHED,
                                FinishReason.LENGTH)
        now = self._clock()
        tick_tokens = len(live)
        self.metrics.record_tick(
            now, self.scheduler.depth, len(live), self.max_slots,
            tick_tokens, now - t0)
        return self.metrics.tokens_emitted - emitted_before

    def run(self, max_steps: Optional[int] = None) -> None:
        """Drive ``step()`` until queue and slots drain (or the step
        budget runs out) — the synchronous serving loop."""
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
