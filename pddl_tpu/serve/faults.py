"""Deterministic fault injection for the serving engine.

The machinery (seeded schedule + rate draws, the fault taxonomy, the
injection-before-dispatch discipline) lives in
:mod:`pddl_tpu.utils.faults` and is shared with the training loop's
:mod:`pddl_tpu.train.faults`; this module pins the SERVING site
vocabulary: a :class:`FaultPlan` hooks every device-call boundary of
:class:`~pddl_tpu.serve.engine.ServeEngine` (the sites are exactly the
engine's ``compile_counts()`` keys) and fires transient errors,
allocation failures, latency spikes, or hard kill-points at chosen or
randomly drawn ``(step, site)`` coordinates — reproducible by
construction, so every recovery path is testable in tier-1 on CPU
(``tests/test_serve_faults.py``) and measurable in
``benchmarks/serve_bench.py``'s fault leg.

The engine's contract per fault kind (details in ``engine._device_call``
and docs/OPERATIONS.md § "Failure modes & recovery (serving)"):

- **TRANSIENT**: bounded-backoff retry; past ``max_retries`` the slot
  KV is declared lost and the request(s) REPLAY token-exactly.
- **OOM**: never blind-retried — DEGRADED mode (prefix-cache donations
  off, unpinned pool blocks flushed), re-arm after a cool-down.
- **LATENCY**: the call is delayed; deadlines and drain keep working.
- **KILL**: unwinds through ``step()`` like a real crash; the test then
  exercises drain/restore on the survivor state.
"""

from __future__ import annotations

from pddl_tpu.utils.faults import (  # noqa: F401 - the serve-layer surface
    FaultKind,
    FaultSpec,
    InjectedResourceExhausted,
    InjectedTransientError,
    KillPoint,
    classify,
)
from pddl_tpu.utils.faults import FaultPlan as _BaseFaultPlan


class FaultPlan(_BaseFaultPlan):
    """Seeded fault schedule over the engine's device-call sites
    (== ``ServeEngine.compile_counts()`` keys).

    The speculative sites (ISSUE 12): ``draft`` (the n-gram or
    draft-model proposal program — a lost draft call degrades to
    fallback drafts, never to a KV rebuild, unless a real error
    consumed the donated draft tree), ``verify`` (the wide-window
    program that replaces ``tick`` on a ``spec_k > 0`` engine — same
    donated-tree recovery: full live-slot replay), and
    ``draft_prefill`` (the draft model's admission chunk, paged
    engines only).

    The tiered-KV site (ISSUE 13): ``host_promote`` — the H2D scatter
    that promotes a host-tier chain into the pool on a ``host_tier``
    engine. Transients retry against the intact host copy (the tier
    pin holds across retries); exhausted retries unwind the promotion
    (ids + pins released exactly) and charge the admission a replay; a
    REAL error may have consumed the donated pool tree and recovers
    like donate/chunk (pool rebuild; paged: full live-slot replay).
    Demotion is deliberately NOT a site: it is an eager opportunistic
    read whose failure degrades to the old free-and-recompute path."""

    SITES = ("prefill", "gather", "chunk_prefill", "chunk_prefill_wide",
             "donate", "insert", "tick", "sample_first", "adapter_load",
             "draft", "verify", "draft_prefill", "host_promote")
