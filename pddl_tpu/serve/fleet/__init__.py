"""Multi-replica serving fleet: health-checked router, replica
failover, live request migration, elastic autoscaling.

`router.py` is the front door (prefix-affinity + sticky-session +
rendezvous routing, QueueFull shedding, failover with `serve/drain.py`
as the migration wire format, runtime `scale_up`/`scale_down`
mechanics), `replica.py` the driver surface (:class:`LocalReplica`
in-process for deterministic tier-1 chaos, :class:`ProcessReplica` over
a stdio pipe for real multiprocess parallelism — with the typed
:class:`ReplicaSpawnTimeout` + non-blocking ``poll_ready`` the
autoscaler's concurrent warm-starts ride on), `worker.py` the replica
process entrypoint, `health.py` the per-replica circuit breaker,
`admission.py` the overload front door (per-priority token buckets,
overload detector, hysteretic brownout ladder), `autoscaler.py` the
pressure-driven capacity controller that closes the loop (scale-up
ahead of the brownout ladder, scale-down by zero-loss live migration),
`tracegen.py` the seeded scenario-diversity trace generator (diurnal
curve, heavy-tail session mix, tenant popularity skew), and `replay.py`
the hint-honoring open-loop replay client that meters
goodput-per-replica-hour, `journal.py` the control-plane WAL that
makes the ROUTER itself crash-recoverable (``FleetRouter.recover``),
and `transport.py` the CRC-framed, sequence-checked, fault-injectable
pipe protocol between :class:`ProcessReplica` and `worker.py`, and
`disagg.py` the disaggregated prefill/decode layer (ISSUE 17): replica
ROLES, the prefill->decode KV hand-off executor, and the per-role
autoscaler multiplexer. See
`docs/OPERATIONS.md` § "Fleet runbook", § "Overload & brownout",
§ "Autoscaling runbook" and § "Control-plane failure & recovery", and
`docs/SERVING.md` § "Serving fleet".
"""

from pddl_tpu.serve.fleet.admission import (
    AdmissionControl,
    BrownoutController,
    BrownoutRung,
    OverloadDetector,
    TokenBucket,
)
from pddl_tpu.serve.fleet.autoscaler import (
    AutoscaleMetrics,
    FleetAutoscaler,
    ScaleDecision,
)
from pddl_tpu.serve.fleet.disagg import (
    ROLES,
    HandoffManager,
    RoleAutoscaler,
    validate_role,
)
from pddl_tpu.serve.fleet.health import (
    BreakerState,
    CircuitBreaker,
    GrayDetector,
)
from pddl_tpu.serve.fleet.journal import RouterJournal
from pddl_tpu.serve.fleet.replay import ReplayReport, replay_trace
from pddl_tpu.serve.fleet.replica import (
    EpochFenced,
    LocalReplica,
    ProcessReplica,
    ReplicaDied,
    ReplicaSpawnTimeout,
)
from pddl_tpu.serve.fleet.router import (
    FleetHandle,
    FleetMetrics,
    FleetRouter,
    NoHealthyReplica,
    ReplicaLifecycle,
)
from pddl_tpu.serve.fleet.standby import (
    HotStandby,
    Lease,
    LeaseHeld,
    LeaseKeeper,
    WalShipper,
    WalTail,
)
from pddl_tpu.serve.fleet.tracegen import diurnal_trace
from pddl_tpu.serve.fleet.transport import (
    FrameReceiver,
    FrameSender,
    WireFaultKind,
    WireFaultPlan,
    WireFaultSpec,
)

__all__ = [
    "AdmissionControl",
    "AutoscaleMetrics",
    "BreakerState",
    "BrownoutController",
    "BrownoutRung",
    "CircuitBreaker",
    "EpochFenced",
    "FleetAutoscaler",
    "FleetHandle",
    "FleetMetrics",
    "FleetRouter",
    "FrameReceiver",
    "FrameSender",
    "GrayDetector",
    "HandoffManager",
    "HotStandby",
    "Lease",
    "LeaseHeld",
    "LeaseKeeper",
    "LocalReplica",
    "NoHealthyReplica",
    "OverloadDetector",
    "ProcessReplica",
    "ROLES",
    "ReplayReport",
    "ReplicaDied",
    "ReplicaLifecycle",
    "ReplicaSpawnTimeout",
    "RoleAutoscaler",
    "RouterJournal",
    "ScaleDecision",
    "TokenBucket",
    "WalShipper",
    "WalTail",
    "WireFaultKind",
    "WireFaultPlan",
    "WireFaultSpec",
    "diurnal_trace",
    "replay_trace",
    "validate_role",
]
