"""Multi-replica serving fleet: health-checked router, replica
failover, live request migration.

`router.py` is the front door (prefix-affinity + sticky-session +
rendezvous routing, QueueFull shedding, failover with `serve/drain.py`
as the migration wire format), `replica.py` the driver surface
(:class:`LocalReplica` in-process for deterministic tier-1 chaos,
:class:`ProcessReplica` over a stdio pipe for real multiprocess
parallelism), `worker.py` the replica process entrypoint, `health.py`
the per-replica circuit breaker, `admission.py` the overload front
door (per-priority token buckets, overload detector, hysteretic
brownout ladder). See `docs/OPERATIONS.md` § "Fleet runbook" and
§ "Overload & brownout", and `docs/SERVING.md` § "Serving fleet".
"""

from pddl_tpu.serve.fleet.admission import (
    AdmissionControl,
    BrownoutController,
    BrownoutRung,
    OverloadDetector,
    TokenBucket,
)
from pddl_tpu.serve.fleet.health import BreakerState, CircuitBreaker
from pddl_tpu.serve.fleet.replica import (
    LocalReplica,
    ProcessReplica,
    ReplicaDied,
)
from pddl_tpu.serve.fleet.router import (
    FleetHandle,
    FleetMetrics,
    FleetRouter,
    NoHealthyReplica,
    ReplicaLifecycle,
)

__all__ = [
    "AdmissionControl",
    "BreakerState",
    "BrownoutController",
    "BrownoutRung",
    "CircuitBreaker",
    "FleetHandle",
    "FleetMetrics",
    "FleetRouter",
    "LocalReplica",
    "NoHealthyReplica",
    "OverloadDetector",
    "ProcessReplica",
    "ReplicaDied",
    "ReplicaLifecycle",
    "TokenBucket",
]
