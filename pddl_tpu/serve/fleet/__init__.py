"""Multi-replica serving fleet: health-checked router, replica
failover, live request migration.

`router.py` is the front door (prefix-affinity + sticky-session +
rendezvous routing, QueueFull shedding, failover with `serve/drain.py`
as the migration wire format), `replica.py` the driver surface
(:class:`LocalReplica` in-process for deterministic tier-1 chaos,
:class:`ProcessReplica` over a stdio pipe for real multiprocess
parallelism), `worker.py` the replica process entrypoint, `health.py`
the per-replica circuit breaker. See `docs/OPERATIONS.md` § "Fleet
runbook" and `docs/SERVING.md` § "Serving fleet".
"""

from pddl_tpu.serve.fleet.health import BreakerState, CircuitBreaker
from pddl_tpu.serve.fleet.replica import (
    LocalReplica,
    ProcessReplica,
    ReplicaDied,
)
from pddl_tpu.serve.fleet.router import (
    FleetHandle,
    FleetMetrics,
    FleetRouter,
    NoHealthyReplica,
    ReplicaLifecycle,
)

__all__ = [
    "BreakerState",
    "CircuitBreaker",
    "FleetHandle",
    "FleetMetrics",
    "FleetRouter",
    "LocalReplica",
    "NoHealthyReplica",
    "ProcessReplica",
    "ReplicaDied",
    "ReplicaLifecycle",
]
