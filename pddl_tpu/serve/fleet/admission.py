"""Fleet admission control: rate limits, overload detection, brownout.

The router's failover machinery (r11) makes the fleet robust to
FAULTS; this module makes it robust to OVERLOAD — the difference
between "a replica died" and "everyone showed up at once". Three
pieces, composed by :meth:`FleetRouter.submit`:

- :class:`TokenBucket` — per-priority rate limits at the front door.
  A class that exceeds its configured requests/s is rejected BEFORE
  any engine queue is consulted, with an honest ``retry_after_s`` (the
  bucket's own refill time), so a misbehaving batch client cannot
  starve interactive traffic of queue slots.
- :class:`OverloadDetector` — a sliding-window pressure score fed by
  the signals the fleet already emits: engine ``QueueFull``s (the
  reroutes they force and the fleet-wide rejections they end in) and
  replicas reporting DEGRADED (r08's OOM machinery — memory pressure
  IS overload pressure, which is how the brownout ladder composes
  with degraded mode). Pressure is the rejected/shed fraction of
  recent submits, boosted while any replica is degraded.
- :class:`BrownoutController` — the ladder. Sustained pressure above
  the high-water mark climbs one rung at a time; recovery is
  HYSTERETIC: pressure must hold below the low-water mark for
  ``recover_hold_s`` before stepping DOWN one rung (never straight to
  NORMAL), so a flapping load pattern cannot oscillate the fleet.

  The rungs shed the RIGHT work, cheapest first:

  1. ``SHED_BEST_EFFORT`` — reject ``best_effort`` submissions with a
     hint covering the whole remaining ladder unwind (they re-enter
     last).
  2. ``CAP_OUTPUT`` — additionally clamp every admitted request's
     ``max_new_tokens`` to ``output_cap``: shorter streams drain the
     queue faster without rejecting anyone.
  3. ``REJECT_COLD`` — additionally reject COLD prompts (no prefix
     affinity, no sticky session): a cold prompt costs a full prefill,
     the most expensive admission the fleet can buy under overload,
     while warm traffic rides the caches it already paid for.

Every decision returns an honest ``retry_after_s``: a rejected class
is told how long the ladder needs to unwind to re-admit it, scaled by
how many rungs stand between it and service — which is what makes a
``best_effort`` hint under brownout LONGER than an ``interactive``
one, and keeps polite clients from hammering a browned-out fleet.
"""

from __future__ import annotations

import enum
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from pddl_tpu.serve.request import Priority


class TokenBucket:
    """Classic token bucket: ``rate_per_s`` tokens/s up to ``burst``.

    ``None`` rate means unlimited (the default for ``interactive``).
    Refill is lazy (computed at ``take()``), so an idle bucket costs
    nothing and a fake clock drives it deterministically in tests."""

    def __init__(self, rate_per_s: Optional[float], burst: float):
        if rate_per_s is not None and rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0 or None, got "
                             f"{rate_per_s}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate_per_s = rate_per_s
        self.burst = float(burst)
        self._tokens = float(burst)
        self._stamp: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self._stamp is not None and self.rate_per_s is not None:
            self._tokens = min(
                self.burst,
                self._tokens + (now - self._stamp) * self.rate_per_s)
        self._stamp = now

    def take(self, now: float) -> bool:
        """Consume one token if available."""
        if self.rate_per_s is None:
            return True
        self._refill(now)
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def time_until_token(self, now: float) -> float:
        """Seconds until one token exists — the honest retry hint for
        a rate-limit rejection. 0 when a token is already there."""
        if self.rate_per_s is None:
            return 0.0
        self._refill(now)
        if self._tokens >= 1.0:
            return 0.0
        return (1.0 - self._tokens) / self.rate_per_s


class OverloadDetector:
    """Sliding-window pressure over recent submit outcomes.

    ``observe(now, rejected=...)`` records one routing outcome
    (rejected covers engine QueueFulls that forced a reroute AND
    fleet-wide sheds); ``pressure(now)`` is the rejected fraction of
    the window, raised to at least ``degraded_floor`` while any
    replica reports DEGRADED (``set_degraded``) — r08's OOM state is
    an overload signal even when the queues look calm, because the
    cold path serves slower than the caches the fleet is sized for."""

    def __init__(self, *, window_s: float = 2.0, min_samples: int = 8,
                 degraded_floor: float = 0.5):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = float(window_s)
        self.min_samples = int(min_samples)
        self.degraded_floor = float(degraded_floor)
        self._events: Deque[Tuple[float, bool]] = deque()
        self._degraded_replicas = 0

    def set_degraded(self, n_replicas: int) -> None:
        self._degraded_replicas = int(n_replicas)

    def observe(self, now: float, *, rejected: bool) -> None:
        self._events.append((now, bool(rejected)))
        self._trim(now)

    def _trim(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def pressure(self, now: float) -> float:
        """Rejected/shed fraction of the window in [0, 1]; 0 before
        ``min_samples`` outcomes exist (a cold fleet is not
        overloaded, however its first submit went)."""
        self._trim(now)
        p = 0.0
        if len(self._events) >= self.min_samples:
            p = sum(r for _, r in self._events) / len(self._events)
        if self._degraded_replicas > 0:
            p = max(p, self.degraded_floor)
        return p


class BrownoutRung(enum.IntEnum):
    """The ladder, ordered: each rung includes every rung below it."""

    NORMAL = 0
    SHED_BEST_EFFORT = 1
    CAP_OUTPUT = 2
    REJECT_COLD = 3


class BrownoutController:
    """Hysteretic ladder over the detector's pressure signal.

    Escalation: pressure >= ``high`` continuously for
    ``escalate_hold_s`` climbs ONE rung (and re-arms the hold, so a
    storm walks the ladder a rung at a time, not to the top in one
    step). Recovery: pressure <= ``low`` continuously for
    ``recover_hold_s`` steps DOWN one rung. The gap between ``high``
    and ``low`` plus the holds is the hysteresis — a load level
    hovering at the threshold cannot flap the fleet between states.

    ``update(now, pressure)`` returns the (possibly new) rung;
    ``decide(...)`` answers one admission question."""

    def __init__(self, *, high: float = 0.3, low: float = 0.1,
                 escalate_hold_s: float = 0.5,
                 recover_hold_s: float = 3.0,
                 output_cap: int = 32,
                 on_transition=None):
        if not 0.0 <= low < high <= 1.0:
            raise ValueError(
                f"need 0 <= low < high <= 1, got low={low} high={high}")
        if output_cap < 1:
            raise ValueError(f"output_cap must be >= 1, got {output_cap}")
        self.high = float(high)
        self.low = float(low)
        self.escalate_hold_s = float(escalate_hold_s)
        self.recover_hold_s = float(recover_hold_s)
        self.output_cap = int(output_cap)
        self.on_transition = on_transition
        self.rung = BrownoutRung.NORMAL
        self.escalations = 0
        self.deescalations = 0
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None

    def _move(self, new: BrownoutRung) -> None:
        old, self.rung = self.rung, new
        if new > old:
            self.escalations += 1
        else:
            self.deescalations += 1
        if self.on_transition is not None:
            self.on_transition(old, new)

    def update(self, now: float, pressure: float) -> BrownoutRung:
        if pressure >= self.high:
            self._below_since = None
            if self._above_since is None:
                self._above_since = now
            if (now - self._above_since >= self.escalate_hold_s
                    and self.rung < BrownoutRung.REJECT_COLD):
                self._move(BrownoutRung(self.rung + 1))
                self._above_since = now  # one rung per hold, not a jump
        elif pressure <= self.low:
            self._above_since = None
            if self._below_since is None:
                self._below_since = now
            if (now - self._below_since >= self.recover_hold_s
                    and self.rung > BrownoutRung.NORMAL):
                self._move(BrownoutRung(self.rung - 1))
                self._below_since = now  # hysteresis: one rung per hold
        else:
            # The dead band: neither escalate nor recover accumulates.
            self._above_since = None
            self._below_since = None
        return self.rung

    # ------------------------------------------------------- decisions
    def recovery_hint_s(self, rungs_to_unwind: int) -> float:
        """Honest retry hint: each rung needs at least
        ``recover_hold_s`` of calm before it unwinds, so a class
        blocked behind N rungs waits at least N holds."""
        return max(1, rungs_to_unwind) * self.recover_hold_s

    def decide(self, priority: Priority, *,
               cold: bool) -> Tuple[bool, Optional[str], float]:
        """(admit, reject_reason, retry_after_s) for one submission.
        ``cold`` = no prefix affinity and no sticky session — the
        full-prefill admission the top rung refuses to buy."""
        if (self.rung >= BrownoutRung.SHED_BEST_EFFORT
                and priority is Priority.BEST_EFFORT):
            # best_effort re-enters only at NORMAL: the whole ladder
            # must unwind, hence the longest hint of any rejection.
            return False, "brownout_shed", self.recovery_hint_s(
                int(self.rung))
        if self.rung >= BrownoutRung.REJECT_COLD and cold:
            # Cold prompts re-enter one rung down.
            return False, "brownout_cold", self.recovery_hint_s(
                int(self.rung) - int(BrownoutRung.CAP_OUTPUT))
        return True, None, 0.0

    def cap_new_tokens(self, max_new_tokens: int) -> int:
        if self.rung >= BrownoutRung.CAP_OUTPUT:
            return min(int(max_new_tokens), self.output_cap)
        return int(max_new_tokens)


class AdmissionControl:
    """The composed front door the router consults per submit.

    Args:
      rates: ``{Priority: requests/s}`` token-bucket rates (``None`` or
        a missing class = unlimited); ``burst`` scales each bucket's
        burst allowance.
      detector / brownout: constructed from ``detector_kw`` /
        ``brownout_kw`` overrides.
    """

    def __init__(self, *, rates: Optional[Dict[Priority, float]] = None,
                 burst: float = 8.0,
                 detector_kw: Optional[Dict[str, object]] = None,
                 brownout_kw: Optional[Dict[str, object]] = None,
                 on_transition=None):
        rates = rates or {}
        self.buckets: Dict[Priority, TokenBucket] = {
            p: TokenBucket(rates.get(p), burst) for p in Priority}
        self.detector = OverloadDetector(**(detector_kw or {}))
        self.brownout = BrownoutController(
            on_transition=on_transition, **(brownout_kw or {}))

    @property
    def rung(self) -> BrownoutRung:
        return self.brownout.rung

    def update(self, now: float, degraded_replicas: int = 0) -> BrownoutRung:
        """Periodic re-evaluation (the router calls this once per
        routing round): feed the degraded gauge, advance the ladder on
        current pressure."""
        self.detector.set_degraded(degraded_replicas)
        return self.brownout.update(now, self.detector.pressure(now))

    def admit(self, now: float, priority: Priority, *,
              cold: bool) -> Tuple[bool, Optional[str], float]:
        """(admit, reject_reason, retry_after_s). Order matters: the
        rate limit is per-class and independent of load; the brownout
        rungs apply after it."""
        bucket = self.buckets[priority]
        if not bucket.take(now):
            hint = bucket.time_until_token(now)
            if self.brownout.rung > BrownoutRung.NORMAL:
                hint = max(hint, self.brownout.recovery_hint_s(1))
            return False, "rate_limit", hint
        return self.brownout.decide(priority, cold=cold)

    def observe(self, now: float, *, rejected: bool) -> None:
        """One routing outcome (engine-level shed/reject or success)
        into the detector."""
        self.detector.observe(now, rejected=rejected)
