"""Elastic autoscaling: the control loop that closes the fleet's size.

The fleet is robust to faults (r11: breakers, live migration) and to
overload (r12: admission, brownout) — but its SIZE is static, so
sustained overload can only shed and idle capacity can only burn.
AlpaServe (Li et al., OSDI '23) frames the capacity question the right
way — SLO attainment per resource-hour, not raw throughput — and
Llumnix (Sun et al., OSDI '24) shows live migration is the right
primitive for rescheduling LLM requests across instances. This module
is the controller that applies both: watch the pressure the fleet
already measures, and use the migration machinery the fleet already
has, to scale in BOTH directions without losing a request.

**Signals.** The :class:`~.admission.OverloadDetector`'s pressure (the
rejected/shed fraction of recent submits, floored while any replica is
OOM-degraded) plus the mean assigned load per available replica, and —
for observability and the scale-down guard — per-class goodput rates
derived from the router's ``tokens_streamed_by_priority`` counters over
a sliding window.

**Hysteresis** (the :class:`~.admission.BrownoutController` discipline,
applied to capacity): pressure must hold above ``up_pressure`` for
``up_hold_s`` before a scale-up starts, and below ``down_pressure``
(with load under ``down_load``) for ``down_hold_s`` before a
scale-down; every executed action opens a ``cooldown_s`` window in
which no further action fires. One replica per action — a storm walks
capacity up a rung at a time, exactly like the brownout ladder walks
shedding. ``up_pressure`` defaults BELOW the brownout ladder's
``high`` water mark on purpose: capacity arrives ahead of the ladder
engaging, so brownout stays the last resort, not the first response.

**Scale-up = concurrent warm-start.** The ``replica_factory`` spawns
an UNREADY driver (a :class:`~.replica.ProcessReplica` with
``wait_ready=False``); the controller polls
:meth:`~.replica.ProcessReplica.poll_ready` once per tick while the
fleet keeps serving, and hands the driver to
:meth:`~.router.FleetRouter.scale_up` only when its engine is built
and warmed. A wedged spawn raises the typed
:class:`~.replica.ReplicaSpawnTimeout`; the attempt fails FAST and a
breaker-style doubling backoff gates the retry, so a broken image
cannot make the control loop spawn-storm.

**Scale-down = live migration, zero loss by construction.** The victim
(the least-loaded available replica) retires through
:meth:`~.router.FleetRouter.scale_down`: its queued+running streams are
captured via its drain snapshot (`serve/drain.py` wire format — the
same one failover uses, but taken gracefully) and restored onto
survivors before the process exits. A projection guard vetoes the
retirement when the survivors could not absorb the victim's load
without re-crossing the scale-up threshold — shrink must not cause the
very pressure that forces the next grow.

Every transition (SCALE_UP/SCALE_DOWN/HOLD/COOLDOWN) is counted in
:class:`AutoscaleMetrics`, exported through
:func:`pddl_tpu.obs.export.fleet_exposition` (``pddl_fleet_autoscale_*``)
and traced via ``on_fleet_event("autoscale", ...)``.
"""

from __future__ import annotations

import enum
import random
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from pddl_tpu.serve.fleet.disagg import role_of, validate_role
from pddl_tpu.serve.fleet.replica import ReplicaDied, ReplicaSpawnTimeout


class ScaleDecision(enum.Enum):
    """One control tick's outcome. HOLD covers both "signals are in the
    dead band" and "an action's hold timer is still accumulating";
    COOLDOWN means an action recently fired and the controller is
    deliberately deaf; SCALE_UP/SCALE_DOWN mark the ticks that START a
    spawn (or complete one) / execute a retirement."""

    HOLD = "hold"
    SCALE_UP = "scale_up"
    SCALE_DOWN = "scale_down"
    COOLDOWN = "cooldown"


class AutoscaleMetrics:
    """Controller-side counters (the router's FleetMetrics carries the
    mechanism side: ``scale_up_events``/``scale_down_events``/
    ``scale_down_migrated``). Snapshot keys derive from the exporter's
    canonical ``AUTOSCALE_COUNTER_KEYS`` so the two cannot drift —
    the same discipline as FleetMetrics."""

    def __init__(self):
        self.scale_up_started = 0     # spawns launched
        self.scale_up_completed = 0   # spawns that joined the rotation
        self.scale_up_failed = 0      # spawn timeout or death pre-ready
        self.scale_down_completed = 0
        self.scale_down_vetoed = 0    # projection guard refused a shrink
        self.spawn_timeouts = 0       # the ReplicaSpawnTimeout subset
        self.decision_ticks: Dict[str, int] = {
            d.value: 0 for d in ScaleDecision}

    def snapshot(self) -> Dict[str, object]:
        from pddl_tpu.obs.export import AUTOSCALE_COUNTER_KEYS  # noqa: PLC0415

        out = {k: getattr(self, k) for k in sorted(AUTOSCALE_COUNTER_KEYS)}
        for d, n in sorted(self.decision_ticks.items()):
            out["decision_ticks_" + d] = n
        return out


class FleetAutoscaler:
    """Hysteretic pressure-driven capacity controller over one
    :class:`~.router.FleetRouter`.

    Args:
      router: the fleet to control. The constructor attaches itself
        (``router.attach_autoscaler``), so every ``router.step()``
        drives one control tick — benches and chaos tests that pump
        the router get the control loop for free.
      replica_factory: ``fn(replica_id) -> driver``. For process
        fleets, return ``ProcessReplica(..., wait_ready=False)`` — the
        controller polls readiness concurrently. A driver without
        ``poll_ready`` (``LocalReplica``) counts as ready immediately.
      min_replicas / max_replicas: hard fleet-size bounds (a pending
        spawn counts against ``max_replicas``).
      up_pressure: overload-detector pressure that, held for
        ``up_hold_s``, starts a scale-up. Keep it BELOW the brownout
        ladder's ``high`` mark so capacity engages first.
      down_pressure / down_load: recovery band — pressure at or below
        ``down_pressure`` AND mean assigned load per available replica
        at or below ``down_load``, held for ``down_hold_s``, retires
        one replica.
      up_load: optional load trigger — mean assigned load per
        available replica at or above this also arms scale-up (and
        powers the scale-down projection guard). ``None`` disables
        both (pressure-only control; no projection veto).
      cooldown_s: post-action deafness (flap damping on top of the
        hold hysteresis).
      goodput_window_s: sliding window for the per-class goodput rates
        (:meth:`goodput_tokens_per_s`).
      spawn_backoff_base_s / spawn_backoff_max_s: bounded exponential
        backoff between FAILED spawn attempts (doubles per failure,
        resets on success) — the circuit-breaker discipline applied to
        the factory.
      tracer: defaults to the router's tracer.
      clock: defaults to the router's clock (one epoch for holds,
        cooldowns, breaker backoffs, and heartbeats).
      role: scope this controller to ONE role pool of a disaggregated
        fleet (`fleet/disagg.py`): size bounds, mean load, and the
        retirement victim are all computed over replicas of this role
        only, and the factory is expected to produce drivers carrying
        it. ``None`` (default) controls the whole fleet — the
        pre-ISSUE-17 behavior.
      attach: attach to the router's step cadence (the default).
        :class:`~pddl_tpu.serve.fleet.disagg.RoleAutoscaler` passes
        ``False`` — it multiplexes several controllers behind one
        attachment, and a second ``attach_autoscaler`` would silently
        replace the first.
      id_alloc: optional ``fn() -> int`` minting replica ids. Per-role
        controllers over one fleet MUST share an allocator (the
        multiplexer provides it) — each minting independently would
        collide on the shared id space. ``None`` uses an internal
        counter seeded past the fleet's current ids.
    """

    def __init__(self, router, replica_factory, *,
                 min_replicas: int = 1, max_replicas: int = 8,
                 up_pressure: float = 0.15, down_pressure: float = 0.02,
                 up_load: Optional[float] = None, down_load: float = 1.0,
                 up_hold_s: float = 0.25, down_hold_s: float = 2.0,
                 cooldown_s: float = 1.0,
                 goodput_window_s: float = 5.0,
                 spawn_backoff_base_s: float = 0.5,
                 spawn_backoff_max_s: float = 30.0,
                 spawn_jitter_frac: float = 0.0,
                 spawn_jitter_seed: Optional[int] = None,
                 tracer=None, clock=None,
                 role: Optional[str] = None, attach: bool = True,
                 id_alloc=None):
        if not 1 <= int(min_replicas) <= int(max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}/{max_replicas}")
        if not 0.0 <= down_pressure < up_pressure <= 1.0:
            raise ValueError(
                f"need 0 <= down_pressure < up_pressure <= 1, got "
                f"{down_pressure}/{up_pressure}")
        if spawn_backoff_base_s <= 0 \
                or spawn_backoff_max_s < spawn_backoff_base_s:
            raise ValueError(
                f"need 0 < spawn_backoff_base_s <= spawn_backoff_max_s, "
                f"got {spawn_backoff_base_s}/{spawn_backoff_max_s}")
        if not 0.0 <= spawn_jitter_frac < 1.0:
            raise ValueError(
                f"spawn_jitter_frac must be in [0, 1), got "
                f"{spawn_jitter_frac}")
        self.router = router
        self._factory = replica_factory
        self.role = (validate_role(role) if role is not None else None)
        self._id_alloc = id_alloc
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_pressure = float(up_pressure)
        self.down_pressure = float(down_pressure)
        self.up_load = float(up_load) if up_load is not None else None
        self.down_load = float(down_load)
        self.up_hold_s = float(up_hold_s)
        self.down_hold_s = float(down_hold_s)
        self.cooldown_s = float(cooldown_s)
        self.goodput_window_s = float(goodput_window_s)
        self._clock = clock if clock is not None else router.clock
        self._tracer = tracer if tracer is not None else router.tracer
        self.metrics = AutoscaleMetrics()
        self._next_id = 1 + max(
            (s.replica_id for s in router.replicas), default=-1)
        self._pending = None            # spawned driver, not ready yet
        self._above_since: Optional[float] = None
        self._below_since: Optional[float] = None
        self._cooldown_until = float("-inf")
        self._spawn_backoff_s = float(spawn_backoff_base_s)
        self._spawn_backoff_base_s = float(spawn_backoff_base_s)
        self._spawn_backoff_max_s = float(spawn_backoff_max_s)
        # Subtractive retry jitter (ISSUE 18): several controllers
        # recovering from the same incident (role pools, restarted
        # fleets) must not all retry their spawns at the same instant.
        # Same discipline as CircuitBreaker.jitter_frac — a jittered
        # retry never fires LATER than the deterministic schedule.
        self._spawn_jitter_frac = float(spawn_jitter_frac)
        self._spawn_rng = random.Random(spawn_jitter_seed)
        self._spawn_retry_at = float("-inf")
        self._last_decision = ScaleDecision.HOLD
        self._last_pressure = 0.0
        self._last_load = 0.0
        # (t, {class: cumulative tokens}) ring for the goodput rates.
        self._goodput_ring: Deque[Tuple[float, Dict[str, int]]] = deque()
        if attach:
            router.attach_autoscaler(self)

    # ------------------------------------------------------------- signals
    def pressure(self, now: float) -> float:
        """The overload detector's pressure when admission control is
        armed; 0.0 otherwise (a pressure-blind fleet still scales on
        ``up_load``)."""
        admission = self.router.admission
        if admission is None:
            return 0.0
        return admission.detector.pressure(now)

    def _pool(self):
        """The replicas this controller governs: the whole fleet, or
        — for a role-scoped controller — its role's pool only."""
        slots = self.router.replicas
        if self.role is None:
            return slots
        return [s for s in slots if role_of(s.driver) == self.role]

    def mean_load(self) -> float:
        """Mean assigned requests per AVAILABLE replica of this
        controller's pool (the routable denominator: dead/open-circuit
        replicas serve nothing)."""
        avail = [s for s in self._pool() if s.available]
        if not avail:
            return 0.0
        return sum(s.load for s in avail) / len(avail)

    def _update_goodput(self, now: float) -> None:
        cum = dict(self.router.metrics.tokens_streamed_by_priority)
        self._goodput_ring.append((now, cum))
        cutoff = now - self.goodput_window_s
        while len(self._goodput_ring) > 1 \
                and self._goodput_ring[0][0] < cutoff:
            self._goodput_ring.popleft()

    def goodput_tokens_per_s(self) -> Dict[str, float]:
        """Per-class delivered-token rates over the sliding window —
        the goodput view of the same scaling decision (exported as a
        labeled gauge series; the scale-down guard reasons in load
        units, which track the same signal one derivative earlier)."""
        if len(self._goodput_ring) < 2:
            return {cls: 0.0 for cls in
                    self.router.metrics.tokens_streamed_by_priority}
        (t0, c0), (t1, c1) = self._goodput_ring[0], self._goodput_ring[-1]
        dt = max(t1 - t0, 1e-9)
        return {cls: (c1.get(cls, 0) - c0.get(cls, 0)) / dt for cls in c1}

    # ------------------------------------------------------- control loop
    def step(self, now: Optional[float] = None) -> ScaleDecision:
        """One control tick (the router calls this once per routing
        round). Progresses any pending spawn, then evaluates the
        hysteresis bands and executes at most one action."""
        now = self._clock() if now is None else float(now)
        self._update_goodput(now)
        self._last_pressure = self.pressure(now)
        self._last_load = self.mean_load()
        decision = self._tick(now)
        self.metrics.decision_ticks[decision.value] += 1
        if decision is not self._last_decision:
            self._tracer.on_fleet_event(
                "autoscale",
                transition=(f"{self._last_decision.value}->"
                            f"{decision.value}"),
                replicas=len(self.router.replicas),
                pressure=round(self._last_pressure, 4))
            self._last_decision = decision
        return decision

    def _tick(self, now: float) -> ScaleDecision:
        if self._pending is not None:
            return self._poll_spawn(now)
        if now < self._cooldown_until:
            # Deaf by design: hold anchors also reset, so a storm that
            # persists past the cooldown re-earns its hold from zero.
            self._above_since = self._below_since = None
            return ScaleDecision.COOLDOWN
        n = len(self._pool())
        want_up = self._last_pressure >= self.up_pressure or (
            self.up_load is not None and self._last_load >= self.up_load)
        want_down = (self._last_pressure <= self.down_pressure
                     and self._last_load <= self.down_load)
        if want_up:
            self._below_since = None
            if n >= self.max_replicas:  # _pending is None past the top
                return ScaleDecision.HOLD
            if self._above_since is None:
                self._above_since = now
            if now - self._above_since < self.up_hold_s:
                return ScaleDecision.HOLD
            if now < self._spawn_retry_at:
                return ScaleDecision.HOLD  # backing off a failed spawn
            return self._start_spawn(now)
        if want_down:
            self._above_since = None
            if n <= self.min_replicas:
                return ScaleDecision.HOLD
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since < self.down_hold_s:
                return ScaleDecision.HOLD
            return self._retire_one(now)
        self._above_since = self._below_since = None
        return ScaleDecision.HOLD

    # ------------------------------------------------------------ scale up
    def _start_spawn(self, now: float) -> ScaleDecision:
        if self._id_alloc is not None:
            rid = int(self._id_alloc())
        else:
            rid = self._next_id
            self._next_id += 1
        self.metrics.scale_up_started += 1
        try:
            driver = self._factory(rid)
        except Exception as e:  # noqa: BLE001 - factory failure = attempt
            self._spawn_failed(now, rid, e)    # failed, backoff applies
            return ScaleDecision.HOLD
        self._pending = driver
        self._tracer.on_fleet_event("autoscale_spawn", replica=rid)
        return self._poll_spawn(now, just_started=True)

    def _poll_spawn(self, now: float,
                    just_started: bool = False) -> ScaleDecision:
        driver = self._pending
        poll = getattr(driver, "poll_ready", None)
        try:
            ready = poll() if poll is not None else True
        except ReplicaSpawnTimeout as e:
            self.metrics.spawn_timeouts += 1
            self._spawn_failed(now, driver.replica_id, e)
            return ScaleDecision.HOLD
        except ReplicaDied as e:
            self._spawn_failed(now, driver.replica_id, e)
            return ScaleDecision.HOLD
        if not ready:
            # Warm-start in flight: the fleet serves on, the controller
            # answers SCALE_UP on the starting tick (the transition the
            # trace marks) and HOLD while the compile finishes.
            return (ScaleDecision.SCALE_UP if just_started
                    else ScaleDecision.HOLD)
        self._pending = None
        self.router.scale_up(driver)
        self.metrics.scale_up_completed += 1
        self._spawn_backoff_s = self._spawn_backoff_base_s
        self._spawn_retry_at = float("-inf")
        self._arm_cooldown(now)
        return ScaleDecision.SCALE_UP

    def _spawn_failed(self, now: float, rid: int,
                      cause: BaseException) -> None:
        self.metrics.scale_up_failed += 1
        self._pending = None
        interval = self._spawn_backoff_s
        if self._spawn_jitter_frac > 0.0:
            interval *= 1.0 - self._spawn_jitter_frac \
                * self._spawn_rng.random()
        self._spawn_retry_at = now + interval
        self._spawn_backoff_s = min(self._spawn_backoff_s * 2.0,
                                    self._spawn_backoff_max_s)
        self._above_since = None  # re-earn the hold before retrying
        self._tracer.on_fleet_event(
            "autoscale_spawn_failed", replica=rid,
            error=type(cause).__name__,
            retry_in_s=round(self._spawn_retry_at - now, 3))

    # ---------------------------------------------------------- scale down
    def _retire_one(self, now: float) -> ScaleDecision:
        avail = [s for s in self._pool() if s.available]
        if len(avail) < 2 and self.role is None:
            return ScaleDecision.HOLD  # migration needs a survivor
        if self.role is not None:
            # A role-scoped retirement needs a survivor for the WORK
            # (any available replica elsewhere qualifies — the router
            # checks) but must also never empty its own pool below
            # min_replicas, which the n-bound in _tick already holds;
            # an empty or singleton pool simply has nothing optional
            # to retire when min_replicas >= 1.
            if not avail or len(self.router.replicas) < 2:
                return ScaleDecision.HOLD
        victim = min(avail, key=lambda s: s.load)
        if self.up_load is not None and len(avail) >= 2:
            # Projection guard: survivors must absorb the victim's work
            # without re-crossing the scale-up band — a shrink that
            # causes the next grow is flapping with extra steps.
            projected = sum(s.load for s in avail) / (len(avail) - 1)
            if projected >= self.up_load:
                self.metrics.scale_down_vetoed += 1
                self._below_since = None
                return ScaleDecision.HOLD
        try:
            self.router.scale_down(victim.replica_id)
        except ValueError:
            # No other available replica fleet-wide to absorb the
            # victim's work (possible for a role-scoped controller
            # whose siblings' pools all died): a retirement must never
            # orphan work, so hold and re-earn the band.
            self._below_since = None
            return ScaleDecision.HOLD
        self.metrics.scale_down_completed += 1
        self._arm_cooldown(now)
        return ScaleDecision.SCALE_DOWN

    def _arm_cooldown(self, now: float) -> None:
        self._cooldown_until = now + self.cooldown_s
        self._above_since = self._below_since = None

    def close(self) -> None:
        """Put down an in-flight spawn: a warming worker whose fleet is
        shutting down will never be read by anyone — without this, a
        scale-up racing a teardown leaks a replica-worth of process
        until the parent exits. The router's ``close()`` calls this."""
        driver, self._pending = self._pending, None
        if driver is None:
            return
        kill = getattr(driver, "kill", None)  # SIGKILL beats a close()
        try:                                  # that would wait out a
            (kill if kill is not None else driver.close)()  # shutdown
        except Exception:  # noqa: BLE001 - teardown is best-effort
            pass

    @property
    def pending_spawns(self) -> int:
        """Spawns in flight (0 or 1 — one scale op at a time). A
        spawning worker burns a replica's worth of resources before it
        serves a token, so honest replica-hour accounting (the replay
        harness's ``replica_seconds``) charges for it."""
        return 1 if self._pending is not None else 0

    # ------------------------------------------------------ observability
    def gauges(self) -> Dict[str, object]:
        """Live controller gauges for the exposition: fleet size, spawn
        state, the raw signals, and the per-class goodput rates as a
        labeled series."""
        return {
            "replicas": len(self._pool()),
            "pending_spawns": 1 if self._pending is not None else 0,
            "pressure": self._last_pressure,
            "mean_load_per_replica": self._last_load,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "spawn_backoff_s": self._spawn_backoff_s,
            "cooldown_active": 1 if self._clock() < self._cooldown_until
            else 0,
            "goodput_tokens_per_s": {
                cls: round(rate, 3)
                for cls, rate in self.goodput_tokens_per_s().items()},
        }
