"""Disaggregated prefill/decode serving: role-split fleet with
block-granular KV hand-off (ISSUE 17).

DistServe (Zhong et al., OSDI '24) and Splitwise (Patel et al., ISCA
'24) make the case this module implements: prefill and decode have
opposite resource shapes — prefill is compute-bound and bursty, decode
is latency-bound and steady — so co-locating them means one long cold
prompt admitted onto a decode-heavy replica inflates every resident
stream's token latency. The fix is to split the ROLES: prefill
replicas absorb cold prompts (chunked through the engine's
``prefill_slice_tokens`` state machine, so even a 32k prompt
time-slices against the prefill replica's own tick), and the finished
KV chain ships to a decode replica whose resident streams never pay
for it.

**Roles.** Every replica driver carries a ``role`` — ``prefill``,
``decode``, or ``unified`` (the default, fully backward compatible:
an all-unified fleet routes exactly like r19). The fleet is ARMED for
disaggregation when it holds at least one strict-``prefill`` AND one
strict-``decode`` replica; while armed, the router sends every
non-sticky admission to the prefill pool (route label ``prefill``),
and sticky sessions keep following their stream — which, after the
hand-off, lives on a decode replica.

**The hand-off.** The first token a prefill replica emits for a
stream is the completion signal: prefill is done, decode has begun in
the wrong place. The :class:`HandoffManager` (driven by the router
AFTER its slot loop, the same no-mutation-under-iteration discipline
the autoscaler rides) then rebinds the stream:

1. the finished prefill chain exports from the source over the
   `serve/drain.py` chain wire format (the r18 ``chain_pull_blocks``
   machinery) and imports into the decode replica's HOST tier, where
   the replay admission PROMOTES it — block copies, not prefill
   compute, are all the decode replica pays;
2. the stream itself moves by the r11 mirror-replay contract under a
   FRESH rid (the source's cancel-finish must fall into the void, not
   settle the moved stream), journaled under the original rid via the
   same alias discipline hedges use;
3. the router stamps the rebinding in the WAL
   (:func:`~pddl_tpu.serve.fleet.journal.encode_handoff`) and counts
   it (``handoffs_completed``/``handoffs_failed``/``handoff_bytes``/
   ``handoff_tokens``).

Every failure mode degrades, never loses: a source that dies
mid-export unwinds through ``_on_death`` (the stream re-prefills
elsewhere, token-exact; the engine's export pins release in its own
``finally``), a dead import target likewise, and a merely REFUSED
transfer leaves the stream decoding on the prefill replica (slow
beats wrong) and counts a failure.

**Per-role autoscaling.** :class:`RoleAutoscaler` multiplexes one
:class:`~pddl_tpu.serve.fleet.autoscaler.FleetAutoscaler` per role
pool behind the single ``step()``/``close()``/``gauges()`` surface the
router drives — independent pressure/load bands per role, shared
replica-id line, one decision per role per routing round. Sizing the
pools is the operator's lever (docs/OPERATIONS.md § "Disaggregated
serving runbook").
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from pddl_tpu.utils.faults import KillPoint

# Machine-checked role vocabulary (graftlint `role-vocab`): the
# replica roles the router, the drivers, and the worker process all
# agree on. `fleet/worker.py` declares the same literal tuple — the
# rule pins the two to set equality, so a role added here cannot be a
# config value the worker silently rejects (or vice versa).
ROLES = ("prefill", "decode", "unified")


def validate_role(role) -> str:
    """Normalize + validate a replica role (None -> ``unified``)."""
    role = "unified" if role is None else str(role)
    if role not in ROLES:
        raise ValueError(f"replica role must be one of {ROLES}, "
                         f"got {role!r}")
    return role


def role_of(driver) -> str:
    """A driver's role; drivers predating ISSUE 17 are ``unified``."""
    return getattr(driver, "role", "unified")


def _chain_wire_bytes(entry) -> int:
    """Payload size of a chain wire entry (the b64 block leaves) — the
    hand-off bytes the exposition counts."""
    if not isinstance(entry, dict):
        return 0
    total = 0
    for block in entry.get("blocks", []):
        for leaf in block.values():
            b64 = leaf.get("b64") if isinstance(leaf, dict) else None
            if isinstance(b64, str):
                total += len(b64)
    return total


class HandoffManager:
    """The prefill->decode rebinding executor, owned by one
    :class:`~pddl_tpu.serve.fleet.router.FleetRouter`.

    The router's event loop calls :meth:`note` when a stream's first
    tokens arrive on a prefill-role slot, and :meth:`execute` once per
    routing round AFTER the slot loop — a hand-off restores onto
    another slot and must never happen under the slot iteration."""

    def __init__(self, router):
        self._router = router
        self._pending: List[int] = []
        # Streams whose transfer a target REFUSED (no host tier /
        # budget): they finish where they are — retrying every round
        # would pay the export D2H again and again for nothing.
        self._refused: set = set()
        # Streams already counted against decode_long_prompt_stalls
        # (one count per stream, however many rounds the stall lasts —
        # these DO retry, a decode replica may free up).
        self._stalled: set = set()

    @property
    def pending(self) -> int:
        return len(self._pending)

    def note(self, rid: int) -> None:
        """Mark one rid as decode-ready (its prefill slot just emitted
        tokens). Idempotent within a round."""
        if rid in self._refused:
            return
        if rid not in self._pending:
            self._pending.append(rid)

    def execute(self) -> int:
        """Run every pending hand-off; returns how many completed."""
        if not self._pending:
            return 0
        pending, self._pending = self._pending, []
        moved = 0
        for rid in pending:
            moved += self._handoff_one(rid)
        # The give-up/stall sets hold rids, and rids outlive streams:
        # purge settled ones so a long-lived router cannot leak.
        if self._refused or self._stalled:
            live = set(self._router._by_rid)
            self._refused &= live
            self._stalled &= live
        return moved

    def _handoff_one(self, rid: int) -> int:
        from pddl_tpu.serve.fleet import journal as journal_io
        from pddl_tpu.serve.fleet.replica import ReplicaDied

        r = self._router
        fh = r._by_rid.get(rid)
        # Hedged pairs keep their own settle ceremony: a hand-off of
        # one copy would race the first-result-wins cancellation.
        if fh is None or fh.done or rid in r._hedge_peer \
                or rid in r._hedge_rids:
            return 0
        src = next((s for s in r._slots if rid in s.assigned), None)
        if src is None or role_of(src.driver) != "prefill" \
                or not src.available:
            return 0
        targets = [s for s in r._slots
                   if s.available and s is not src
                   and role_of(s.driver) in ("decode", "unified")]
        if not targets:
            # No decode replica can take it: the long prompt decodes
            # where it prefilled for now — the interference the stall
            # gauge makes visible on the dashboard. Counted once per
            # stream; the next tokens event re-notes it, so the move
            # still happens if a decode replica frees up.
            if rid not in self._stalled:
                self._stalled.add(rid)
                r.metrics.decode_long_prompt_stalls += 1
            return 0
        dst = min(targets, key=lambda s: s.load)
        prompt = list(fh.request.prompt)
        t0 = r._gray_timer()
        # The stream's trace follows it across the rebind below: both
        # chain-wire legs carry the ORIGINAL trace context, and the
        # fresh rid aliases back (`TraceCollector.rebind`) so one
        # stitched trace spans prefill replica -> wire -> decode.
        collector = r._dtrace
        ctx = (collector.context_for(rid)
               if collector is not None else None)
        export_s = import_s = 0.0
        # 1. Ship the finished prefill KV: source exports the chain
        # (drain wire format), target lands it in its HOST tier. The
        # engine's export pins the chain for exactly the copy and
        # releases in its own finally — a KillPoint here leaks
        # nothing, it kills the replica and the unwind below
        # re-prefills the stream elsewhere.
        chain = None
        n_blocks = 0
        export = getattr(src.driver, "export_chain", None)
        import_fn = getattr(dst.driver, "import_chain", None)
        try:
            if export is not None:
                t_leg = r._gray_timer()
                chain = (export(prompt, None, trace=ctx)
                         if ctx is not None else export(prompt, None))
                export_s = r._gray_timer() - t_leg
        except (KillPoint, ReplicaDied) as e:
            r.metrics.handoffs_failed += 1
            r._on_death(src, e)
            return 0
        except Exception:  # noqa: BLE001 - refused export: move anyway
            chain = None
        if chain and import_fn is not None:
            try:
                t_leg = r._gray_timer()
                n_blocks = (import_fn(chain, trace=ctx)
                            if ctx is not None else import_fn(chain))
                import_s = r._gray_timer() - t_leg
            except (KillPoint, ReplicaDied) as e:
                r.metrics.handoffs_failed += 1
                r._on_death(dst, e)
                return 0
            except Exception:  # noqa: BLE001 - refused import
                n_blocks = 0
        if not n_blocks:
            # The KV did not land (no host tier, refused import, empty
            # export): moving the stream would make the target
            # re-prefill the long prompt — the exact interference this
            # subsystem exists to prevent. Keep decoding on the
            # prefill replica instead (slow for this stream, harmless
            # for the residents), count the failure, and stop retrying
            # this stream.
            self._refused.add(rid)
            r.metrics.handoffs_failed += 1
            r._tracer.on_fleet_event(
                "handoff_refused", request_id=fh.request.request_id,
                from_replica=src.replica_id, to_replica=dst.replica_id)
            return 0
        # 2. Commit point: move the stream under a FRESH rid. The
        # source's cancel produces a finish event for the OLD rid,
        # which must fall into the void (`_by_rid` miss) instead of
        # settling the moved stream — the same unbinding discipline
        # `_settle_hedge` uses. The journal keeps the original rid:
        # its admit is filed there, so tokens/finish/checkpoint alias
        # back (the hedge-alias mechanism, reused verbatim).
        new_rid = r._new_rid()
        entry = r._wire_entry(fh)
        src.assigned.pop(rid, None)
        r._by_rid.pop(rid, None)
        old_alias = r._hedge_alias.pop(rid, rid)
        try:
            src.driver.cancel(rid)
        except Exception:  # noqa: BLE001 - a dying source settles later
            pass
        if collector is not None:
            collector.rebind(rid, new_rid)
        try:
            if collector is not None:
                dst.driver.restore(
                    [(new_rid, entry)],
                    traces={new_rid: collector.context_for(new_rid)})
            else:
                dst.driver.restore([(new_rid, entry)])
        except (KillPoint, ReplicaDied) as e:
            r.metrics.handoffs_failed += 1
            r._on_death(dst, e)
            # The stream is bound nowhere right now: re-enter it
            # through the migration machinery from a fresh mirror.
            r._hedge_alias[new_rid] = old_alias
            if not fh.done:
                r._distribute([(new_rid, r._wire_entry(fh), fh)],
                              "replay")
            return 0
        # 3. Rebind.
        fh.replica_id = dst.replica_id
        fh.migrations += 1
        dst.assigned[new_rid] = fh
        r._by_rid[new_rid] = fh
        r._hedge_alias[new_rid] = old_alias
        dst.shadow.observe(prompt, max_blocks=r._affinity_blocks)
        if n_blocks > 0:
            pulled = (len(chain.get("tokens", [])) // r._block_size
                      if isinstance(chain, dict) else n_blocks)
            dst.shadow.observe_host(
                prompt, max_blocks=min(r._affinity_blocks, pulled))
        if fh.session is not None:
            r._session_pin(fh.session, dst)
        moved_bytes = _chain_wire_bytes(chain) if n_blocks > 0 else 0
        moved_tokens = (len(chain.get("tokens", []))
                        if n_blocks > 0 and isinstance(chain, dict)
                        else 0)
        r.metrics.handoffs_completed += 1
        r.metrics.handoff_bytes += moved_bytes
        r.metrics.handoff_tokens += moved_tokens
        if r._journal is not None:
            r._journal.append(journal_io.encode_handoff(
                old_alias, src.replica_id, dst.replica_id))
        r._tracer.on_fleet_event(
            "handoff", request_id=fh.request.request_id,
            from_replica=src.replica_id, to_replica=dst.replica_id,
            blocks=n_blocks, bytes=moved_bytes,
            ms=round((r._gray_timer() - t0) * 1e3, 3))
        if collector is not None:
            collector.on_handoff(new_rid, src.replica_id,
                                 dst.replica_id, export_s, import_s,
                                 n_blocks)
        return 1


class _SummedAutoscaleMetrics:
    """The per-role controllers' counters summed into one snapshot —
    the exposition surface :func:`~pddl_tpu.obs.export.fleet_exposition`
    reads is identical for a single controller and a multiplexer."""

    def __init__(self, controllers: Dict[str, object]):
        self._controllers = controllers

    def snapshot(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for controller in self._controllers.values():
            for key, n in controller.metrics.snapshot().items():
                out[key] = out.get(key, 0) + n
        return out


class RoleAutoscaler:
    """Per-role capacity control: one hysteretic
    :class:`~pddl_tpu.serve.fleet.autoscaler.FleetAutoscaler` per role
    pool, multiplexed behind the single ``step()``/``close()`` surface
    the router drives.

    Args:
      router: the fleet to control; the constructor attaches itself
        (so ``router.step()`` drives one decision tick PER ROLE per
        routing round — the pools' pressure signals are independent).
      factories: ``{role: fn(replica_id) -> driver}`` — each factory
        must return a driver carrying that role (the role is the
        factory's contract, not the controller's to stamp). One
        controller is built per entry; roles absent from the map are
        not scaled.
      per_role: optional ``{role: kwargs}`` overriding ``common_kw``
        for that role's controller (independent min/max and bands —
        the sizing lever the runbook describes).
      **common_kw: forwarded to every controller
        (:class:`FleetAutoscaler` kwargs).
    """

    def __init__(self, router, factories: Dict[str, object], *,
                 per_role: Optional[Dict[str, Dict]] = None,
                 **common_kw):
        from pddl_tpu.serve.fleet.autoscaler import FleetAutoscaler

        if not factories:
            raise ValueError("RoleAutoscaler needs at least one role "
                             "factory")
        self.router = router
        # One replica-id line across every pool: two controllers
        # minting ids independently would collide on the shared fleet.
        next_id = 1 + max((s.replica_id for s in router.replicas),
                          default=-1)
        self._ids = itertools.count(next_id)
        self.controllers: Dict[str, object] = {}
        for role in sorted(factories):
            kw = dict(common_kw)
            kw.update((per_role or {}).get(role, {}))
            self.controllers[role] = FleetAutoscaler(
                router, factories[role], role=validate_role(role),
                attach=False, id_alloc=lambda: next(self._ids), **kw)
        self.metrics = _SummedAutoscaleMetrics(self.controllers)
        router.attach_autoscaler(self)

    def step(self, now: Optional[float] = None) -> Dict[str, object]:
        """One control tick per role pool; returns each pool's
        :class:`~pddl_tpu.serve.fleet.autoscaler.ScaleDecision`."""
        return {role: c.step(now)
                for role, c in self.controllers.items()}

    def close(self) -> None:
        for controller in self.controllers.values():
            controller.close()

    @property
    def pending_spawns(self) -> int:
        return sum(c.pending_spawns for c in self.controllers.values())

    def gauges(self) -> Dict[str, object]:
        """Merged controller gauges: fleet-wide scalars plus the
        per-role pool sizes/bounds as labeled series."""
        any_controller = next(iter(self.controllers.values()))
        return {
            "replicas": len(self.router.replicas),
            "pending_spawns": self.pending_spawns,
            "pressure": any_controller._last_pressure,
            "role_replicas": {
                role: len(c._pool())
                for role, c in self.controllers.items()},
            "role_pending_spawns": {
                role: c.pending_spawns
                for role, c in self.controllers.items()},
            "role_min_replicas": {
                role: c.min_replicas
                for role, c in self.controllers.items()},
            "role_max_replicas": {
                role: c.max_replicas
                for role, c in self.controllers.items()},
            "role_mean_load": {
                role: round(c.mean_load(), 4)
                for role, c in self.controllers.items()},
        }
