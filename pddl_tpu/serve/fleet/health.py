"""Replica health: circuit breaker + heartbeat bookkeeping.

A fleet's defining property is that any replica can die at any moment —
and a router that keeps sending traffic at a dying replica converts one
machine's failure into every caller's latency. The standard defense
(Nygard's *Release It!*, the pattern every service mesh ships) is the
CIRCUIT BREAKER, one per replica:

- **CLOSED** — healthy: traffic flows; consecutive failures count up.
- **OPEN** — tripped (``failure_threshold`` consecutive failures, or an
  outright replica death): no traffic, no probes, until a bounded
  exponential backoff expires (``backoff_base_s * 2**n``, capped at
  ``backoff_max_s`` — each failed recovery attempt doubles the wait, so
  a flapping replica cannot make the router spend its time probing).
- **HALF_OPEN** — the backoff expired: exactly one probe is allowed (a
  respawn attempt — fresh engine for an in-process replica, fresh
  worker process for a process replica). Success closes the circuit,
  failure re-opens it with the doubled backoff.

The breaker is pure host-side state with an injectable clock, so every
transition is unit-testable without sleeping. Heartbeats are the
FAILURE DETECTOR feeding it: the shared-FS beat pattern of
:class:`pddl_tpu.parallel.multiworker.HeartbeatMonitor` applied to the
serving tier — a local replica "beats" by completing a step, a process
replica by answering pipe pings — and a beat older than
``heartbeat_timeout_s`` counts as a failure exactly like an explicit
error does.
"""

from __future__ import annotations

import enum
from typing import Callable, Optional


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-replica circuit breaker (CLOSED → OPEN → HALF_OPEN → ...).

    Args:
      failure_threshold: consecutive failures that trip CLOSED → OPEN.
      backoff_base_s: first OPEN interval; doubles per re-open.
      backoff_max_s: backoff cap (bounded exponential).
      on_transition: optional ``fn(old: BreakerState, new: BreakerState)``
        — the router wires this to its metrics/tracer so every
        transition is observable.
    """

    def __init__(self, *, failure_threshold: int = 3,
                 backoff_base_s: float = 0.5, backoff_max_s: float = 30.0,
                 on_transition: Optional[Callable] = None):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        if backoff_base_s <= 0 or backoff_max_s < backoff_base_s:
            raise ValueError(
                f"need 0 < backoff_base_s <= backoff_max_s, got "
                f"{backoff_base_s}/{backoff_max_s}")
        self.failure_threshold = int(failure_threshold)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.on_transition = on_transition
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.open_until_s = 0.0
        self._backoff_s = self.backoff_base_s

    def _to(self, new: BreakerState) -> None:
        if new is self.state:
            return
        old, self.state = self.state, new
        if self.on_transition is not None:
            self.on_transition(old, new)

    # ------------------------------------------------------------ queries
    @property
    def allows_traffic(self) -> bool:
        """Route new requests here? Only a CLOSED circuit takes traffic
        (HALF_OPEN carries exactly the probe, nothing else)."""
        return self.state is BreakerState.CLOSED

    def probe_due(self, now_s: float) -> bool:
        """OPEN and past the backoff: one recovery probe may fire."""
        return self.state is BreakerState.OPEN and now_s >= self.open_until_s

    # ---------------------------------------------------------- recording
    def begin_probe(self, now_s: float) -> None:
        """OPEN → HALF_OPEN: the single allowed probe is in flight."""
        if self.state is not BreakerState.OPEN:
            raise RuntimeError(
                f"begin_probe from {self.state.value} (must be open)")
        self._to(BreakerState.HALF_OPEN)

    def record_success(self, now_s: float) -> None:
        """A successful call (or probe): close the circuit, reset the
        failure count AND the backoff (a recovered replica earns a
        fresh slate — the next incident starts at the base interval)."""
        self.consecutive_failures = 0
        self._backoff_s = self.backoff_base_s
        self._to(BreakerState.CLOSED)

    def record_failure(self, now_s: float) -> None:
        """One failure/timeout. CLOSED trips at the threshold; a
        HALF_OPEN probe failure re-opens immediately with the doubled
        (capped) backoff."""
        self.consecutive_failures += 1
        if self.state is BreakerState.HALF_OPEN:
            self._reopen(now_s)
        elif (self.state is BreakerState.CLOSED
                and self.consecutive_failures >= self.failure_threshold):
            self._reopen(now_s)

    def trip(self, now_s: float) -> None:
        """Unconditional → OPEN (the router saw the replica die; no
        threshold debate needed)."""
        if self.state is not BreakerState.OPEN:
            self._reopen(now_s)

    def _reopen(self, now_s: float) -> None:
        self.open_until_s = now_s + self._backoff_s
        self._backoff_s = min(self._backoff_s * 2.0, self.backoff_max_s)
        self._to(BreakerState.OPEN)
