"""Replica health: circuit breaker + heartbeat bookkeeping.

A fleet's defining property is that any replica can die at any moment —
and a router that keeps sending traffic at a dying replica converts one
machine's failure into every caller's latency. The standard defense
(Nygard's *Release It!*, the pattern every service mesh ships) is the
CIRCUIT BREAKER, one per replica:

- **CLOSED** — healthy: traffic flows; consecutive failures count up.
- **OPEN** — tripped (``failure_threshold`` consecutive failures, or an
  outright replica death): no traffic, no probes, until a bounded
  exponential backoff expires (``backoff_base_s * 2**n``, capped at
  ``backoff_max_s`` — each failed recovery attempt doubles the wait, so
  a flapping replica cannot make the router spend its time probing).
- **HALF_OPEN** — the backoff expired: exactly one probe is allowed (a
  respawn attempt — fresh engine for an in-process replica, fresh
  worker process for a process replica). Success closes the circuit,
  failure re-opens it with the doubled backoff.

The breaker is pure host-side state with an injectable clock, so every
transition is unit-testable without sleeping. Heartbeats are the
FAILURE DETECTOR feeding it: the shared-FS beat pattern of
:class:`pddl_tpu.parallel.multiworker.HeartbeatMonitor` applied to the
serving tier — a local replica "beats" by completing a step, a process
replica by answering pipe pings — and a beat older than
``heartbeat_timeout_s`` counts as a failure exactly like an explicit
error does.

The breaker answers DEAD-or-alive; Gray Failure (Huang et al.,
HotOS '17) argues the component that actually takes production down is
the one that is neither — alive enough to pass every ping, degraded
enough to drag every request it touches. :class:`GrayDetector` is the
second detector for exactly that differential: a per-replica latency-
quantile drift monitor (recent p95 vs the replica's own established
baseline, a z-score band with a consecutive-strike debounce) whose
SUSPECTED verdict the router acts on PROACTIVELY — hedging interactive
submissions to a healthy sibling and draining the suspect through the
r16 ``scale_down`` live-migration path before it hard-fails — instead
of waiting for the breaker's threshold that a gray replica, by
definition, never trips.
"""

from __future__ import annotations

import enum
import random
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Set


class BreakerState(enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-replica circuit breaker (CLOSED → OPEN → HALF_OPEN → ...).

    Args:
      failure_threshold: consecutive failures that trip CLOSED → OPEN.
      backoff_base_s: first OPEN interval; doubles per re-open.
      backoff_max_s: backoff cap (bounded exponential).
      jitter_frac: seeded desynchronization (ISSUE 18) — each OPEN
        interval is scaled by ``1 - jitter_frac * U[0, 1)``, so a
        mass-kill does not schedule every replica's HALF_OPEN probe at
        the same instant (the synchronized respawn herd). Subtractive
        on purpose: a jittered probe never fires LATER than the
        deterministic schedule, so backoff bounds still hold. Default
        0.0 (exact doubling — the unit-testable schedule); the router
        arms it fleet-wide with a per-replica seed.
      seed: PRNG seed for the jitter draws (deterministic per replica).
      on_transition: optional ``fn(old: BreakerState, new: BreakerState)``
        — the router wires this to its metrics/tracer so every
        transition is observable.
    """

    def __init__(self, *, failure_threshold: int = 3,
                 backoff_base_s: float = 0.5, backoff_max_s: float = 30.0,
                 jitter_frac: float = 0.0, seed: Optional[int] = None,
                 on_transition: Optional[Callable] = None):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        if backoff_base_s <= 0 or backoff_max_s < backoff_base_s:
            raise ValueError(
                f"need 0 < backoff_base_s <= backoff_max_s, got "
                f"{backoff_base_s}/{backoff_max_s}")
        if not 0.0 <= jitter_frac < 1.0:
            raise ValueError(
                f"jitter_frac must be in [0, 1), got {jitter_frac}")
        self.failure_threshold = int(failure_threshold)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter_frac = float(jitter_frac)
        self._rng = random.Random(seed)
        self.on_transition = on_transition
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.open_until_s = 0.0
        self._backoff_s = self.backoff_base_s

    def _to(self, new: BreakerState) -> None:
        if new is self.state:
            return
        old, self.state = self.state, new
        if self.on_transition is not None:
            self.on_transition(old, new)

    # ------------------------------------------------------------ queries
    @property
    def allows_traffic(self) -> bool:
        """Route new requests here? Only a CLOSED circuit takes traffic
        (HALF_OPEN carries exactly the probe, nothing else)."""
        return self.state is BreakerState.CLOSED

    def probe_due(self, now_s: float) -> bool:
        """OPEN and past the backoff: one recovery probe may fire."""
        return self.state is BreakerState.OPEN and now_s >= self.open_until_s

    # ---------------------------------------------------------- recording
    def begin_probe(self, now_s: float) -> None:
        """OPEN → HALF_OPEN: the single allowed probe is in flight."""
        if self.state is not BreakerState.OPEN:
            raise RuntimeError(
                f"begin_probe from {self.state.value} (must be open)")
        self._to(BreakerState.HALF_OPEN)

    def record_success(self, now_s: float) -> None:
        """A successful call (or probe): close the circuit, reset the
        failure count AND the backoff (a recovered replica earns a
        fresh slate — the next incident starts at the base interval)."""
        self.consecutive_failures = 0
        self._backoff_s = self.backoff_base_s
        self._to(BreakerState.CLOSED)

    def record_failure(self, now_s: float) -> None:
        """One failure/timeout. CLOSED trips at the threshold; a
        HALF_OPEN probe failure re-opens immediately with the doubled
        (capped) backoff."""
        self.consecutive_failures += 1
        if self.state is BreakerState.HALF_OPEN:
            self._reopen(now_s)
        elif (self.state is BreakerState.CLOSED
                and self.consecutive_failures >= self.failure_threshold):
            self._reopen(now_s)

    def trip(self, now_s: float) -> None:
        """Unconditional → OPEN (the router saw the replica die; no
        threshold debate needed)."""
        if self.state is not BreakerState.OPEN:
            self._reopen(now_s)

    def _reopen(self, now_s: float) -> None:
        interval = self._backoff_s
        if self.jitter_frac > 0.0:
            interval *= 1.0 - self.jitter_frac * self._rng.random()
        self.open_until_s = now_s + interval
        self._backoff_s = min(self._backoff_s * 2.0, self.backoff_max_s)
        self._to(BreakerState.OPEN)


class GrayDetector:
    """Latency-quantile degradation detector: per-replica per-tick
    wall samples, recent-window p95 judged against the SAME replica's
    own baseline window via a z-score band, with a consecutive-strike
    debounce. Self-relative on purpose — a fleet-relative comparison
    degenerates at N=2 (the slow replica drags the fleet statistic
    with it), while "this replica drifted from what it used to be" is
    the gray-failure differential itself.

    Pure host-side state, no clock: feed it samples, read
    :attr:`suspected`. The router observes each replica's ``step()``
    wall per round — LATENCY faults in a replica's engine (the
    `utils/faults.py` taxonomy) surface there, which is what makes a
    gray replica injectable in tier-1.

    Args:
      window: recent samples whose p95 is judged.
      baseline: older samples forming the replica's own baseline
        (mean/std). Judging starts once a replica has
        ``window + baseline`` samples — before that it is unknown,
        never suspected.
      z_threshold: strikes accrue while
        ``(recent_p95 - baseline_mean) / baseline_std`` exceeds this.
      min_excess_s: absolute drift floor — the band also requires
        ``recent_p95 >= baseline_mean + min_excess_s``, so a replica
        with a near-zero-variance baseline (std ~ 0 makes any wiggle
        an infinite z) is not condemned over microseconds.
      consecutive: strikes in a row before SUSPECTED (debounce), and
        symmetrically the in-band samples in a row that CLEAR it.
        While suspected the baseline is FROZEN — otherwise a
        persistently slow replica would launder its own degradation
        into the sliding baseline and absolve itself; recovery means
        returning to the band of what it USED to be, after which its
        history restarts fresh.
      smooth: median-of-``smooth`` prefilter (ISSUE 18 de-flake):
        raw samples collect in groups of ``smooth`` and only each
        group's MEDIAN enters the windows. With a small ``window`` the
        recent p95 is effectively the max, so one real scheduler
        hiccup on a loaded host either falsely suspects a healthy
        replica or inflates a baseline's std enough to never suspect
        a gray one; a median absorbs the isolated spike while a
        genuine slow-wall (every sample slow) passes straight
        through. ``1`` (default) judges every raw sample unchanged.
    """

    def __init__(self, *, window: int = 16, baseline: int = 32,
                 z_threshold: float = 4.0, min_excess_s: float = 0.0,
                 consecutive: int = 3, smooth: int = 1):
        if window < 4 or baseline < 4:
            raise ValueError(
                f"need window >= 4 and baseline >= 4, got "
                f"{window}/{baseline}")
        if consecutive < 1:
            raise ValueError(
                f"consecutive must be >= 1, got {consecutive}")
        if smooth < 1:
            raise ValueError(f"smooth must be >= 1, got {smooth}")
        self.window = int(window)
        self.baseline = int(baseline)
        self.z_threshold = float(z_threshold)
        self.min_excess_s = float(min_excess_s)
        self.consecutive = int(consecutive)
        self.smooth = int(smooth)
        self._pending: Dict[int, List[float]] = {}
        self._samples: Dict[int, Deque[float]] = {}
        self._strikes: Dict[int, int] = {}
        self._recovery: Dict[int, int] = {}
        # rid -> (baseline_mean, baseline_std) frozen at suspicion.
        self._frozen: Dict[int, tuple] = {}
        self.suspected: Set[int] = set()

    def observe(self, replica_id: int, seconds: float) -> None:
        """One per-tick wall sample; re-judges the replica when enough
        history exists."""
        rid = int(replica_id)
        seconds = float(seconds)
        if self.smooth > 1:
            pend = self._pending.setdefault(rid, [])
            pend.append(seconds)
            if len(pend) < self.smooth:
                return
            pend.sort()
            seconds = pend[len(pend) // 2]
            self._pending[rid] = []
        if rid in self.suspected:
            # Frozen baseline: the sample itself must return to the
            # band of what the replica USED to be, `consecutive` times
            # in a row, to clear suspicion — then history restarts.
            mean, std = self._frozen[rid]
            band = mean + max(self.min_excess_s,
                              self.z_threshold * (std + 1e-9))
            if seconds <= band:
                self._recovery[rid] = self._recovery.get(rid, 0) + 1
                if self._recovery[rid] >= self.consecutive:
                    self.forget(rid)
            else:
                self._recovery[rid] = 0
            return
        dq = self._samples.setdefault(
            rid, deque(maxlen=self.window + self.baseline))
        dq.append(seconds)
        if len(dq) < self.window + self.baseline:
            return
        samples = list(dq)
        base = samples[:self.baseline]
        recent = sorted(samples[self.baseline:])
        p95 = recent[min(len(recent) - 1,
                         int(0.95 * (len(recent) - 1) + 0.5))]
        mean = sum(base) / len(base)
        var = sum((x - mean) ** 2 for x in base) / len(base)
        std = var ** 0.5
        z = (p95 - mean) / (std + 1e-9)
        if z > self.z_threshold and p95 >= mean + self.min_excess_s:
            self._strikes[rid] = self._strikes.get(rid, 0) + 1
            if self._strikes[rid] >= self.consecutive:
                self.suspected.add(rid)
                self._frozen[rid] = (mean, std)
                self._recovery[rid] = 0
        else:
            self._strikes[rid] = 0

    def forget(self, replica_id: int) -> None:
        """Drop a replica's history and suspicion (death, retirement,
        respawn, recovery — a fresh process re-earns a fresh
        baseline)."""
        rid = int(replica_id)
        self._pending.pop(rid, None)
        self._samples.pop(rid, None)
        self._strikes.pop(rid, None)
        self._recovery.pop(rid, None)
        self._frozen.pop(rid, None)
        self.suspected.discard(rid)

    def is_suspected(self, replica_id: int) -> bool:
        return int(replica_id) in self.suspected
