"""Router write-ahead log: the control plane becomes crash-safe.

Every fault the fleet survives — device errors (r08/r10), replica
death (r11), overload (r12), kill-mid-migration (r16/r18) — assumed
the :class:`~pddl_tpu.serve.fleet.router.FleetRouter` process itself
is immortal. This module removes that assumption with the CheckFreq
discipline (Mohan et al., FAST '21) the training side already applies
to its checkpoints (r10): journal first, verify on read, restore from
the newest VERIFIED state.

**The WAL.** :class:`RouterJournal` appends one CRC-framed binary
record per control-plane event to ``wal.log``:

- ``admit`` — a request entered the fleet (the full replayable
  request: prompt, budget, sampling, priority, tenant fields,
  session). Durable (fsync) BEFORE the caller's handle returns: an
  acked admission survives a router SIGKILL, an unacked one was never
  promised.
- ``route`` — the rid -> replica binding (admission, migration, and
  hedge bindings alike — the ``HandleLedger`` assignment journaled).
- ``tokens`` — the emitted-token mirror delta. fsync-BATCHED: losing
  a tail of token records is safe by construction, because the
  mirror-replay contract (r08 -> r11) regenerates the identical
  tokens from (params, prompt, tokens-so-far).
- ``finish`` — the stream settled (with state/reason); recovery
  replays admits minus finishes.
- ``handoff`` — the disaggregated fleet's prefill->decode KV
  rebinding (ISSUE 17): the finished prefill chain left
  ``from_replica`` and the stream now decodes on ``replica``.
  Audit-only on recovery, like ``route``.

Record framing on disk is ``magic | seq | length | crc32 | payload``;
a torn tail (the record a SIGKILL cut mid-write) fails its CRC or
length check and everything from the first unreadable record on is
discarded — exactly the readable prefix is recovered, which is what
"crash-exact" means for a log.

**Checkpoint + truncate.** The WAL cannot grow forever; every
``checkpoint_every_records`` appends the router snapshots its live
mirrors — riding the `serve/drain.py` entry encoder, the SAME wire
format migration uses — into ``checkpoint.json`` (tmp + fsync +
atomic rename, with an embedded whole-file CRC), demotes the previous
checkpoint to ``checkpoint.prev.json``, and rotates the WAL segment
(``wal.log`` -> ``wal.prev.log``; the segment before THAT is the only
thing deleted — it is covered by two generations of checkpoint).
Every record carries a monotone ``seq`` and the checkpoint stores the
last seq it covers, so a crash anywhere in the cycle replays nothing
twice. A checkpoint that fails its CRC on read (torn by a crash
mid-cycle, bit-rotted later) falls back to the previous verified one
PLUS the rotated segment that checkpoint still covers — the r10
newest-VERIFIED discipline, with no window where corruption loses
acknowledged state.

**Recovery.** :func:`read_state` folds checkpoint + WAL tail into
``{rid: entry}`` drain-format wire entries for every in-flight
stream; :meth:`~pddl_tpu.serve.fleet.router.FleetRouter.recover`
builds a fresh router over fresh/re-spawned replicas and re-enters
them through the r11 mirror-replay path — token-exact, zero special
cases, because router death is now just the snapshot path's second
"normal case".

**Storage faults (ISSUE 18).** The WAL exists to survive crashes, so
it cannot itself be fail-stop-naive about the disk under it. Every
file op routes through :class:`_JournalVFS` (faults injectable via
``utils.faults.StorageFaultPlan``), and failures degrade instead of
propagating out of ``append``:

- transient write/fsync errors retry with bounded backoff;
- persistent failure enters **NON_DURABLE** mode: acks keep flowing
  (``append`` never raises ``OSError``), the backlog is retained
  in-memory, the widened loss-on-crash window is alarmed via the
  ``journal_non_durable`` gauge + a traced event, and rate-limited
  re-arm probes (plus every durable append, which still attempts a
  synchronous write+fsync) restore durability the moment the disk
  recovers — the r08 OOM-degraded discipline applied to storage;
- ``ENOSPC`` sets :attr:`RouterJournal.emergency_checkpoint_due` so
  the router runs an immediate checkpoint+rotate, reclaiming the
  covered segment instead of blind-retrying a full disk;
- a mid-:meth:`RouterJournal.checkpoint` failure aborts with the
  checkpoint/prev pair still readable and never leaves ``_fd``
  closed for good (recovery falls back to the newest VERIFIED state,
  the r10 rule; probes re-open the WAL).

A torn write leaves garbage bytes past the last good frame; the
journal tracks the known-good byte offset and truncates back to it
before writing again, so retries never bury readable records behind
an unreadable tail.
"""

from __future__ import annotations

import errno
import json
import os
import struct
import time
import zlib
from typing import Dict, List, Optional, Tuple

from pddl_tpu.serve import drain as drain_io
from pddl_tpu.utils.faults import StorageFaultKind

# Version 3: version 2 (the disaggregation-era WAL) plus the
# ``epoch`` record — the router-HA single-writer token (ISSUE 20). A
# promoted router stamps its fencing epoch into the log so a forensic
# read shows exactly which writer issued every suffix, and so a
# standby tailing the stream learns leadership changes in-band.
# Bumping the record shape requires bumping this AND renaming
# RECORD_KEYS_V3 — graftlint's snapshot-hygiene rule machine-checks
# the pairing, the same discipline `serve/drain.py` carries for its
# snapshot entries. V1/V2 logs stay readable: the new record kind is
# additive and recovery ignores it like ``route``.
JOURNAL_VERSION = 3
_READABLE_JOURNAL_VERSIONS = frozenset({1, 2, 3})

# Machine-checked wire manifest (graftlint `snapshot-hygiene`): the
# exact record keys the encode_* functions below emit at the CURRENT
# journal version. Changing a record shape requires bumping
# JOURNAL_VERSION and renaming this tuple to RECORD_KEYS_V<new> in the
# same commit — the static checker fails the tree otherwise.
RECORD_KEYS_V3 = ("rec", "rid", "prompt", "max_new_tokens", "sampling",
                  "deadline_s", "priority", "adapter", "constraint",
                  "session", "replica", "via", "toks", "state", "reason",
                  "from_replica", "epoch")

# Machine-checked record-kind vocabulary (graftlint `role-vocab`):
# every ``"rec"`` literal an encoder below emits, exactly. Recovery's
# fold dispatches on these; adding a kind here without a reader-side
# decision (rebuild vs audit-only) is what the rule exists to catch.
RECORD_KINDS = ("admit", "route", "tokens", "finish", "handoff",
                "epoch")

# Machine-checked ``via`` vocabulary (graftlint `role-vocab`): every
# label a ``route`` record may carry — the router's routing labels
# plus the re-bind provenances (``migration``/``hedge``). The
# router's ROUTE_LABELS must be a subset; a label minted there but
# missing here is a binding the forensic reader cannot classify.
VIA_LABELS = ("sticky", "adapter", "affinity", "load", "host_tier",
              "hash", "shed", "prefill", "migration", "hedge")

_HEADER = struct.Struct(">4sQII")  # magic, seq, payload len, crc32
_MAGIC = b"PJL1"

# Machine-checked storage-op vocabulary (graftlint `site-vocab`,
# storage leg): every file-operation site the journal dispatches
# through ``_JournalVFS._storage_op``, exactly — and the SITES of
# ``utils.faults.StorageFaultPlan`` must equal it. An op gated here
# but missing from the plan is a fault coordinate chaos can never
# reach; a plan site nothing dispatches is a schedule that silently
# never fires.
STORAGE_OPS = ("open", "write", "fsync", "replace", "fstat")


class _JournalVFS:
    """The journal's file-op seam: every ``os``-level operation the WAL
    and checkpoint cycle perform goes through here, and an optional
    :class:`~pddl_tpu.utils.faults.StorageFaultPlan` is consulted
    immediately BEFORE each real call — same injection-before-dispatch
    discipline as the device ``_device_call`` sites, so a fault never
    leaves the real op half-observed. TORN is the one exception by
    design: the plan *returns* it and :meth:`write` persists a prefix
    of the buffer before raising EIO, modeling the power-cut shape
    ``_readable_prefix_len`` truncates at recovery."""

    def __init__(self, plan=None):
        self.plan = plan

    def _storage_op(self, op: str) -> Optional[StorageFaultKind]:
        if self.plan is None:
            return None
        return self.plan.check(op)

    def open(self, path: str, flags: int, mode: int = 0o644) -> int:
        self._storage_op("open")
        return os.open(path, flags, mode)

    def write(self, fd: int, data) -> int:
        kind = self._storage_op("write")
        if kind is StorageFaultKind.TORN:
            half = len(data) // 2
            if half:
                os.write(fd, data[:half])
            raise OSError(errno.EIO,
                          f"injected torn write ({half}/{len(data)} bytes)")
        return os.write(fd, data)

    def fsync(self, fd: int) -> None:
        self._storage_op("fsync")
        os.fsync(fd)

    def replace(self, src: str, dst: str) -> None:
        self._storage_op("replace")
        os.replace(src, dst)

    def fstat(self, fd: int):
        self._storage_op("fstat")
        return os.fstat(fd)


def encode_admit(rid: int, request, session: Optional[str]) -> Dict:
    """The admission record: everything replay needs to re-enter the
    request from zero (the drain entry's request half, rid-tagged)."""
    return {
        "rec": "admit",
        "rid": int(rid),
        "prompt": [int(t) for t in request.prompt],
        "max_new_tokens": int(request.max_new_tokens),
        "sampling": drain_io.encode_sampling(request.sampling),
        "deadline_s": (float(request.deadline_s)
                       if request.deadline_s is not None else None),
        "priority": request.priority.value,
        "adapter": (str(request.adapter)
                    if request.adapter is not None else None),
        "constraint": request.constraint,
        "session": session,
    }


def encode_route(rid: int, replica_id: int, via: str) -> Dict:
    """The rid -> replica binding (``via``: the routing label, or
    ``migration``/``hedge`` for re-binds)."""
    return {"rec": "route", "rid": int(rid), "replica": int(replica_id),
            "via": str(via)}


def encode_tokens(rid: int, toks: List[int]) -> Dict:
    """The emitted-token mirror delta (fsync-batched; safe to lose —
    replay regenerates)."""
    return {"rec": "tokens", "rid": int(rid),
            "toks": [int(t) for t in toks]}


def encode_finish(rid: int, state: str, reason: Optional[str]) -> Dict:
    return {"rec": "finish", "rid": int(rid), "state": str(state),
            "reason": reason}


def encode_handoff(rid: int, from_replica: int, to_replica: int) -> Dict:
    """The prefill->decode KV rebinding (disaggregated fleet, ISSUE
    17): the finished prefill chain shipped from ``from_replica`` and
    the stream now runs on ``to_replica``. Audit-only on recovery —
    like ``route``, the fresh fleet re-routes — but it is what a
    hand-off forensic reads."""
    return {"rec": "handoff", "rid": int(rid),
            "replica": int(to_replica),
            "from_replica": int(from_replica)}


def encode_fence_epoch(epoch: int) -> Dict:
    """Encoder for the ``"epoch"`` record (NOT ``encode_epoch``: a
    helper named ``encode_<declared wire key>`` reads as a nested
    sub-encoder to graftlint's snapshot-hygiene manifest check, and
    ``epoch`` is both the record kind and its field).

    The single-writer token (router HA, ISSUE 20): the issuing
    router's fencing epoch, stamped at arm/takeover and re-stamped
    after every checkpoint so the live WAL tail always carries the
    current writer's identity. Audit-only on recovery — leadership is
    re-acquired through the lease, never replayed — but it is what a
    split-brain forensic reads."""
    return {"rec": "epoch", "epoch": int(epoch)}


class RouterJournal:
    """Append-only, CRC-framed, fsync-batched control-plane WAL with an
    atomic checkpoint+truncate cycle.

    Args:
      journal_dir: directory holding ``wal.log`` / ``checkpoint.json``
        / ``checkpoint.prev.json``. Created if absent; an existing
        WAL/checkpoint is picked up (the recovery path) and appends
        continue after the readable prefix.
      fsync_batch_records: buffered (non-durable) appends are flushed
        on every :meth:`tick` and fsynced once this many records are
        pending — the token-delta batching knob. ``1`` fsyncs every
        record (chaos tests wanting exact durability).
      checkpoint_every_records: :attr:`checkpoint_due` turns True after
        this many appended records since the last checkpoint; the
        router runs the cycle from its step loop.
      storage_plan: optional
        :class:`~pddl_tpu.utils.faults.StorageFaultPlan` the VFS shim
        consults before every file op (chaos/testing).
      retry_limit: bounded-backoff retries for a transient write/fsync
        error while still durable; past it the journal degrades to
        NON_DURABLE. While already degraded every attempt is single-
        shot (it doubles as a probe) — no backoff hammering.
      retry_backoff_s: first retry delay (doubles per retry).
      rearm_interval_s: minimum spacing of NON_DURABLE re-arm probes
        driven from :meth:`tick`.
      clock / sleep_fn: injectable time sources (tests use fakes).
    """

    def __init__(self, journal_dir: str, *,
                 fsync_batch_records: int = 64,
                 checkpoint_every_records: int = 4096,
                 storage_plan=None,
                 retry_limit: int = 3,
                 retry_backoff_s: float = 0.001,
                 rearm_interval_s: float = 0.25,
                 clock=time.monotonic,
                 sleep_fn=time.sleep):
        self.dir = journal_dir
        os.makedirs(journal_dir, exist_ok=True)
        self.wal_path = os.path.join(journal_dir, "wal.log")
        self.wal_prev_path = os.path.join(journal_dir, "wal.prev.log")
        self.checkpoint_path = os.path.join(journal_dir,
                                            "checkpoint.json")
        self.checkpoint_prev_path = os.path.join(journal_dir,
                                                 "checkpoint.prev.json")
        self._fsync_batch = max(1, int(fsync_batch_records))
        self._checkpoint_every = max(1, int(checkpoint_every_records))
        self.vfs = _JournalVFS(storage_plan)
        self._retry_limit = max(0, int(retry_limit))
        self._retry_backoff_s = float(retry_backoff_s)
        self._rearm_interval_s = float(rearm_interval_s)
        self._clock = clock
        self._sleep = sleep_fn
        # Degradation state (the r08 discipline applied to storage).
        self.non_durable = False
        self.emergency_checkpoint_due = False
        self.storage_errors = 0    # every OSError observed, retries incl.
        self.degraded_events = 0   # entries into NON_DURABLE
        self.rearms = 0            # exits from NON_DURABLE
        self._next_probe_s = 0.0
        self._wal_bytes_last = 0
        # Observer ``fn(event, detail_dict)`` — the router wires it to
        # its tracer + FleetMetrics so degradation is alarmable.
        self.on_storage_event = None
        # Observer ``fn(seq, record)`` — fired on EVERY append, before
        # any disk I/O, so a hot standby's WAL shipper sees the record
        # stream even while the journal is degraded NON_DURABLE (when
        # the disk shows nothing, the wire is the only replica of the
        # backlog). Must not raise; must not touch the journal.
        self.on_record = None
        # Continue the seq line past whatever is already durable — and
        # TRUNCATE the torn tail first: appending after unreadable
        # bytes would put every later record (fsynced admits included)
        # beyond the readable prefix recovery stops at.
        last_seq = self._scan_last_seq()
        self._next_seq = last_seq + 1
        self._fd: Optional[int] = None
        self._good_bytes = 0       # byte offset of the last good frame end
        self._dirty_tail = False   # garbage past _good_bytes (failed write)
        self._open_wal()           # loud on a dead disk: arming must fail
        self._pending = 0          # appended but not yet fsynced
        self._buffer: List[bytes] = []
        self.records_since_checkpoint = 0
        self.records_appended = 0
        self.fsyncs = 0
        self._closed = False

    # ------------------------------------------------------------- append
    def append(self, record: Dict, *, durable: bool = False) -> int:
        """Frame + buffer one record; ``durable=True`` flushes AND
        fsyncs before returning (the admit contract). Returns the
        record's seq.

        NEVER raises ``OSError``: a storage failure degrades the
        journal to NON_DURABLE (alarmed, probed, backlog retained)
        instead of killing the control plane that exists to survive
        crashes. Callers check :attr:`non_durable` / wire
        :attr:`on_storage_event` when they need to know."""
        if self._closed:
            raise ValueError("journal is closed")
        seq = self._next_seq
        self._next_seq += 1
        payload = json.dumps(record, separators=(",", ":")).encode()
        frame = _HEADER.pack(_MAGIC, seq, len(payload),
                             zlib.crc32(payload) & 0xFFFFFFFF) + payload
        self._buffer.append(frame)
        self._pending += 1
        self.records_appended += 1
        self.records_since_checkpoint += 1
        if self.on_record is not None:
            self.on_record(seq, record)
        if durable or self._pending >= self._fsync_batch:
            if self.non_durable:
                # Writes pause while degraded: the batch threshold must
                # not hammer the dead disk with an O(backlog) join per
                # batch — durable appends become rate-limited probe
                # opportunities instead, same cadence as tick().
                self._maybe_probe()
            else:
                self.commit()
        return seq

    # -------------------------------------------------- degradation core
    def _observe(self, event: str, **detail) -> None:
        if self.on_storage_event is not None:
            self.on_storage_event(event, detail)

    def _enter_non_durable(self, op: str, err: OSError) -> None:
        if not self.non_durable:
            self.non_durable = True
            self.degraded_events += 1
            self._observe("journal_degraded", op=op,
                          errno=err.errno, error=str(err))
        self._next_probe_s = self._clock() + self._rearm_interval_s

    def _rearm(self) -> None:
        if self.non_durable:
            self.non_durable = False
            self.rearms += 1
            self._observe("journal_rearmed")

    def _open_wal(self) -> None:
        """(Re)open the WAL fd after truncating any unreadable tail —
        shared by __init__, the checkpoint rotate, and re-arm probes.
        Raises ``OSError`` (callers decide loud vs degrade)."""
        prefix = _readable_prefix_len(self.wal_path)
        if prefix is not None:
            with open(self.wal_path, "r+b") as f:
                f.truncate(prefix)
        self._fd = self.vfs.open(
            self.wal_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        self._good_bytes = prefix or 0
        self._dirty_tail = False
        self._wal_bytes_last = self._good_bytes

    def _try_reopen(self) -> bool:
        try:
            self._open_wal()
            return True
        except OSError as e:
            self.storage_errors += 1
            self._observe("journal_storage_error", op="open",
                          errno=e.errno, error=str(e))
            self._enter_non_durable("open", e)
            return False

    def _repair_tail(self) -> bool:
        """Truncate garbage a failed/torn write left past the last good
        frame, so a retry never buries readable records behind an
        unreadable tail."""
        if not self._dirty_tail:
            return True
        try:
            os.ftruncate(self._fd, self._good_bytes)
            self._dirty_tail = False
            return True
        except OSError:
            return False

    def _write_all(self, data: bytes) -> None:
        view = memoryview(data)
        while view:
            n = self.vfs.write(self._fd, view)
            view = view[n:]

    def _guard(self, fn, op: str) -> bool:
        """Run one file op with bounded-backoff retries (single-shot
        while already degraded — every attempt then doubles as a
        probe). On persistent failure: degrade, don't raise."""
        attempts = 1 if self.non_durable else self._retry_limit + 1
        delay = self._retry_backoff_s
        err: Optional[OSError] = None
        for i in range(attempts):
            try:
                fn()
                return True
            except OSError as e:
                err = e
                self.storage_errors += 1
                self._observe("journal_storage_error", op=op,
                              errno=e.errno, error=str(e))
                if op == "write":
                    # Unknown how much landed: mark the tail dirty and
                    # repair before any retry.
                    self._dirty_tail = True
                    if not self._repair_tail():
                        break
                if e.errno == errno.ENOSPC:
                    # A full disk is not transient: reclaim space via
                    # an emergency checkpoint+rotate, don't blind-retry.
                    self.emergency_checkpoint_due = True
                    break
                if i + 1 < attempts:
                    self._sleep(delay)
                    delay *= 2.0
        self._enter_non_durable(op, err)
        return False

    def _flush(self, fsync: bool) -> bool:
        if self._fd is None and not self._try_reopen():
            return False
        if not self._repair_tail():
            return False
        if self._buffer:
            data = b"".join(self._buffer)
            if not self._guard(lambda: self._write_all(data), "write"):
                return False
            self._good_bytes += len(data)
            self._buffer = []
        if fsync and (self._pending or self.non_durable):
            # While degraded, fsync even with nothing pending: the
            # successful fsync IS the probe signal that re-arms.
            if not self._guard(lambda: self.vfs.fsync(self._fd), "fsync"):
                return False
            self.fsyncs += 1
            self._pending = 0
        if fsync and self.non_durable:
            self._rearm()
        return True

    def commit(self) -> bool:
        """Flush the buffer and fsync. Returns True when everything
        appended so far is durable; False when the journal is (now)
        running NON_DURABLE with the backlog retained in-memory."""
        return self._flush(fsync=True)

    def tick(self) -> None:
        """The step-cadence flush: write buffered frames to the OS (so
        a mere router restart loses nothing) but only fsync when the
        batch threshold says so — the fsync-batching contract. While
        NON_DURABLE, writes pause and this fires a rate-limited re-arm
        probe instead (a full flush+fsync attempt) — no hammering a
        dead disk every step."""
        if self.non_durable:
            self._maybe_probe()
            return
        self._flush(fsync=self._pending >= self._fsync_batch)

    def _maybe_probe(self) -> None:
        """One rate-limited re-arm attempt (full flush+fsync) if the
        probe interval has elapsed — the ONLY disk path while degraded,
        shared by tick() and the append batch threshold."""
        now = self._clock()
        if now >= self._next_probe_s:
            self._next_probe_s = now + self._rearm_interval_s
            self._flush(fsync=True)

    @property
    def checkpoint_due(self) -> bool:
        return self.records_since_checkpoint >= self._checkpoint_every

    @property
    def wal_bytes(self) -> int:
        """On-disk WAL size — last KNOWN size when ``fstat`` fails
        (counted, not swallowed to 0: a zero here reads as "empty WAL"
        to checkpoint/size telemetry during exactly the disk failures
        it should be reporting)."""
        if self._fd is not None:
            try:
                self._wal_bytes_last = int(
                    self.vfs.fstat(self._fd).st_size)
            except OSError as e:
                self.storage_errors += 1
                self._observe("journal_storage_error", op="fstat",
                              errno=e.errno, error=str(e))
        return self._wal_bytes_last

    # --------------------------------------------------------- checkpoint
    def checkpoint(self, entries: List[Tuple[int, Dict]],
                   next_rid: int) -> bool:
        """The atomic checkpoint+truncate cycle: snapshot the live
        rid-tagged mirrors (drain-format entries — the encoder
        migration already rides), make it durable and verified, THEN
        truncate the WAL. Crash anywhere inside: recovery still finds
        either (new checkpoint, truncated-or-full WAL with seqs the
        checkpoint covers marked) or (previous checkpoint, full WAL).

        Storage-fault contract (ISSUE 18): a failure anywhere in the
        cycle ABORTS with the checkpoint/prev pair still readable
        (recovery falls back to the newest VERIFIED state, the r10
        rule) and never leaves ``_fd`` closed for good — at worst the
        WAL fd is down and NON_DURABLE probes re-open it. Returns True
        on a completed cycle. A verified checkpoint makes everything
        it covers durable regardless of the WAL backlog, so success
        drops the covered backlog and re-arms a degraded journal;
        this is also exactly how the ``ENOSPC`` emergency path
        reclaims space (the rotate retires the oldest segment)."""
        self.commit()  # best effort: the snapshot comes from live mirrors
        covered_seq = self._next_seq - 1
        body = {
            "version": JOURNAL_VERSION,
            "snapshot_version": drain_io.SNAPSHOT_VERSION,
            "covered_seq": covered_seq,
            "next_rid": int(next_rid),
            "requests": [[int(rid), entry] for rid, entry in entries],
        }
        blob = json.dumps(body, sort_keys=True,
                          separators=(",", ":")).encode()
        payload = json.dumps({"crc": zlib.crc32(blob) & 0xFFFFFFFF,
                              "body": body}).encode()
        tmp = self.checkpoint_path + ".tmp"
        try:
            fd = self.vfs.open(
                tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
            try:
                view = memoryview(payload)
                while view:
                    n = self.vfs.write(fd, view)
                    view = view[n:]
                self.vfs.fsync(fd)
            finally:
                os.close(fd)
            if os.path.exists(self.checkpoint_path):
                self.vfs.replace(self.checkpoint_path,
                                 self.checkpoint_prev_path)
            self.vfs.replace(tmp, self.checkpoint_path)
        except OSError as e:
            # Abort with the pair readable: the worst interleaving
            # (current demoted to prev, tmp never promoted) still
            # leaves the old checkpoint verified at the prev slot.
            self.storage_errors += 1
            self._observe("journal_checkpoint_failed", op="checkpoint",
                          errno=e.errno, error=str(e))
            self._enter_non_durable("checkpoint", e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        # The new checkpoint is durable and verified: every buffered
        # frame has seq <= covered_seq, so a retained NON_DURABLE
        # backlog is covered and can be dropped.
        self.emergency_checkpoint_due = False
        self._buffer = []
        self._pending = 0
        # Rotate the WAL segment rather than truncating it: the
        # segment this checkpoint covers stays on disk as
        # wal.prev.log until the NEXT cycle retires it, so a
        # checkpoint that later fails its CRC can still fall back to
        # checkpoint.prev + this segment with nothing lost. seq keeps
        # counting upward so the covered_seq skip-rule stays monotone
        # across cycles.
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
        rotated = True
        try:
            if os.path.exists(self.wal_path):
                self.vfs.replace(self.wal_path, self.wal_prev_path)
        except OSError as e:
            # Rotation is an optimization (space reclaim); appends can
            # continue in the un-rotated segment — the covered_seq
            # skip-rule keeps replay exact either way.
            rotated = False
            self.storage_errors += 1
            self._observe("journal_storage_error", op="replace",
                          errno=e.errno, error=str(e))
        self.records_since_checkpoint = 0
        if not self._try_reopen():
            return False  # fd down; NON_DURABLE probes keep retrying
        self._rearm()     # checkpoint + live fd = durable again
        return rotated

    # --------------------------------------------------------------- read
    def _scan_last_seq(self) -> int:
        last = 0
        for path in (self.wal_prev_path, self.wal_path):
            for seq, _ in iter_wal_records(path):
                last = max(last, seq)
        cp = load_checkpoint(self.dir)
        if cp is not None:
            last = max(last, int(cp.get("covered_seq", 0)))
        return last

    def close(self) -> None:
        if not self._closed:
            self.commit()  # best effort — close never raises OSError
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None
            self._closed = True


def _readable_prefix_len(wal_path: str) -> Optional[int]:
    """Byte length of the WAL's readable prefix (None when the file
    does not exist). Everything past it is a torn tail appends must
    not land behind."""
    try:
        with open(wal_path, "rb") as f:
            data = f.read()
    except OSError:
        return None
    end = 0
    off = 0
    while off + _HEADER.size <= len(data):
        magic, _, length, crc = _HEADER.unpack_from(data, off)
        if magic != _MAGIC:
            break
        start = off + _HEADER.size
        stop = start + length
        if stop > len(data) \
                or (zlib.crc32(data[start:stop]) & 0xFFFFFFFF) != crc:
            break
        off = stop
        end = off
    return end


def iter_wal_records(wal_path: str):
    """``(seq, record)`` for the READABLE prefix of a WAL file: stops
    at the first torn/corrupt frame (a record whose header, length, or
    CRC does not verify) — everything after it is untrusted."""
    try:
        with open(wal_path, "rb") as f:
            data = f.read()
    except OSError:
        return
    off = 0
    while off + _HEADER.size <= len(data):
        magic, seq, length, crc = _HEADER.unpack_from(data, off)
        if magic != _MAGIC:
            return  # lost framing: discard the tail
        start = off + _HEADER.size
        end = start + length
        if end > len(data):
            return  # torn tail: the record a crash cut mid-write
        payload = data[start:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            return  # corrupt record: nothing after it is trusted
        try:
            record = json.loads(payload)
        except ValueError:
            return
        yield int(seq), record
        off = end


def tail_wal_records(wal_path: str,
                     offset: int = 0) -> Tuple[List[Tuple[int, Dict]],
                                               int]:
    """Incremental WAL read for a standby's catch-up loop:
    ``(records, new_offset)`` over the readable frames starting at byte
    ``offset``. Pass the returned offset back on the next poll to read
    only what the primary appended since — the file is never re-parsed
    from the top. A torn/corrupt frame ends the read at its start (the
    offset does NOT advance past it), so a half-flushed tail is re-read
    whole once the primary completes it."""
    try:
        with open(wal_path, "rb") as f:
            f.seek(offset)
            data = f.read()
    except OSError:
        return [], offset
    out: List[Tuple[int, Dict]] = []
    off = 0
    while off + _HEADER.size <= len(data):
        magic, seq, length, crc = _HEADER.unpack_from(data, off)
        if magic != _MAGIC:
            break
        start = off + _HEADER.size
        end = start + length
        if end > len(data):
            break
        payload = data[start:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            break
        try:
            record = json.loads(payload)
        except ValueError:
            break
        out.append((int(seq), record))
        off = end
    return out, offset + off


def load_checkpoint(journal_dir: str) -> Optional[Dict]:
    """The newest VERIFIED checkpoint body (r10 discipline): the
    current file if its embedded CRC verifies, else the previous one,
    else None (recover from the WAL alone)."""
    for name in ("checkpoint.json", "checkpoint.prev.json"):
        path = os.path.join(journal_dir, name)
        try:
            with open(path) as f:
                wrapped = json.load(f)
        except (OSError, ValueError):
            continue
        body = wrapped.get("body")
        if not isinstance(body, dict):
            continue
        blob = json.dumps(body, sort_keys=True,
                          separators=(",", ":")).encode()
        if (zlib.crc32(blob) & 0xFFFFFFFF) != wrapped.get("crc"):
            continue  # torn mid-cycle: fall back to the previous one
        if body.get("version") not in _READABLE_JOURNAL_VERSIONS:
            raise ValueError(
                f"router journal version {body.get('version')!r} "
                f"unsupported (this build reads "
                f"{sorted(_READABLE_JOURNAL_VERSIONS)})")
        return body


def read_state(journal_dir: str) -> Tuple[Dict[int, Dict], int]:
    """Fold checkpoint + WAL tail into the recoverable control-plane
    state: ``({rid: drain-format entry}, next_rid)`` for every stream
    that was admitted and had not finished. Entries carry the mirrored
    tokens, so the r11 replay path continues each stream token-exactly
    from what the journal durably saw (tokens past the last fsync
    replay to the identical values — they are a pure function of
    (params, prompt, tokens-so-far))."""
    entries: Dict[int, Dict] = {}
    max_rid = -1
    covered_seq = 0
    cp = load_checkpoint(journal_dir)
    if cp is not None:
        covered_seq = int(cp.get("covered_seq", 0))
        max_rid = int(cp.get("next_rid", 0)) - 1
        for rid, entry in cp.get("requests", []):
            entries[int(rid)] = dict(entry)
    finished: set = set()
    records: List[Tuple[int, Dict]] = []
    # Both segments, rotated-first: seqs are monotone across rotation,
    # and the covered_seq filter drops whatever the verified
    # checkpoint already folded in — including the whole prev segment
    # when the CURRENT checkpoint verified, and only its pre-prev
    # prefix when recovery fell back a generation.
    for name in ("wal.prev.log", "wal.log"):
        records.extend(iter_wal_records(os.path.join(journal_dir, name)))
    records.sort(key=lambda p: p[0])
    for seq, record in records:
        if seq <= covered_seq:
            continue  # the checkpoint already folded this record in
        kind = record.get("rec")
        rid = int(record.get("rid", -1))
        max_rid = max(max_rid, rid)
        if kind == "admit":
            entry = {k: record.get(k) for k in
                     ("prompt", "max_new_tokens", "sampling",
                      "deadline_s", "priority", "adapter",
                      "constraint")}
            entry["tokens"] = []
            entry["elapsed_s"] = 0.0
            entry["ttft_s"] = None
            entry["session"] = record.get("session")
            entries[rid] = entry
        elif kind == "tokens" and rid in entries:
            entries[rid]["tokens"] = (
                list(entries[rid].get("tokens", []))
                + [int(t) for t in record.get("toks", [])])
        elif kind == "finish":
            finished.add(rid)
            entries.pop(rid, None)
        # "route", "handoff", and "epoch" records rebuild nothing
        # here: recovery re-routes on the fresh fleet (the old bindings
        # name dead processes) and re-acquires leadership through the
        # lease, but they make the decision history auditable and are
        # what a partial-failover, hand-off, or split-brain forensic
        # reads.
    for rid in finished:
        entries.pop(rid, None)
    return entries, max_rid + 1
