"""Open-loop trace replay with honest backpressure handling.

The one replay client the fleet benches and tests share, replacing the
per-bench submit loops. Two disciplines the old loops got wrong:

- **Unwind hints are honored.** The fleet's rejections are TYPED and
  carry an honest ``retry_after_s`` (`serve/request.py`: QueueFull's
  queue-drain estimate, AdmissionRejected's brownout-ladder unwind
  horizon). The r12 harness dropped rejected events (or busy-retried on
  a fixed sleep), understating how well the brownout recovers polite
  clients; this client re-enqueues a rejected event at
  ``now + retry_after_s`` — the behavior a well-behaved caller actually
  has — and only counts it shed after ``max_attempts`` unwinds.
- **Resource-hours are metered.** Each poll tick integrates the live
  replica count (plus any spawn in flight — a warming worker burns a
  replica before it serves a token) into ``replica_seconds``, and the
  time the brownout ladder spends above NORMAL into ``rung_seconds``.
  ``goodput_per_replica_hour`` — delivered tokens of FINISHED requests
  per replica-hour — is the one end-to-end production metric the
  autoscale bench (and every future scheduling/caching change) is
  judged on, AlpaServe's per-resource-hour framing made concrete.

The replay runs in REAL time (event ``t`` offsets from the start), so
TTFT includes genuine queue wait; a ``hang_s`` deadline guarantees the
loop reports stragglers instead of spinning on a regression forever.
"""

from __future__ import annotations

import heapq
import time
from typing import Callable, Dict, List, Tuple

from pddl_tpu.serve.fleet.router import NoHealthyReplica, ReplicaLifecycle
from pddl_tpu.serve.request import Priority, QueueFull


class ReplayReport:
    """One replay's outcome: the fleet handles (paired with their
    events), terminal shed counts per class, retry bookkeeping, and the
    integrated resource/rung meters."""

    def __init__(self):
        self.handles: List[Tuple[Dict[str, object], object]] = []
        self.rejects: Dict[str, int] = {p.value: 0 for p in Priority}
        self.retried_after_hint = 0
        self.hinted_rejects = 0
        self.wall_s = 0.0
        self.replica_seconds = 0.0
        self.rung_seconds = 0.0
        self.all_terminal = True
        self.stragglers = 0

    @property
    def delivered_tokens(self) -> int:
        return sum(len(h.tokens) for _, h in self.handles)

    @property
    def goodput_tokens(self) -> int:
        """Tokens of requests that FINISHED (a timed-out or failed
        stream's partial output is delivered work, not good work)."""
        return sum(len(h.tokens) for _, h in self.handles
                   if h.state.value == "finished")

    @property
    def replica_hours(self) -> float:
        return self.replica_seconds / 3600.0

    @property
    def goodput_per_replica_hour(self) -> float:
        """THE production metric: finished tokens per replica-hour."""
        return self.goodput_tokens / max(self.replica_hours, 1e-12)


def _live_replicas(fleet) -> int:
    n = sum(1 for s in fleet.replicas
            if s.state is ReplicaLifecycle.UP)
    scaler = fleet.autoscaler
    if scaler is not None:
        n += scaler.pending_spawns
    return n


def replay_trace(fleet, schedule, *, honor_hints: bool = True,
                 max_attempts: int = 5, default_retry_s: float = 0.1,
                 hang_s: float = 300.0, idle_sleep_s: float = 0.0005,
                 clock: Callable[[], float] = time.monotonic,
                 on_tick=None) -> ReplayReport:
    """Replay ``schedule`` (tracegen events, ``t``-sorted) through a
    :class:`~.router.FleetRouter` in real time.

    A rejection (``QueueFull`` — ``AdmissionRejected`` included) with
    ``honor_hints`` re-enqueues the event at ``now + retry_after_s``
    (``default_retry_s``, doubled per attempt, when no hint came);
    after ``max_attempts`` submissions the event counts as terminally
    shed in ``rejects``. ``on_tick(now, fleet)`` runs once per poll
    loop — the bench's chaos-injection hook."""
    report = ReplayReport()
    # (due_time, seq, attempt, event): seq breaks ties deterministically.
    pending: List[Tuple[float, int, int, Dict[str, object]]] = []
    for seq, ev in enumerate(schedule):
        heapq.heappush(pending, (float(ev["t"]), seq, 1, ev))
    seq = len(schedule)
    t0 = clock()
    deadline = t0 + hang_s
    last = t0
    while pending or fleet.has_work:
        now_abs = clock()
        if now_abs > deadline:
            break  # stranded work: report it, don't hang
        dt, last = now_abs - last, now_abs
        report.replica_seconds += dt * _live_replicas(fleet)
        if fleet.admission is not None and int(fleet.admission.rung) > 0:
            report.rung_seconds += dt
        now = now_abs - t0
        while pending and pending[0][0] <= now:
            _due, _, attempt, ev = heapq.heappop(pending)
            try:
                h = fleet.submit(ev["prompt"], ev["new_tokens"],
                                 priority=ev["priority"],
                                 deadline_s=ev.get("deadline_s"),
                                 session=ev.get("session"),
                                 adapter=ev.get("adapter"))
                report.handles.append((ev, h))
            except QueueFull as e:  # AdmissionRejected included
                if e.retry_after_s is not None:
                    report.hinted_rejects += 1
                if honor_hints and attempt < max_attempts:
                    hint = (e.retry_after_s
                            if e.retry_after_s is not None
                            else default_retry_s * (2 ** (attempt - 1)))
                    seq += 1
                    heapq.heappush(pending,
                                   (now + float(hint), seq,
                                    attempt + 1, ev))
                    report.retried_after_hint += 1
                else:
                    report.rejects[ev["priority"].value] += 1
            except NoHealthyReplica:
                # A momentary total outage (every breaker open) is the
                # hintless transient a polite client retries too; only
                # this — a genuinely unexpected error (a malformed
                # event, a submit regression) must CRASH the replay,
                # never masquerade as a plausible shed count.
                if honor_hints and attempt < max_attempts:
                    seq += 1
                    heapq.heappush(pending,
                                   (now + default_retry_s
                                    * (2 ** (attempt - 1)), seq,
                                    attempt + 1, ev))
                    report.retried_after_hint += 1
                else:
                    report.rejects[ev["priority"].value] += 1
        if on_tick is not None:
            on_tick(now, fleet)
        if fleet.step() == 0 and idle_sleep_s > 0:
            time.sleep(idle_sleep_s)
    report.wall_s = clock() - t0
    report.stragglers = sum(1 for _, h in report.handles if not h.done)
    report.all_terminal = report.stragglers == 0
    return report
