"""Replica drivers: one engine behind a uniform router-facing surface.

The router (`fleet/router.py`) speaks one small protocol no matter how
a replica actually runs:

- ``submit(rid, ...)`` / ``cancel(rid)`` — requests enter keyed by a
  ROUTER-assigned id (engine request ids are per-process counters and
  mean nothing across a fleet).
- ``step() -> events`` — advance/pump the replica; returns the token
  and finish events since the last call as plain dicts (the same
  shapes the process worker writes over its pipe, so the router cannot
  care which driver produced them).
- ``drain_entries(now) -> [(rid, entry)]`` — the live-migration
  capture: every in-flight request's host state in the
  `serve/drain.py` wire format, rid-tagged. Raises when the replica is
  beyond draining (hard-killed process) — the router then falls back
  to its own prompt+emitted-token mirrors, which is exactly r08's
  in-engine replay contract promoted to the fleet level.
- ``restore(pairs)`` — live migration in: wire entries re-enter this
  replica's engine through the normal drain-restore replay path, so a
  migrated stream continues token-exactly.

Two drivers:

- :class:`LocalReplica` — in-process :class:`~pddl_tpu.serve.ServeEngine`
  stepped by the router. Deterministic (injectable clocks/fault plans
  reach the engine directly), so the tier-1 fleet chaos matrix runs on
  it; a replica "dies" when :class:`~pddl_tpu.utils.faults.KillPoint`
  (or a real error) unwinds out of ``step()``.
- :class:`ProcessReplica` — a real OS process (`fleet/worker.py`)
  driven over a stdio JSON-line pipe; pings are the heartbeat, EOF or
  ``SIGKILL`` is death. This is the "multiprocess on CPU" deployment
  the bench measures: N workers genuinely run in parallel.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from pddl_tpu.obs.propagate import ClockAligner, SpanShipper
from pddl_tpu.serve import drain as drain_io
from pddl_tpu.serve.fleet.disagg import validate_role
from pddl_tpu.serve.fleet.transport import (
    MAX_FRAME_BYTES,
    FrameReceiver,
    FrameSender,
    decode_control,
    encode_control,
)
from pddl_tpu.serve.request import (
    Priority,
    QueueFull,
    RequestState,
    SamplingParams,
)


class ReplicaDied(RuntimeError):
    """The replica is gone mid-operation (process exited, pipe EOF).
    The router treats this exactly like a ``KillPoint`` unwinding out of
    a local replica's step: replica down, migrate the in-flight work."""

    def __init__(self, replica_id: int, why: str):
        self.replica_id = replica_id
        super().__init__(f"replica {replica_id} died: {why}")


class ReplicaSpawnTimeout(ReplicaDied):
    """A spawned worker never became ready inside its budget. Subclass
    of :class:`ReplicaDied` (every existing handler still catches it),
    but TYPED so a scale-up controller can tell "this spawn wedged —
    back off and retry later" from "a serving replica died — migrate
    its work": the autoscaler keys its breaker-style spawn backoff off
    this, instead of hanging the router's control loop behind a worker
    that will never ack."""

    def __init__(self, replica_id: int, waited_s: float):
        self.waited_s = float(waited_s)
        super().__init__(replica_id,
                         f"spawn timed out after {waited_s:.1f}s "
                         "(worker never became ready)")


class EpochFenced(RuntimeError):
    """The worker refused a command stamped with a STALE fencing epoch
    (router HA, ISSUE 20): a newer router has taken over and this
    driver's caller is the deposed primary. Deliberately NOT a
    :class:`ReplicaDied` — the replica is healthy and serving the new
    epoch's commands; the correct reaction is to stop commanding, not
    to migrate the replica's work."""

    def __init__(self, replica_id: int, epoch: int, highest: int):
        self.replica_id = int(replica_id)
        self.epoch = int(epoch)
        self.highest = int(highest)
        super().__init__(
            f"replica {replica_id} fenced epoch {epoch} command "
            f"(highest seen: {highest})")


# Machine-checked fencing manifest (graftlint `epoch-vocab`): the
# exact worker-bound command kinds the drivers below stamp with the
# issuing router's epoch — the fleet-state mutators plus the ``fence``
# probe itself. The worker's FENCED_CMDS dispatch table must equal
# this tuple, both directions: a command stamped here but unchecked
# there is a hole a deposed primary drives through; one checked there
# but never stamped here would fence every legacy (epoch-free) caller.
EPOCH_CMDS = ("submit", "cancel", "restore", "fence")


# The submit protocol's sampling wire shape IS the drain snapshot's —
# one encode/decode pair (`serve/drain.py`) for both.
sampling_to_wire = drain_io.encode_sampling
sampling_from_wire = drain_io.decode_sampling


def snapshot_from_pairs(pairs: List[Tuple[int, Dict]]) -> Dict[str, object]:
    """rid-tagged wire entries → a `serve/drain.py` snapshot dict the
    engine's ``restore()`` accepts. The one place the fleet assembles a
    snapshot (both drivers and the worker's restore handler), so a
    format/version change happens here, not in three copies."""
    return {"version": drain_io.SNAPSHOT_VERSION,
            "requests": [entry for _, entry in pairs]}


class HandleLedger:
    """rid → engine handle, plus the diff cursor that turns polled
    handle state into incremental events. Shared by :class:`LocalReplica`
    and the process worker so both emit identical event streams."""

    def __init__(self):
        self._handles: Dict[int, object] = {}
        self._sent: Dict[int, int] = {}

    def add(self, rid: int, handle) -> None:
        self._handles[rid] = handle
        # A restored/migrated handle arrives with its pre-migration
        # tokens attached; those were already streamed to the caller.
        self._sent[rid] = len(handle.tokens)

    def get(self, rid: int):
        return self._handles.get(rid)

    def harvest(self) -> List[Dict[str, object]]:
        """Events since the last harvest: one ``tokens`` event batching
        every stream's new tokens, then a ``finish`` per settled
        request (token order inside a tick does not matter — each
        stream's own order is what token-exactness pins)."""
        events: List[Dict[str, object]] = []
        toks: List[Tuple[int, List[int]]] = []
        done: List[int] = []
        for rid, h in self._handles.items():
            sent = self._sent[rid]
            if len(h.tokens) > sent:
                toks.append((rid, [int(t) for t in h.tokens[sent:]]))
                self._sent[rid] = len(h.tokens)
            if h.done:
                done.append(rid)
        if toks:
            events.append({"ev": "tokens", "toks": toks})
        for rid in done:
            h = self._handles.pop(rid)
            self._sent.pop(rid, None)
            events.append({
                "ev": "finish", "rid": rid, "state": h.state.value,
                "reason": (h.finish_reason.value
                           if h.finish_reason is not None else None),
                "ttft_s": h.ttft_s, "n_tokens": len(h.tokens)})
        return events

    def drain_entries(self, now_s: float) -> List[Tuple[int, Dict]]:
        """Every in-flight request as a rid-tagged drain wire entry,
        running-first FCFS order (the drain discipline: restore owes
        the oldest running stream the earliest re-admission)."""
        live = [(rid, h) for rid, h in self._handles.items() if not h.done]
        live.sort(key=lambda p: (p[1].state is not RequestState.RUNNING,
                                 p[1].arrival_s))
        return [(rid, drain_io.encode_handle(h, now_s)) for rid, h in live]

    def __len__(self) -> int:
        return len(self._handles)


class LocalReplica:
    """An in-process engine replica, stepped by the router.

    ``engine_factory()`` builds (and rebuilds, after a death) the
    :class:`~pddl_tpu.serve.ServeEngine`; keeping construction in a
    factory is what makes the circuit breaker's HALF_OPEN probe a real
    respawn instead of a pointless ping at a dead object.

    ``role`` is the replica's place in a disaggregated fleet
    (`fleet/disagg.py`): ``prefill``, ``decode``, or ``unified`` (the
    default — both phases, the pre-ISSUE-17 behavior). The role is
    router-side policy; the engine underneath is identical.
    """

    can_respawn = True

    def __init__(self, replica_id: int, engine_factory, *,
                 role: str = "unified"):
        self.replica_id = int(replica_id)
        self.role = validate_role(role)
        self._factory = engine_factory
        self.engine = engine_factory()
        self._ledger = HandleLedger()
        # Distributed tracing (ISSUE 19): finished engine spans are
        # pumped into this buffer (rid-tagged) for the router's
        # collector; inert unless the engine has an enabled tracer.
        self._span_buf = SpanShipper()
        self._trace_rids: Dict[int, int] = {}
        self._dtrace_armed = False
        # Highest fencing epoch seen (router HA, ISSUE 20). -1 =
        # never fenced; survives respawn() — the engine dies, the
        # single-writer promise does not.
        self.fence_epoch = -1

    # ------------------------------------------------------------ fencing
    def _check_epoch(self, epoch) -> None:
        """The worker-side fencing decision, in-object: a command
        carrying an epoch below the highest seen is refused with the
        typed reject; an equal-or-higher epoch is adopted. ``None``
        (an epoch-free caller, every pre-HA fleet) always passes."""
        if epoch is None:
            return
        if int(epoch) < self.fence_epoch:
            raise EpochFenced(self.replica_id, int(epoch),
                              self.fence_epoch)
        self.fence_epoch = int(epoch)

    def fence(self, epoch: int) -> int:
        """Adopt ``epoch`` as the floor for future commands (the
        promotion probe): returns the highest epoch now held. Raises
        :class:`EpochFenced` when the CALLER is the stale one."""
        self._check_epoch(int(epoch))
        return self.fence_epoch

    # ------------------------------------------------------------- intake
    def submit(self, rid: int, prompt, max_new_tokens: int,
               sampling: SamplingParams, deadline_s,
               priority: Priority = Priority.INTERACTIVE,
               adapter=None, constraint=None, trace=None,
               epoch=None) -> None:
        self._check_epoch(epoch)
        handle = self.engine.submit(prompt, max_new_tokens,
                                    sampling=sampling, deadline_s=deadline_s,
                                    priority=priority, adapter=adapter,
                                    constraint=constraint)
        self._ledger.add(rid, handle)
        self._apply_trace(rid, handle, trace)

    def _apply_trace(self, rid: int, handle, trace) -> None:
        tracer = self.engine.tracer
        if not tracer.enabled:
            return
        eng_rid = handle.request.request_id
        self._trace_rids[eng_rid] = int(rid)
        if trace is not None:
            tracer.on_trace_context(eng_rid, str(trace[0]), trace[1])

    def cancel(self, rid: int, epoch=None) -> None:
        self._check_epoch(epoch)
        h = self._ledger.get(rid)
        if h is not None:
            h.cancel()

    # ------------------------------------------------------------ serving
    def warmup(self) -> None:
        self.engine.warmup()

    def step(self) -> List[Dict[str, object]]:
        self.engine.step()
        self._pump_spans()
        return self._ledger.harvest()

    def _pump_spans(self) -> None:
        """Move finished engine spans (rid-tagged, replica-tagged) into
        the span buffer — destructive on the tracer's deque, so each
        record ships exactly once."""
        tracer = self.engine.tracer
        if not tracer.enabled:
            return
        finished = getattr(tracer, "finished", None)
        if not finished:
            return
        while True:
            try:
                rec = finished.popleft()
            except IndexError:
                break
            rec = dict(rec)
            rec["rid"] = self._trace_rids.pop(rec.get("request_id"), None)
            rec["replica"] = self.replica_id
            rec["role"] = self.role
            self._span_buf.add(rec)

    def take_span_records(self) -> List[Dict[str, object]]:
        """Span records since the last call (the router's collector
        drains this each step)."""
        self._pump_spans()
        return self._span_buf.drain(None)

    def flush_spans(self) -> None:
        """Death-path flush: cut every in-flight span short (the same
        ``drained`` discipline the engine's own drain applies) so the
        postmortem trace covers streams that never finished."""
        tracer = self.engine.tracer
        try:
            if tracer.enabled and tracer.active:
                tracer.on_drain(0, len(tracer.active))
        except Exception:  # noqa: BLE001 - the engine may be wedged
            pass
        self._pump_spans()

    def clock_offset(self) -> Optional[float]:
        """In-process replicas share the router's clock."""
        return 0.0

    @property
    def queue_depth(self) -> int:
        return self.engine.scheduler.depth

    @property
    def live_slots(self) -> int:
        return self.engine.live_slots

    @property
    def degraded(self) -> bool:
        """The engine's r08 OOM-degraded flag — the router's overload
        detector reads it as pressure (memory pressure IS overload)."""
        return self.engine.degraded

    def compile_counts(self) -> Dict[str, int]:
        return self.engine.compile_counts()

    # --------------------------------------------------------- resilience
    def drain_entries(self, now_s: float) -> List[Tuple[int, Dict]]:
        """Live-migration capture. The engine's own ``drain()`` is also
        invoked (idempotent) so admission stops and in-flight tracer
        spans flush; the rid-tagged entries come from the ledger —
        identical wire format, but keyed for the router.

        ``now_s`` (the ROUTER's clock) is ignored for encoding: each
        handle's ``arrival_s`` was stamped on the ENGINE's clock, and
        ``elapsed_s`` (the consumed deadline budget) only means
        anything as a same-epoch difference — a router driving a fake
        chaos clock over real-clock engines would otherwise snapshot a
        garbage budget."""
        del now_s
        entries = self._ledger.drain_entries(self.engine._clock())
        try:
            self.engine.drain()
        except Exception:  # noqa: BLE001 - the engine may be arbitrarily
            pass           # wedged post-kill; the entries above suffice
        return entries

    def restore(self, pairs: List[Tuple[int, Dict]],
                traces=None, epoch=None) -> None:
        """Migration in: wire entries join this engine's queue through
        the standard restore path (depth limits bypassed — every one of
        these was admitted by the fleet already). ``traces`` optionally
        maps rid -> wire trace context so the resumed streams' spans
        stay in their original fleet traces."""
        self._check_epoch(epoch)
        handles = self.engine.restore(snapshot_from_pairs(pairs))
        for (rid, _), handle in zip(pairs, handles):
            self._ledger.add(rid, handle)
            self._apply_trace(rid, handle,
                              None if traces is None else traces.get(rid))

    def take_pending(self) -> List[Dict[str, object]]:
        """Unharvested ledger events — a request can finish inside the
        very ``engine.step()`` a death unwound out of; harvesting here
        lets the router settle it instead of migrating a done stream."""
        return self._ledger.harvest()

    def export_chain(self, prompt: List[int],
                     max_blocks: Optional[int] = None, trace=None):
        """Replica-to-replica prefix transfer OUT (ISSUE 13): the
        engine's longest cached chain for ``prompt`` as a drain-module
        chain wire entry, or None. A ``trace`` context makes the
        transfer a span in the stream's fleet trace (ISSUE 19)."""
        t0 = time.monotonic()
        entry = self.engine.export_prefix_chain(prompt,
                                                max_blocks=max_blocks)
        if entry is not None and trace is not None \
                and self.engine.tracer.enabled:
            from pddl_tpu.obs.propagate import chain_export_span

            n_blocks = len(entry.get("blocks") or ())
            t1 = time.monotonic()
            self.engine.tracer.on_chain_export(n_blocks, t1 - t0)
            self._span_buf.add(chain_export_span(
                trace, t0, t1, n_blocks, replica=self.replica_id,
                role=self.role))
        return entry

    def import_chain(self, entry, trace=None) -> int:
        """Transfer IN: the chain lands in the engine's HOST tier;
        returns blocks stored (0 = tier off / refused)."""
        t0 = time.monotonic()
        n = self.engine.import_prefix_chain(entry)
        if n and trace is not None and self.engine.tracer.enabled:
            from pddl_tpu.obs.propagate import chain_import_span

            t1 = time.monotonic()
            self.engine.tracer.on_chain_import(n, t1 - t0)
            self._span_buf.add(chain_import_span(
                trace, t0, t1, n, replica=self.replica_id,
                role=self.role))
        return n

    def arm_tracing(self) -> None:
        """Arm a per-request tracer on the engine (idempotent): the
        router calls this when its dtrace collector is armed, so a
        LocalReplica fleet traces without per-test engine plumbing. A
        user-installed tracer is respected (never replaced)."""
        if not self.engine.tracer.enabled:
            from pddl_tpu.obs.trace import RequestTracer

            self.engine.set_tracer(RequestTracer())
        self._dtrace_armed = True

    def respawn(self) -> None:
        self.engine = self._factory()
        self._ledger = HandleLedger()
        self._trace_rids = {}
        if self._dtrace_armed:
            self._dtrace_armed = False
            self.arm_tracing()

    def close(self) -> None:
        pass


class ProcessReplica:
    """A worker process replica (`fleet/worker.py`) over a stdio pipe.

    The parent writes commands to the child's stdin and reads events
    from its stdout (non-blocking, buffered); pings answered with
    pongs are the heartbeat, and process exit / pipe EOF surfaces as
    :class:`ReplicaDied` from whatever call noticed first. ``kill()``
    (SIGKILL) is the un-drainable hard death the chaos/bench legs
    inject; ``terminate()`` (SIGTERM) lets the worker drain and ship
    its snapshot back, which the router can migrate losslessly.

    Since ISSUE 14 the pipe speaks the FRAMED protocol by default
    (`fleet/transport.py`): length-prefix + CRC32 + monotone sequence
    per direction, duplicate suppression, gap detection with bounded
    resend requests, and a max-frame guard — the wire is untrusted,
    and ``wire_fault_plan`` makes its failure modes injectable
    (corrupt/truncate/duplicate/reorder/delay/drop at seeded frame
    coordinates, applied on this side of the pipe in BOTH directions
    so one seeded plan governs the whole link). ``transport="lines"``
    keeps the r11 raw JSON-line protocol for A/B comparison.
    """

    can_respawn = True

    def __init__(self, replica_id: int, worker_config: Dict[str, object], *,
                 role: str = "unified",
                 python: str = sys.executable, ready_timeout_s: float = 300.0,
                 ping_interval_s: float = 0.25, drain_timeout_s: float = 10.0,
                 call_timeout_s: float = 30.0, transport: str = "framed",
                 wire_fault_plan=None,
                 max_frame_bytes: int = MAX_FRAME_BYTES,
                 resend_timeout_s: float = 0.25,
                 max_resend_requests: int = 16,
                 clock=time.monotonic, stderr=None, wait_ready: bool = True):
        if transport not in ("framed", "lines"):
            raise ValueError(
                f"transport must be 'framed' or 'lines', got "
                f"{transport!r}")
        self.replica_id = int(replica_id)
        self._framed = transport == "framed"
        self._config = dict(worker_config)
        self._config["framed"] = self._framed
        # Disaggregation role (`fleet/disagg.py`): an explicit
        # worker_config value wins over the kwarg, and the worker
        # validates it again on its side of the pipe (vocabulary
        # parity, graftlint `role-vocab`).
        self._config["role"] = validate_role(
            self._config.get("role", role))
        self.role = self._config["role"]
        # Both pipe ends must enforce the SAME cap (an explicit
        # worker_config value wins — the asymmetric-cap chaos tests
        # use that): a worker with a larger cap would emit snapshot/
        # chain frames this side terminally refuses.
        self._config.setdefault("max_frame_bytes", int(max_frame_bytes))
        self._plan = wire_fault_plan
        self._max_frame = int(max_frame_bytes)
        self._resend_timeout_s = float(resend_timeout_s)
        self._max_resend_requests = int(max_resend_requests)
        self._python = python
        self._ready_timeout_s = float(ready_timeout_s)
        self._ping_interval_s = float(ping_interval_s)
        self._drain_timeout_s = float(drain_timeout_s)
        self._call_timeout_s = float(call_timeout_s)
        self._clock = clock
        self._stderr = stderr
        self._spawn(wait_ready=wait_ready)

    # ------------------------------------------------------- process mgmt
    def _worker_argv(self) -> List[str]:
        """The child command line — a seam, so tests can stand in a
        process that never acks ready (the spawn-timeout contract)
        without re-implementing the spawn bookkeeping."""
        return [self._python, "-m", "pddl_tpu.serve.fleet.worker",
                "--config-json", json.dumps(self._config)]

    def _spawn(self, wait_ready: bool = True) -> None:
        # The worker must import pddl_tpu from wherever THIS process
        # found it — which may be a sys.path entry the child would not
        # inherit (PYTHONPATH is appended to, never overwritten: other
        # entries, e.g. platform-plugin site dirs, must survive).
        import pddl_tpu  # noqa: PLC0415

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(pddl_tpu.__file__)))
        env = dict(os.environ)
        parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
        if pkg_root not in parts:
            env["PYTHONPATH"] = os.pathsep.join([pkg_root] + parts)
        self._proc = subprocess.Popen(
            self._worker_argv(),
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._stderr, text=False, env=env)
        os.set_blocking(self._proc.stdout.fileno(), False)
        self._spawn_started_s = self._clock()
        self._buf = b""
        self._pending: List[Dict[str, object]] = []
        self._unanswered_ping_s: Optional[float] = None
        self._last_ping_s = 0.0
        self._degraded = False
        # Framed-transport state, fresh per process: per-direction
        # sender/receiver, the ingress frame counter (the fault plan's
        # deterministic step coordinate on the "ev" site), and the
        # bounded resend-request machinery.
        self._sender = FrameSender()
        self._receiver = FrameReceiver(max_frame_bytes=self._max_frame)
        self._ev_frame_no = 0
        self._oversize_dropping = False
        self._resend_attempts = 0
        self._next_resend_at = 0.0
        self._wire_retries = 0
        self._tick_walls: List[float] = []
        # Distributed tracing (ISSUE 19), fresh per process: span
        # records shipped back over the pipe, and the min-RTT clock
        # aligner fed by ping-echo timestamps on pongs.
        self._span_records: List[Dict[str, object]] = []
        self._spans_dropped = 0
        self._aligner = ClockAligner()
        self.ready_compile_counts: Optional[Dict[str, int]] = None
        if wait_ready:
            self.wait_ready()

    def wire_stats(self) -> Dict[str, int]:
        """Transport counters for the router's FleetMetrics fold (and
        the bench's zero-corrupt-frames-accepted referee): resend
        rounds requested, frames the CRC/length check refused, dups
        dropped, gaps seen, typed oversize rejects."""
        s = self._receiver.stats
        return {"retries": self._wire_retries,
                "crc_rejects": s["crc_rejects"],
                "dups": s["dups"], "gaps": s["gaps"],
                "too_large": s["too_large"],
                "frames_ok": s["frames_ok"],
                "frames_sent": self._sender.frames_sent,
                "frames_resent": self._sender.frames_resent}

    def wait_ready(self, timeout_s: Optional[float] = None) -> None:
        """Block until the worker's ``ready`` ack (engine built and
        warmed). Split from :meth:`_spawn` so a fleet can launch every
        worker first (``wait_ready=False``) and pay the N warmup
        compiles concurrently instead of serially.

        ``timeout_s`` overrides the constructor's ``ready_timeout_s``
        for THIS wait; either budget expiring kills the wedged worker
        and raises the typed :class:`ReplicaSpawnTimeout`, so a caller
        holding a control loop (the autoscaler's scale-up path) fails
        the attempt fast instead of blocking serving behind it."""
        budget = (self._ready_timeout_s if timeout_s is None
                  else float(timeout_s))
        deadline = self._clock() + budget
        while self.ready_compile_counts is None:
            for ev in self._read_events(block_s=0.1):
                if ev.get("ev") == "ready":
                    self.ready_compile_counts = ev.get("compile_counts")
                else:
                    self._pending.append(ev)
            if self._proc.poll() is not None:
                raise ReplicaDied(self.replica_id,
                                  f"worker exited rc={self._proc.returncode} "
                                  "before ready")
            if self._clock() > deadline:
                self._proc.kill()
                raise ReplicaSpawnTimeout(
                    self.replica_id, self._clock() - self._spawn_started_s)

    def poll_ready(self) -> bool:
        """Non-blocking readiness probe for concurrent warm-starts: the
        autoscaler spawns with ``wait_ready=False`` and polls this once
        per control tick, so a scale-up compiles in the background while
        the fleet keeps serving. Returns True once the ``ready`` ack has
        arrived; raises :class:`ReplicaDied` if the worker exited first
        and :class:`ReplicaSpawnTimeout` once ``ready_timeout_s`` has
        elapsed since the spawn (the worker is killed — a wedged spawn
        must not leak a zombie process)."""
        if self.ready_compile_counts is not None:
            return True
        for ev in self._read_events():
            if ev.get("ev") == "ready":
                self.ready_compile_counts = ev.get("compile_counts")
            else:
                self._pending.append(ev)
        if self.ready_compile_counts is not None:
            return True
        if self._proc.poll() is not None:
            raise ReplicaDied(self.replica_id,
                              f"worker exited rc={self._proc.returncode} "
                              "before ready")
        waited = self._clock() - self._spawn_started_s
        if waited > self._ready_timeout_s:
            self._proc.kill()
            raise ReplicaSpawnTimeout(self.replica_id, waited)
        return False

    def _send(self, cmd: Dict[str, object]) -> None:
        if self._proc.poll() is not None:
            raise ReplicaDied(self.replica_id,
                              f"worker exited rc={self._proc.returncode}")
        if self._framed:
            frame = self._sender.encode(
                json.dumps(cmd, separators=(",", ":")).encode())
            lines = ([frame] if self._plan is None else
                     self._plan.apply("cmd", self._sender.last_seq,
                                      frame))
        else:
            lines = [(json.dumps(cmd) + "\n").encode()]
        try:
            for line in lines:
                self._proc.stdin.write(line)
            self._proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            raise ReplicaDied(self.replica_id, f"pipe write failed: {e}") \
                from e

    def _write_raw(self, frames: List[bytes]) -> None:
        """Resent frames go out verbatim — the chaos already fired at
        their seq coordinates once; recovery must terminate."""
        try:
            for frame in frames:
                self._proc.stdin.write(frame)
            if frames:
                self._proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            raise ReplicaDied(self.replica_id, f"pipe write failed: {e}") \
                from e

    def _consume_line(self, line: bytes,
                      out: List[Dict[str, object]]) -> None:
        """One raw stdout line -> zero or more in-order events (framed
        mode runs the fault plan's ingress mangling, then the receiver;
        transport-control events are handled here, not surfaced)."""
        if not line.strip():
            return
        if not self._framed:
            if len(line) > self._max_frame:
                # Typed oversize reject (the unbounded single-line
                # read fix): drop the line, count it, never crash.
                self._receiver.stats["too_large"] += 1
                return
            self._absorb(json.loads(line), out)
            return
        ctl = decode_control(line)
        if ctl is not None:
            # Out-of-band control (never sequenced — a resend request
            # ordered behind the gap it reports would deadlock): the
            # worker lost command frames, replay them verbatim.
            if ctl.get("ctl") == "resend":
                self._wire_retries += 1
                self._write_raw(self._sender.resend_from(
                    int(ctl.get("from", 1))))
            return
        self._ev_frame_no += 1
        mangled = ([line + b"\n"] if self._plan is None else
                   self._plan.apply("ev", self._ev_frame_no,
                                    line + b"\n"))
        for raw in mangled:
            for payload in self._receiver.feed(raw.rstrip(b"\n")):
                self._absorb(json.loads(payload), out)

    def _absorb(self, ev: Dict[str, object],
                out: List[Dict[str, object]]) -> None:
        """Span batches are transport-level (ISSUE 19): fold them into
        the span buffer at the single ingestion point — whatever wait
        loop happened to read them — instead of surfacing an event the
        router's apply path would have to know to ignore."""
        if ev.get("ev") == "spans":
            self._span_records.extend(ev.get("spans") or [])
            if ev.get("dropped") is not None:
                self._spans_dropped = max(self._spans_dropped,
                                          int(ev["dropped"]))
            return
        out.append(ev)

    def _nudge(self) -> None:
        """Traffic generator for framed wait loops: a ping at the
        heartbeat cadence forces the worker to emit, so a corrupted or
        dropped REPLY surfaces as a sequence gap the resend machinery
        can heal — an idle pipe cannot tell "nothing sent" from
        "everything lost"."""
        if not self._framed:
            return
        now = self._clock()
        if now - self._last_ping_s >= self._ping_interval_s:
            self._last_ping_s = now
            # t_s echoes back on the pong with the worker's own
            # monotonic read: one clock-offset sample per heartbeat.
            self._send({"cmd": "ping", "t_s": now})
            if self._unanswered_ping_s is None:
                self._unanswered_ping_s = now

    def _maybe_request_resend(self) -> None:
        """Gap recovery, bounded: ask the worker to resend from the
        first missing event seq, with timeout backoff between asks;
        past the budget the wire is declared unrecoverable and the
        replica dies its typed death (the router migrates)."""
        if not self._framed:
            return
        if not self._receiver.has_gap:
            # Healed: BOTH the attempt budget and the backoff anchor
            # reset — a later, unrelated gap must get its first
            # request immediately, not inherit this one's backoff.
            self._resend_attempts = 0
            self._next_resend_at = 0.0
            return
        gap_from = self._receiver.expected_seq
        now = self._clock()
        if now < self._next_resend_at:
            return
        if self._resend_attempts >= self._max_resend_requests:
            raise ReplicaDied(
                self.replica_id,
                f"wire unrecoverable: event seq {gap_from} still "
                f"missing after {self._resend_attempts} resend "
                "requests")
        self._resend_attempts += 1
        self._wire_retries += 1
        self._next_resend_at = now + self._resend_timeout_s * min(
            8, 2 ** (self._resend_attempts - 1))
        # Out-of-band: a framed request would order BEHIND the very
        # gap it reports (mutual deadlock when both directions have
        # one) — control lines are sequence-free and idempotent.
        self._write_raw([encode_control(
            {"ctl": "resend", "from": int(gap_from)})])

    def _read_events(self, block_s: float = 0.0) -> List[Dict[str, object]]:
        """Drain available stdout lines (optionally waiting up to
        ``block_s`` for the first byte). EOF raises ReplicaDied."""
        out: List[Dict[str, object]] = []
        deadline = self._clock() + block_s
        while True:
            try:
                chunk = self._proc.stdout.read()
            except (BlockingIOError, OSError):
                chunk = None
            if chunk:
                self._buf += chunk
                # Max-frame guard on the LINE BUFFER itself: a payload
                # that never newline-terminates must not balloon the
                # parent's memory — discard through the next newline
                # and count the typed reject. 4x headroom so a
                # complete oversized FRAME still reaches the
                # receiver's skip path (which consumes its seq slot);
                # only unbounded garbage lands here.
                if self._oversize_dropping or (
                        b"\n" not in self._buf
                        and len(self._buf) > 4 * self._max_frame):
                    if b"\n" in self._buf:
                        _, self._buf = self._buf.split(b"\n", 1)
                        if self._oversize_dropping:
                            self._receiver.stats["too_large"] += 1
                        self._oversize_dropping = False
                    else:
                        self._buf = b""
                        self._oversize_dropping = True
                while b"\n" in self._buf:
                    line, self._buf = self._buf.split(b"\n", 1)
                    self._consume_line(line, out)
                if out:
                    # ANY event is a liveness proof — not just pongs —
                    # so whatever ping was outstanding is answered.
                    self._unanswered_ping_s = None
                    # Gap recovery must not wait for an idle read:
                    # under heavy token flow every pass returns early
                    # here, and deferring the resend request to a
                    # quiet moment turns a 1 ms heal into a whole
                    # engine-tick stall per fault.
                    self._maybe_request_resend()
                    return out
            elif chunk == b"":  # EOF: the worker is gone
                if self._proc.poll() is None:
                    self._proc.wait(timeout=5)
                raise ReplicaDied(
                    self.replica_id,
                    f"stdout EOF (rc={self._proc.returncode})")
            self._maybe_request_resend()
            if self._clock() >= deadline:
                return out
            time.sleep(0.002)

    # ------------------------------------------------------------- intake
    def submit(self, rid: int, prompt, max_new_tokens: int,
               sampling: SamplingParams, deadline_s,
               priority: Priority = Priority.INTERACTIVE,
               adapter=None, constraint=None, trace=None,
               epoch=None) -> None:
        """Synchronous across the pipe: the worker acks admission or
        reports its typed QueueFull (depth + retry_after hint), which
        re-raises here so the router's shed logic is driver-agnostic.
        ``adapter``/``constraint`` (the tenant fields) are already
        plain wire values — a name string and a spec dict; ``trace``
        is the router's ``(trace_id, parent_span_id)`` wire context
        (ISSUE 19), stamped only when fleet tracing is armed;
        ``epoch`` is the issuing router's fencing epoch (ISSUE 20) —
        a stale one re-raises the worker's typed reject as
        :class:`EpochFenced`."""
        cmd = {"cmd": "submit", "rid": int(rid),
               "prompt": [int(t) for t in prompt],
               "max_new_tokens": int(max_new_tokens),
               "sampling": sampling_to_wire(sampling),
               "deadline_s": deadline_s,
               "priority": Priority(priority).value,
               "adapter": adapter, "constraint": constraint}
        if trace is not None:
            cmd["trace"] = [str(trace[0]), trace[1]]
        if epoch is not None:
            cmd["epoch"] = int(epoch)
        self._send(cmd)
        deadline = self._clock() + self._call_timeout_s
        while True:
            # Consume the WHOLE batch before acting on the ack: token
            # events can share a read with it, and an early return would
            # silently drop them (a lost token = a corrupted replay
            # mirror = a non-token-exact migration later).
            self._nudge()
            verdict = None
            for ev in self._read_events(block_s=0.05):
                kind = ev.get("ev")
                if kind == "submit_ok" and ev.get("rid") == rid:
                    verdict = "ok"
                elif kind == "queue_full" and ev.get("rid") == rid:
                    verdict = QueueFull(int(ev["queue_depth"]),
                                        int(ev["max_queue_depth"]),
                                        retry_after_s=ev.get("retry_after_s"),
                                        priority=Priority(priority))
                elif kind == "error" and ev.get("rid") == rid:
                    verdict = ValueError(str(ev.get("message")))
                elif kind == "fenced" and ev.get("rid") == rid:
                    verdict = EpochFenced(self.replica_id,
                                          int(ev.get("epoch", -1)),
                                          int(ev.get("highest", -1)))
                else:
                    self._pending.append(ev)
            if verdict == "ok":
                return
            if verdict is not None:
                raise verdict
            if self._clock() > deadline:
                raise ReplicaDied(self.replica_id, "submit ack timed out")

    def cancel(self, rid: int, epoch=None) -> None:
        cmd = {"cmd": "cancel", "rid": int(rid)}
        if epoch is not None:
            cmd["epoch"] = int(epoch)
        self._send(cmd)

    def fence(self, epoch: int) -> int:
        """Adopt ``epoch`` on the worker (the promotion probe):
        synchronous like :meth:`compile_counts` — the promoting router
        must KNOW every worker holds the new epoch before the deposed
        primary's next command can race it. Returns the worker's
        highest epoch; raises :class:`EpochFenced` when the caller's
        epoch is the stale one."""
        self._send({"cmd": "fence", "epoch": int(epoch)})
        deadline = self._clock() + self._call_timeout_s
        while self._clock() < deadline:
            self._nudge()
            verdict = None  # consume the whole batch (see submit())
            for ev in self._read_events(block_s=0.05):
                kind = ev.get("ev")
                if kind == "fence_ok" and verdict is None:
                    verdict = int(ev.get("highest", epoch))
                elif kind == "fenced" and ev.get("rid") is None \
                        and verdict is None:
                    verdict = EpochFenced(self.replica_id,
                                          int(ev.get("epoch", -1)),
                                          int(ev.get("highest", -1)))
                else:
                    self._pending.append(ev)
            if isinstance(verdict, EpochFenced):
                raise verdict
            if verdict is not None:
                return verdict
        raise ReplicaDied(self.replica_id, "fence ack timed out")

    # ------------------------------------------------------------ serving
    def warmup(self) -> None:
        pass  # ready implies warmed: the worker compiles before its ack

    def step(self) -> List[Dict[str, object]]:
        """Pump events; the worker self-drives its engine loop. Sends a
        ping at ``ping_interval_s`` cadence — pongs are the heartbeat
        the router's staleness check reads via :meth:`beat_age_s`."""
        now = self._clock()
        if now - self._last_ping_s >= self._ping_interval_s:
            self._last_ping_s = now
            self._send({"cmd": "ping", "t_s": now})
            if self._unanswered_ping_s is None:
                self._unanswered_ping_s = now
        events, self._pending = self._pending, []
        events.extend(self._read_events())
        out = []
        for ev in events:
            if ev.get("ev") == "pong":
                # Pongs double as the degraded gauge's transport: the
                # router's overload detector reads it off `degraded`.
                self._degraded = bool(ev.get("degraded", False))
                # ...and as the gray detector's: the worker's
                # self-reported engine-tick wall (the parent's pump
                # wall cannot see a slow self-driving worker).
                if ev.get("tick_wall_s") is not None:
                    self._tick_walls.append(float(ev["tick_wall_s"]))
                # ...and as the clock aligner's: the echoed ping send
                # time plus the worker's monotonic read is one NTP
                # sample. A pong that sat buffered through a blocked
                # call reads as a huge RTT, which the min-RTT filter
                # discards on its own.
                if (ev.get("echo_t_s") is not None
                        and ev.get("mono_s") is not None):
                    self._aligner.observe(float(ev["echo_t_s"]),
                                          self._clock(),
                                          float(ev["mono_s"]))
            else:
                out.append(ev)
        return out

    def take_latency_samples(self) -> List[float]:
        """Per-tick latency samples since the last call (worker
        self-reported engine-step walls, carried on pongs) — the gray
        detector's input for process replicas."""
        out, self._tick_walls = self._tick_walls, []
        return out

    def take_span_records(self) -> List[Dict[str, object]]:
        """Worker span records absorbed from the pipe since the last
        call (the router's collector drains this each step)."""
        out, self._span_records = self._span_records, []
        return out

    @property
    def spans_dropped(self) -> int:
        """The worker shipper's cumulative overflow counter, as last
        reported."""
        return self._spans_dropped

    def clock_offset(self) -> Optional[float]:
        """Best current estimate of (worker monotonic - router
        monotonic), from the minimal-RTT ping/pong sample; None until
        the first heartbeat answers."""
        return self._aligner.offset_s

    @property
    def flightrec_dir(self) -> Optional[str]:
        """Where this worker's flight recorder writes (config-armed);
        the router harvests it on death."""
        val = self._config.get("flightrec_dir")
        return None if val is None else str(val)

    def set_tick_delay(self, delay_s: float) -> None:
        """Chaos knob: make THIS worker gray — every engine step gains
        ``delay_s`` of wall time from here on (the process-replica
        analogue of a LATENCY fault plan on every device call)."""
        self._send({"cmd": "set_tick_delay", "delay_s": float(delay_s)})

    @property
    def degraded(self) -> bool:
        """Last pong's engine-degraded flag (r08 OOM machinery)."""
        return self._degraded

    def beat_age_s(self) -> float:
        """Age of the OLDEST unanswered ping; 0.0 when none is
        outstanding. Anchored to when a ping was actually SENT, never
        to the last read — a router that idles between bursts must not
        read its own quiet gap as replica silence and breaker-kill a
        healthy worker on the first steps after waking. Buffered
        events are drained (non-blocking) before judging: a pong that
        arrived while the router was blocked elsewhere (e.g. a bounded
        10 s drain capture of a wedged sibling) counts as answered."""
        if self._unanswered_ping_s is not None:
            try:
                self._pending.extend(self._read_events())
            except ReplicaDied:
                pass  # a real death surfaces from the next step()/send
        if self._unanswered_ping_s is None:
            return 0.0
        return self._clock() - self._unanswered_ping_s

    def compile_counts(self) -> Dict[str, int]:
        """Counts as of the last ``counts``/snapshot report (the ready
        ack at minimum)."""
        self._send({"cmd": "counts"})
        deadline = self._clock() + self._call_timeout_s
        while self._clock() < deadline:
            self._nudge()
            counts = None  # consume the whole batch (see submit())
            for ev in self._read_events(block_s=0.05):
                if ev.get("ev") == "counts" and counts is None:
                    counts = dict(ev["counts"])
                else:
                    self._pending.append(ev)
            if counts is not None:
                return counts
        raise ReplicaDied(self.replica_id, "counts request timed out")

    def export_chain(self, prompt: List[int],
                     max_blocks: Optional[int] = None, trace=None):
        """Replica-to-replica prefix transfer OUT, over the pipe:
        synchronous like :meth:`compile_counts` (the router is about to
        route based on the answer), bounded by ``call_timeout_s``.
        Returns the chain wire entry or None."""
        cmd = {"cmd": "export_chain",
               "prompt": [int(t) for t in prompt],
               "max_blocks": (int(max_blocks)
                              if max_blocks is not None else None)}
        if trace is not None:
            cmd["trace"] = [str(trace[0]), trace[1]]
        self._send(cmd)
        deadline = self._clock() + self._call_timeout_s
        while self._clock() < deadline:
            self._nudge()
            entry = missing = object()
            for ev in self._read_events(block_s=0.05):
                if ev.get("ev") == "chain" and entry is missing:
                    entry = ev.get("entry")
                else:
                    self._pending.append(ev)
            if entry is not missing:
                return entry
        raise ReplicaDied(self.replica_id, "export_chain timed out")

    def import_chain(self, entry, trace=None) -> int:
        """Transfer IN, over the pipe: the worker stores the chain in
        its engine's host tier and acks with the stored-block count."""
        cmd = {"cmd": "import_chain", "entry": entry}
        if trace is not None:
            cmd["trace"] = [str(trace[0]), trace[1]]
        self._send(cmd)
        deadline = self._clock() + self._call_timeout_s
        while self._clock() < deadline:
            self._nudge()
            n = None
            for ev in self._read_events(block_s=0.05):
                if ev.get("ev") == "chain_imported" and n is None:
                    n = int(ev.get("n", 0))
                else:
                    self._pending.append(ev)
            if n is not None:
                return n
        raise ReplicaDied(self.replica_id, "import_chain timed out")

    # --------------------------------------------------------- resilience
    def drain_entries(self, now_s: float) -> List[Tuple[int, Dict]]:
        """Graceful capture: SIGTERM the worker, read back its
        rid-tagged snapshot (the worker's drain handler writes it as
        its last event). A hard-killed worker raises instead — the
        router falls back to its own mirrors. The wait is bounded by
        ``drain_timeout_s``: the router's event loop blocks here, so a
        WEDGED worker must degrade to the replay fallback quickly
        rather than stall every surviving replica's stream for long."""
        if self._proc.poll() is not None:
            raise ReplicaDied(self.replica_id,
                              f"worker already dead rc={self._proc.returncode}")
        try:
            self._proc.send_signal(signal.SIGTERM)
        except OSError as e:
            raise ReplicaDied(self.replica_id, f"SIGTERM failed: {e}") from e
        deadline = self._clock() + self._drain_timeout_s
        snapshot = None
        while snapshot is None and self._clock() < deadline:
            try:
                events = self._read_events(block_s=0.1)
            except ReplicaDied:
                break  # EOF before the snapshot line made it out
            for ev in events:
                if ev.get("ev") == "snapshot":
                    snapshot = ev
                else:
                    # Backlog sharing the pipe with the snapshot —
                    # finish/token events for requests that settled just
                    # before the SIGTERM. Dropping them would leave their
                    # fleet handles unsettled forever; the router applies
                    # them via take_pending() after the capture.
                    self._pending.append(ev)
        if snapshot is None:
            if self._proc.poll() is None:  # wedged past the bound: put
                self._proc.kill()          # it down, replay-migrate
            raise ReplicaDied(self.replica_id,
                              "no drain snapshot before EOF")
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self._proc.kill()
        return [(int(rid), entry) for rid, entry in snapshot["requests"]]

    def take_pending(self) -> List[Dict[str, object]]:
        """Hand any buffered backlog events to the caller (the router
        applies these after a drain capture so same-pipe finish/token
        events are not lost with the replica). Drains the OS pipe
        buffer first, best-effort: a SIGKILL'd worker's stdout stays
        readable until EOF, and finish/token events it wrote before
        dying must settle their handles rather than force a pointless
        replay-migration of an already-complete stream."""
        try:
            while True:
                got = self._read_events()
                if not got:
                    break
                self._pending.extend(got)
        except ReplicaDied:
            pass  # EOF: everything readable was parsed above
        events, self._pending = self._pending, []
        return events

    _RESTORE_CHUNK = 8  # entries per restore command

    def restore(self, pairs: List[Tuple[int, Dict]],
                traces=None, epoch=None) -> None:
        """Migration in, chunked: one huge restore line can exceed the
        stdin pipe capacity while the worker is itself blocked writing
        token events nobody is reading — a mutual stall. Small commands
        with a non-blocking stdout drain between them keep both pipe
        directions moving; the worker treats each chunk as an
        independent restore. ``traces`` optionally maps rid -> wire
        trace context (ISSUE 19); ``epoch`` is the issuing router's
        fencing epoch (ISSUE 20) — a stale restore is refused whole
        (the typed reject surfaces through the event stream)."""
        for i in range(0, len(pairs), self._RESTORE_CHUNK):
            chunk = pairs[i:i + self._RESTORE_CHUNK]
            cmd = {"cmd": "restore",
                   "requests": [[int(rid), entry]
                                for rid, entry in chunk]}
            if traces:
                stamped = [[int(rid), [str(traces[rid][0]),
                                       traces[rid][1]]]
                           for rid, _ in chunk if rid in traces]
                if stamped:
                    cmd["traces"] = stamped
            if epoch is not None:
                cmd["epoch"] = int(epoch)
            self._send(cmd)
            self._pending.extend(self._read_events())

    def respawn(self) -> None:
        if self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()
        self._spawn()

    # ------------------------------------------------------- fault inject
    def kill(self) -> None:
        """SIGKILL — the un-drainable death (bench/chaos legs)."""
        self._proc.kill()

    def terminate(self) -> None:
        self._proc.send_signal(signal.SIGTERM)

    def close(self) -> None:
        if self._proc.poll() is None:
            try:
                self._send({"cmd": "shutdown"})
                self._proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 - best-effort shutdown
                self._proc.kill()
                self._proc.wait()
