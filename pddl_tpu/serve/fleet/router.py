"""The fleet router: N replicas behind one health-checked front door.

One :class:`~pddl_tpu.serve.ServeEngine` multiplexes one chip; the
ROADMAP's "millions of users" need a fleet — and a fleet's defining
property is that any replica can die at any moment. DistServe (Zhong et
al., 2024) and Splitwise (Patel et al., 2024) draw the architectural
conclusion this module implements: replicas are disposable ROLES behind
a router, never pets. Three router duties:

**Routing.** Prefix-affinity first: the router keeps a host-side SHADOW
of each replica's radix cache (the same
:class:`~pddl_tpu.serve.kvcache.RadixPrefixCache` match machinery,
holding token chains but no device blocks) and sends a prompt to the
healthy replica whose cache already holds its longest leading-block
chain — shared system prompts land where their KV lives, which is what
makes per-replica prefix caches pay at fleet scale. Sticky sessions
(``session=``) keep multi-turn conversations on one replica for the
same reason. With host-tier replicas (ISSUE 13) the shadow models the
SECOND tier too — its own LRU eviction demotes chains into a host
shadow, so affinity can route to "has it in host RAM" when no replica
has it in HBM — and ``chain_pull_blocks`` arms replica-to-replica
prefix transfer: a cold-routed request whose prefix a sibling holds
gets the chain PULLED into the target's host tier over the drain-module
chain wire format, eliminating the duplicate prefill fleet-wide. Cold
prompts route by RENDEZVOUS HASH of the leading blocks over the healthy
set, so one replica's death remaps only its own keys. A full replica (typed
:class:`~pddl_tpu.serve.request.QueueFull`) sheds to the least-loaded
healthy replica, carrying the ``retry_after_s`` hint forward; only a
fleet-wide full queue rejects the caller.

**Disaggregation (ISSUE 17).** When the fleet holds strict ``prefill``
and ``decode`` role replicas (`fleet/disagg.py`), non-sticky
admissions route to the prefill pool (label ``prefill``) and each
stream hands off to a decode replica at first token — the finished KV
chain ships over the r18 chain wire into the target's host tier, the
rebinding journals as a ``handoff`` record, and decode replicas never
pay a long prompt's prefill. An all-unified fleet (the default role)
routes exactly as above.

**Health.** Per-replica circuit breaker (`fleet/health.py`):
consecutive failures or heartbeat silence trip CLOSED→OPEN, a bounded
exponential backoff gates HALF_OPEN probes, and a successful probe (a
respawn — fresh engine / fresh worker process) closes the circuit and
returns the replica to rotation.

**Failover with live migration.** When a replica dies, the router
captures its drain snapshot — `serve/drain.py` is already the wire
format — and ``restore()``s the in-flight streams on survivors, where
the engine's replay admission rebuilds each KV token-exactly: a request
that STARTED on the dead replica FINISHES with the identical token
sequence. An un-drainable hard kill (SIGKILL, no snapshot possible)
falls back to the router's own prompt+emitted-token mirrors — exactly
r08's in-engine replay contract, held at fleet level. Requests with no
surviving replica park as orphans and re-enter when a probe brings a
replica back; they fail terminally only when recovery is impossible.

**Durability & gray failure (ISSUE 14).** The router itself is no
longer assumed immortal: with a :class:`~.journal.RouterJournal`
attached, every admission, routing/ledger binding, emitted-token
mirror delta, and finish is write-ahead logged (admits fsynced before
the caller's handle returns; token deltas fsync-batched — losing them
is safe, replay regenerates), with an atomic checkpoint+truncate
cycle riding the drain-snapshot encoder. :meth:`FleetRouter.recover`
rebuilds a fresh router + fresh replicas after a SIGKILL and resumes
every in-flight stream token-exactly through the same r11
mirror-replay contract failover uses. And between dead and alive sits
DEGRADED: a :class:`~.health.GrayDetector` watches per-replica
per-tick latency quantiles, interactive submissions to a
suspected-gray replica are HEDGED to the least-loaded healthy sibling
(first result wins, the loser is cancelled), and — with
``gray_drain`` on — the suspect is proactively retired through the
r16 ``scale_down`` live-migration path before it hard-fails.

Every fleet event (replica_up/down, circuit transitions, migrations,
sheds, hedges, gray drains) flows through the `obs/` tracer
(``on_fleet_event``) and the Prometheus exporter
(:func:`pddl_tpu.obs.export.fleet_exposition`).
"""

from __future__ import annotations

import collections
import enum
import hashlib
import time
from typing import Dict, List, Optional, Sequence, Tuple

from pddl_tpu.obs import flightrec as flightrec_io
from pddl_tpu.obs.propagate import TraceCollector
from pddl_tpu.obs.trace import NULL_TRACER
from pddl_tpu.serve import drain as drain_io
from pddl_tpu.serve.fleet import journal as journal_io
from pddl_tpu.serve.fleet.admission import AdmissionControl
from pddl_tpu.serve.fleet.disagg import HandoffManager, role_of
from pddl_tpu.serve.fleet.health import (
    BreakerState,
    CircuitBreaker,
    GrayDetector,
)
from pddl_tpu.serve.fleet.replica import EpochFenced, ReplicaDied
from pddl_tpu.serve.kvcache import RadixPrefixCache
from pddl_tpu.serve.request import (
    AdmissionRejected,
    FinishReason,
    Priority,
    QueueFull,
    Request,
    RequestState,
    SamplingParams,
)
from pddl_tpu.utils.faults import KillPoint

# Machine-checked route-label vocabulary (graftlint `role-vocab`):
# every label `_route`/`submit` can stamp on a routing decision. The
# journal's `VIA_LABELS` manifest must cover all of them (plus its own
# ledger-only labels, `migration`/`hedge`) — a label minted here that
# the WAL reader cannot classify is a lint error, not a runtime
# surprise.
ROUTE_LABELS = ("sticky", "adapter", "affinity", "load", "host_tier",
                "hash", "shed", "prefill")


class NoHealthyReplica(RuntimeError):
    """Every replica's circuit is open (or dead): the fleet cannot take
    this request right now. The HTTP-503 analogue — distinct from
    :class:`~pddl_tpu.serve.request.QueueFull` (healthy but saturated)
    so upstream can tell "back off briefly" from "page someone"."""


class ReplicaLifecycle(enum.Enum):
    UP = "up"
    DEAD = "dead"
    # Retired by an elastic scale-down: the replica's in-flight work was
    # LIVE-MIGRATED onto survivors (drain snapshot first, router mirrors
    # as the fallback) and the slot left the rotation for good — unlike
    # DEAD, nothing probes it back.
    RETIRED = "retired"


class FleetHandle:
    """The caller's stream handle at fleet level.

    Mirrors the :class:`~pddl_tpu.serve.request.RequestHandle` surface
    (``tokens``/``state``/``finish_reason``/``done``/``ttft_s``/
    ``cancel()``) but is owned by the ROUTER: ``tokens`` is the
    canonical append-only stream the caller reads, fed from whichever
    replica currently runs the request — across any number of
    migrations, which ``migrations`` counts. It doubles as the replay
    mirror: ``prompt + tokens`` is sufficient to rebuild the stream on
    a survivor when a replica hard-dies, so it duck-types the
    `serve/drain.py` encoder's handle surface."""

    def __init__(self, request: Request, arrival_s: float,
                 session: Optional[str] = None):
        self.request = request
        self.arrival_s = arrival_s
        self.session = session
        self.tokens: List[int] = []
        self.state = RequestState.QUEUED
        self.finish_reason: Optional[FinishReason] = None
        self.ttft_s: Optional[float] = None
        self.finish_s: Optional[float] = None
        self.replica_id: Optional[int] = None
        self.migrations = 0
        self._cancel = False
        self._orphan_counted = False

    def cancel(self) -> None:
        self._cancel = True

    @property
    def cancelled(self) -> bool:
        return self._cancel

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.CANCELLED,
                              RequestState.TIMED_OUT, RequestState.FAILED)

    def __repr__(self) -> str:  # debugging aid, not an API
        return (f"FleetHandle(id={self.request.request_id}, "
                f"replica={self.replica_id}, state={self.state.value}, "
                f"tokens={len(self.tokens)}, migrations={self.migrations})")


class FleetMetrics:
    """Fleet-level counters (replica lifecycle, routing decisions,
    migrations, shedding); per-request latency stays in each engine's
    own :class:`~pddl_tpu.serve.ServeMetrics`."""

    def __init__(self):
        self.replica_up_events = 0       # respawns that closed a circuit
        self.replica_down_events = 0
        self.migrations = 0              # death → redistribution passes
        self.requests_migrated = 0
        self.migrated_via_drain = 0      # live migration (snapshot)
        self.migrated_via_replay = 0     # hard kill (router mirrors)
        self.requests_routed = 0
        self.routed_sticky = 0
        self.routed_affinity = 0
        self.routed_hash = 0
        self.routed_load_balanced = 0  # interactive shed off a hot
        #                                affinity replica (pressure-
        #                                aware routing)
        self.routed_adapter = 0        # adapter-affinity hit: routed to
        #                                the replica whose pool already
        #                                holds the request's LoRA
        #                                adapter (`serve/tenant/`)
        self.routed_host_tier = 0      # affinity hit on a replica's
        #                                HOST tier: no replica held the
        #                                chain in HBM, one held it in
        #                                host RAM (`kvcache/hosttier.py`)
        self.routed_prefill = 0        # disaggregated fleet (ISSUE 17):
        #                                cold prompt sent to the PREFILL
        #                                pool; the stream hands off to a
        #                                decode replica at first token
        # Replica-to-replica prefix transfer (ISSUE 13): chains pulled
        # from the replica that held them into the routed target's host
        # tier — duplicate prefill eliminated fleet-wide — and the
        # prompt tokens those pulls moved.
        self.chain_pulls = 0
        self.chain_pull_tokens = 0
        # Prefill->decode hand-offs (`fleet/disagg.py`): streams
        # rebound from the prefill pool to a decode replica at first
        # token, the subset that failed (died mid-transfer or the
        # target refused the KV), and the chain payload they moved.
        # `decode_long_prompt_stalls` counts streams that had to KEEP
        # decoding on a prefill replica because no decode replica
        # could take them (once per stream) — the exposition gauges it
        # NaN while the fleet is not disaggregation-armed.
        self.handoffs_completed = 0
        self.handoffs_failed = 0
        self.handoff_bytes = 0
        self.handoff_tokens = 0
        self.decode_long_prompt_stalls = 0
        self.shed_rerouted = 0           # QueueFull → another replica took it
        self.shed_rejected = 0           # fleet-wide full: caller rejected
        # Admission control / brownout (`fleet/admission.py`): front-
        # door rejections BEFORE any engine queue was consulted, plus
        # the ladder's movement counters. Per-class rejection splits
        # flatten into the snapshot as admission_rejected_<class>.
        self.admission_rate_limited = 0
        self.brownout_shed_best_effort = 0
        self.brownout_rejected_cold = 0
        self.brownout_capped_output = 0
        self.brownout_escalations = 0
        self.brownout_deescalations = 0
        self.rejected_by_priority: Dict[str, int] = {
            p.value: 0 for p in Priority}
        # Elastic scaling (`fleet/autoscaler.py` is the policy; the
        # router executes): replicas added/retired at runtime, and the
        # requests a scale-down live-migrated off its victim. The
        # policy-side counters (holds, cooldowns, spawn backoff) live
        # on the autoscaler's own metrics.
        self.scale_up_events = 0
        self.scale_down_events = 0
        self.scale_down_migrated = 0
        # Gray-failure machinery (ISSUE 14): interactive submissions
        # hedged off a suspected-gray replica, the subset where the
        # HEDGE copy beat the suspect to first result, the duplicate
        # copies cancelled (one per settled pair), and suspects
        # proactively retired through the scale_down migration path.
        self.hedges_launched = 0
        self.hedge_wins = 0
        self.hedge_cancelled = 0
        self.gray_drains = 0
        # Framed-transport health (ISSUE 14), aggregated from every
        # process replica's wire stats: resend rounds the gap/corrupt
        # recovery ran, and frames the CRC/length check REFUSED (a
        # nonzero reject count with token-exact streams is the "zero
        # corrupt frames accepted" proof, not a failure).
        self.wire_retries = 0
        self.wire_crc_rejects = 0
        # Journal storage health (ISSUE 18), mirrored from the WAL's
        # degradation machinery: every OSError the VFS shim surfaced
        # (retries included), entries into the NON_DURABLE degraded
        # mode, and re-arms back to durable. A nonzero error count
        # with zero degraded events is the bounded-backoff retry loop
        # absorbing a transient disk; the gauge (`journal_non_durable`)
        # carries the live alarmed state.
        self.journal_storage_errors = 0
        self.journal_degraded_events = 0
        self.journal_rearms = 0
        # Router HA (ISSUE 20): standby promotions executed by this
        # process, worker-bound commands a replica REFUSED because they
        # carried a stale fencing epoch (a nonzero count is the split-
        # brain defence firing, not a fleet fault), and catch-up folds a
        # standby ran from checkpoint+segment because the live stream
        # had a gap (join, or a NON_DURABLE backlog on the primary).
        self.takeovers = 0
        self.fenced_commands_refused = 0
        self.standby_catchups = 0
        self.requests_finished = 0
        self.requests_failed = 0
        self.requests_orphaned = 0
        self.heartbeat_failures = 0
        self.probes = 0
        self.probe_failures = 0
        self.tokens_streamed = 0
        # Per-class delivery splits (tokens_streamed_<class> in the
        # snapshot): the autoscaler's goodput signal — and the
        # dashboard's — without a second accounting path.
        self.tokens_streamed_by_priority: Dict[str, int] = {
            p.value: 0 for p in Priority}
        self.circuit_transitions: Dict[str, int] = {}

    def snapshot(self) -> Dict[str, object]:
        # Derived from the exporter's canonical key set so the two
        # cannot drift: a counter added above but missing from
        # FLEET_COUNTER_KEYS never reaches the snapshot, and one listed
        # there but not defined here raises loudly right away.
        from pddl_tpu.obs.export import FLEET_COUNTER_KEYS  # noqa: PLC0415

        out = {k: getattr(self, k) for k in sorted(FLEET_COUNTER_KEYS)}
        for key, n in sorted(self.circuit_transitions.items()):
            out["circuit_" + key.replace("->", "_to_")] = n
        for cls, n in sorted(self.rejected_by_priority.items()):
            out["admission_rejected_" + cls] = n
        for cls, n in sorted(self.tokens_streamed_by_priority.items()):
            out["tokens_streamed_" + cls] = n
        return out


class _ShadowIndex:
    """Host-only shadow of one replica's radix cache: the SAME match
    machinery (`serve/kvcache/radix.py`), but its "block ids" are
    placeholders — no device pool exists here. Optimistic by design
    (the replica's real cache may have evicted a chain the shadow still
    holds); a stale hit costs one suboptimal route, never correctness.

    With ``host_capacity_blocks > 0`` the shadow models the replica's
    SECOND tier too (ISSUE 13): the device shadow's own LRU eviction
    demotes the victim's full chain into a host-shadow index — the same
    eviction-becomes-demotion composition the engine runs, mirrored
    structurally — so prefix-affinity can route to "has it in host
    RAM" when no replica has it in HBM. Same optimism: the engine's
    real policy (spill-worthiness, byte budget) may have decided
    differently; a stale host hit costs one promotion-less route."""

    def __init__(self, block_size: int, capacity_blocks: int,
                 host_capacity_blocks: int = 0):
        self._bs = int(block_size)
        self._idx = RadixPrefixCache(self._bs, capacity_blocks + 1)
        self._host = (RadixPrefixCache(self._bs, host_capacity_blocks + 1)
                      if host_capacity_blocks > 0 else None)
        if self._host is not None:
            self._idx.on_evict = self._demote

    def _demote(self, victims) -> None:
        for node in victims:
            tokens = self._idx.chain_tokens(node)
            self._store(self._host, tokens, len(tokens) // self._bs)

    def match_blocks(self, prompt, max_blocks: int) -> int:
        return self._idx.match(prompt, max_blocks=max_blocks).n_blocks

    def match_blocks_host(self, prompt, max_blocks: int) -> int:
        """Leading blocks the HOST-tier shadow holds (0 when the
        replica has no second tier)."""
        if self._host is None:
            return 0
        return self._host.match(prompt, max_blocks=max_blocks).n_blocks

    def observe(self, prompt, max_blocks: int) -> None:
        """Record that this replica now holds the prompt's leading
        blocks (mirror of the engine's donate-side dedup walk)."""
        self._store(self._idx, prompt, max_blocks)

    def observe_host(self, prompt, max_blocks: int) -> None:
        """Record that this replica's HOST tier now holds the prompt's
        leading blocks (a replica-to-replica chain pull landed)."""
        if self._host is not None:
            self._store(self._host, prompt, max_blocks)

    def _store(self, idx: RadixPrefixCache, prompt,
               max_blocks: int) -> None:
        match = idx.match(prompt, max_blocks=max_blocks)
        node, stored = idx.descend(match.node, prompt, match.n_blocks)
        want = min(len(prompt) // self._bs, max_blocks) - stored
        if want <= 0:
            return
        ids = idx.allocate(want)
        if ids:
            idx.extend(
                node,
                prompt[stored * self._bs:(stored + len(ids)) * self._bs],
                ids)


class _ReplicaSlot:
    """One replica's router-side state: driver + breaker + shadow index
    + the fleet handles currently assigned to it."""

    def __init__(self, driver, breaker: CircuitBreaker,
                 shadow_block_size: int, shadow_capacity: int,
                 shadow_host_capacity: int = 0):
        self.driver = driver
        self.replica_id = driver.replica_id
        self.breaker = breaker
        self.state = ReplicaLifecycle.UP
        self.assigned: Dict[int, FleetHandle] = {}
        self._shadow_cfg = (shadow_block_size, shadow_capacity,
                            shadow_host_capacity)
        self.shadow = _ShadowIndex(*self._shadow_cfg)
        # Last-read wire-stat snapshot (framed process replicas): the
        # router folds per-step DELTAS into FleetMetrics, so a respawn
        # (fresh transport, counters back to zero) resets this baseline
        # instead of double-counting or going negative.
        self.wire_base: Optional[Dict[str, int]] = None

    def reset_shadow(self) -> None:
        self.shadow = _ShadowIndex(*self._shadow_cfg)

    @property
    def load(self) -> int:
        return len(self.assigned)

    @property
    def available(self) -> bool:
        return (self.state is ReplicaLifecycle.UP
                and self.breaker.allows_traffic)


class FleetRouter:
    """Health-checked router over N replica drivers.

    Args:
      replicas: driver sequence (:class:`~.replica.LocalReplica` /
        :class:`~.replica.ProcessReplica`), ids unique.
      affinity_block_size: token granularity of the routing shadow —
        match the replicas' ``prefix_block_size`` so shadow hits
        predict real radix hits.
      affinity_blocks: leading blocks consulted for affinity AND fed to
        the rendezvous hash (the "prompt head").
      shadow_capacity_blocks: per-replica shadow index size (host RAM
        only; LRU beyond it).
      breaker: kwargs for each replica's :class:`CircuitBreaker`.
      heartbeat_timeout_s: a driver exposing ``beat_age_s`` (process
        replicas) older than this counts a breaker failure per step.
      respawn: allow HALF_OPEN probes to rebuild dead replicas (fresh
        engine / fresh worker process). With it off, a dead replica
        stays dead and its circuit never half-opens.
      tracer: `obs/` tracer; fleet events emit via ``on_fleet_event``.
      clock: injectable monotonic clock (chaos tests drive backoff and
        heartbeat timeouts with a fake one).
      admission: optional :class:`~.admission.AdmissionControl` — the
        overload front door (per-priority token buckets, overload
        detector, brownout ladder). ``None`` (default) admits
        everything the engines will take, exactly the r11 behavior.
      interactive_reroute_load: priority-aware routing pressure
        threshold — when the affinity-chosen replica's assigned load
        reaches this many requests, INTERACTIVE submissions route to
        the least-loaded healthy replica instead of the warm cache
        (batch / best_effort keep pure prefix affinity: they can
        afford the queue wait the warm cache buys back). ``None``
        (default) keeps pure affinity for every class.
      shadow_host_capacity_blocks: per-replica HOST-TIER shadow size
        (ISSUE 13): the device shadow's own LRU eviction demotes
        chains into a second shadow index, mirroring the replicas'
        ``host_tier`` engines, so prefix-affinity can route to "has it
        in host RAM" when no replica has it in HBM (route label
        ``host_tier``). ``0`` (default) keeps the shadow tier-blind —
        exactly the r17 router.
      journal: optional :class:`~.journal.RouterJournal` — the
        control-plane WAL (ISSUE 14). Admissions/bindings are logged
        durably before the caller's handle returns, token mirrors as
        fsync-batched deltas, and the checkpoint+truncate cycle runs
        on the step cadence; :meth:`recover` rebuilds a crashed router
        from the same directory. ``None`` (default) keeps the r18
        in-memory-only control plane.
      gray: arm the gray-failure detector — a
        :class:`~.health.GrayDetector` instance, a kwargs dict for
        one, or ``True`` for defaults. The router feeds it each
        replica's per-step wall time; suspects are hedged around
        (``gray_hedge``) and optionally retired (``gray_drain``).
        ``None`` (default) keeps the dead-or-alive-only fleet.
      gray_hedge: with ``gray`` armed, INTERACTIVE submissions routed
        to a suspected replica are duplicated to the least-loaded
        healthy non-suspected sibling; the first replica to produce a
        result wins and the other copy is cancelled — the classic
        tail-tolerant hedge, applied only where suspicion already
        says the latency will be bad.
      gray_drain: with ``gray`` armed, a suspected replica is
        proactively RETIRED through the ``scale_down`` live-migration
        path (zero loss, the r16 contract) while it can still drain —
        the gray-failure analogue of failover, run before the
        failure.
      gray_timer: wall-clock source for the per-step latency samples
        (``time.perf_counter``; injectable so chaos tests can script
        exact durations).
      chain_pull_blocks: replica-to-replica prefix transfer (ISSUE 13)
        — when a request routes COLD (rendezvous hash, or a load
        escape off the warm replica) and some OTHER healthy replica's
        shadow holds at least this many leading blocks more than the
        target, the router PULLS the chain: the source exports it over
        the drain-module chain wire format
        (`serve/drain.py` ``kv_chain_to_wire``) and the target imports
        it into its HOST tier, where the admission promotes it instead
        of re-prefilling — duplicate prefill eliminated fleet-wide.
        Requires host-tier-enabled replicas to land anywhere. ``None``
        (default) disables pulling.
    """

    def __init__(self, replicas: Sequence[object], *,
                 affinity_block_size: int = 8, affinity_blocks: int = 8,
                 shadow_capacity_blocks: int = 4096,
                 breaker: Optional[Dict[str, object]] = None,
                 heartbeat_timeout_s: float = 5.0,
                 respawn: bool = True, tracer=None,
                 max_sessions: int = 65536,
                 admission: Optional[AdmissionControl] = None,
                 interactive_reroute_load: Optional[int] = None,
                 shadow_host_capacity_blocks: int = 0,
                 chain_pull_blocks: Optional[int] = None,
                 journal=None, gray=None, gray_hedge: bool = True,
                 gray_drain: bool = False, gray_timer=time.perf_counter,
                 dtrace=None, clock=time.monotonic):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"replica ids must be unique, got {ids}")
        self._clock = clock
        self._tracer = NULL_TRACER if tracer is None else tracer
        self._heartbeat_timeout_s = float(heartbeat_timeout_s)
        self._respawn = bool(respawn)
        self._affinity_blocks = int(affinity_blocks)
        self._block_size = int(affinity_block_size)
        self._interactive_reroute_load = (
            int(interactive_reroute_load)
            if interactive_reroute_load is not None else None)
        if (self._interactive_reroute_load is not None
                and self._interactive_reroute_load < 1):
            raise ValueError(
                f"interactive_reroute_load must be >= 1, got "
                f"{interactive_reroute_load}")
        self.metrics = FleetMetrics()
        # Kept beyond __init__: an elastic scale-up builds new slots
        # with the SAME breaker policy and shadow sizing as the
        # original fleet.
        self._breaker_kw = dict(breaker or {})
        self._shadow_capacity = int(shadow_capacity_blocks)
        self._shadow_host_capacity = int(shadow_host_capacity_blocks)
        self._chain_pull_blocks = (int(chain_pull_blocks)
                                   if chain_pull_blocks is not None
                                   else None)
        if (self._chain_pull_blocks is not None
                and self._chain_pull_blocks < 1):
            raise ValueError(
                f"chain_pull_blocks must be >= 1, got "
                f"{chain_pull_blocks}")
        self._autoscaler = None
        self._journal = journal
        if journal is not None:
            # Storage degradation is alarmable, not silent: every VFS
            # error, NON_DURABLE entry, and re-arm lands in the trace
            # with its (op, errno) coordinate and mirrors into the
            # fleet counters the exposition exports.
            journal.on_storage_event = self._on_journal_storage_event
        if gray is True:
            gray = GrayDetector()
        elif isinstance(gray, dict):
            gray = GrayDetector(**gray)
        self._gray = gray
        self._gray_hedge = bool(gray_hedge)
        self._gray_drain = bool(gray_drain)
        self._gray_timer = gray_timer
        # Fleet-wide distributed tracing (ISSUE 19): `dtrace=True`
        # builds the router-side TraceCollector; pass a constructed
        # collector to share/inspect it. When armed, every submit/
        # restore/chain command is stamped with a wire trace context
        # and replica span records are drained into the collector each
        # step. None/False keeps every hot path byte-identical.
        if dtrace is None or dtrace is False:
            self._dtrace = None
        elif dtrace is True:
            self._dtrace = TraceCollector(clock=clock)
        else:
            self._dtrace = dtrace
        # Hedge bookkeeping: rid <-> rid cross-links for live pairs,
        # and the subset of rids that are the HEDGE copy (so a win by
        # the hedge — not by the suspected primary — is countable).
        self._hedge_peer: Dict[int, int] = {}
        self._hedge_rids: set = set()
        # hedge rid -> primary rid, for the JOURNAL's sake: the admit
        # was logged under the primary rid, so every later record for
        # the stream — tokens, the finish, the checkpoint entry — must
        # use the same key or recovery would resurrect a stream whose
        # finish it filed under an unknown rid.
        self._hedge_alias: Dict[int, int] = {}
        # Prefill->decode stream rebinding (`fleet/disagg.py`). Always
        # constructed; it only acts when a prefill-role slot emits
        # tokens, so an all-unified fleet never touches it.
        self._handoff = HandoffManager(self)
        self._slots: List[_ReplicaSlot] = []
        for driver in replicas:
            self._new_slot(driver)
        self._by_rid: Dict[int, FleetHandle] = {}
        self._rid_counter = 0
        # Sticky-session map, LRU-bounded: sessions outlive their
        # requests by design (that is the stickiness), so without a cap
        # a long-lived router grows one entry per distinct session
        # forever. Least-recently-routed sessions fall off first; an
        # evicted session that returns simply re-routes by affinity.
        self._max_sessions = int(max_sessions)
        self._sessions: "collections.OrderedDict[str, _ReplicaSlot]" = \
            collections.OrderedDict()
        # Adapter-affinity homes (`serve/tenant/`): adapter name → the
        # replica whose device pool last loaded it. Routing same-
        # adapter traffic back keeps the pool warm (a cold load per
        # replica per adapter, not per request); a death drops only its
        # own entries, and the home FOLLOWS reality — it re-pins to
        # wherever a request actually landed (shed reroutes included).
        self._adapter_homes: Dict[str, _ReplicaSlot] = {}
        # (rid, FleetHandle) pairs with no surviving replica, waiting
        # for a probe to bring one back.
        self._orphans: List[Tuple[int, FleetHandle]] = []
        self._closed = False
        # Fencing epoch (ISSUE 20): None = unarmed, every driver call
        # goes out epoch-free and pre-HA fleets are byte-identical.
        # Armed (via set_epoch, normally by HotStandby.promote), every
        # worker-bound mutator carries it and a deposed router's
        # commands come back as typed EpochFenced rejects.
        self._epoch: Optional[int] = None
        self._admission = admission
        if admission is not None:
            admission.brownout.on_transition = self._brownout_observer(
                admission.brownout.on_transition)

    @property
    def epoch(self) -> Optional[int]:
        """The fencing epoch this router stamps on worker-bound
        commands; None while HA is unarmed (single-router fleets)."""
        return self._epoch

    def set_epoch(self, epoch: int) -> None:
        """Arm (or raise) the fencing epoch. Journals an ``epoch``
        record so the WAL tail always names the current writer — a
        standby tailing this journal learns the leadership change from
        the same stream it replicates."""
        epoch = int(epoch)
        if self._epoch is not None and epoch < self._epoch:
            raise ValueError(
                f"epoch may only move forward ({self._epoch} -> {epoch})")
        self._epoch = epoch
        if self._journal is not None:
            self._journal.append(journal_io.encode_fence_epoch(epoch),
                                 durable=True)
        self._tracer.on_fleet_event("epoch_armed", epoch=epoch)

    def _count_fenced(self, exc: EpochFenced) -> None:
        self.metrics.fenced_commands_refused += 1
        self._tracer.on_fleet_event(
            "command_fenced", replica_id=exc.replica_id,
            epoch=exc.epoch, highest=exc.highest)

    def _brownout_observer(self, chained):
        def observe(old, new) -> None:
            if new > old:
                self.metrics.brownout_escalations += 1
            else:
                self.metrics.brownout_deescalations += 1
            self._tracer.on_fleet_event(
                "brownout", transition=f"{old.name}->{new.name}",
                rung=int(new))
            if chained is not None:  # the caller's own hook still fires
                chained(old, new)
        return observe

    @property
    def admission(self) -> Optional[AdmissionControl]:
        return self._admission

    @property
    def journal(self):
        """The attached control-plane WAL (None when not armed)."""
        return self._journal

    @property
    def gray(self) -> Optional[GrayDetector]:
        """The gray-failure detector (None when not armed)."""
        return self._gray

    @property
    def dtrace(self):
        """The distributed-trace collector (None when not armed) —
        `obs/assemble.py` stitches its ``records()``; the chaos
        conductor's ``trace_complete`` invariant keys off it."""
        return self._dtrace

    def _new_rid(self) -> int:
        rid = self._rid_counter
        self._rid_counter += 1
        return rid

    @property
    def clock(self):
        """The router's monotonic clock (injectable for chaos tests) —
        shared with the autoscaler so control-loop holds and cooldowns
        live on the same epoch as breaker backoffs and heartbeats."""
        return self._clock

    def _degraded_replica_count(self) -> int:
        """Replicas reporting DEGRADED (r08's OOM machinery) — fed to
        the overload detector so memory pressure and load pressure
        compose into one brownout signal."""
        return sum(1 for s in self._slots
                   if s.state is ReplicaLifecycle.UP
                   and bool(getattr(s.driver, "degraded", False)))

    # ------------------------------------------------------ observability
    def _circuit_observer(self, slot: _ReplicaSlot):
        def observe(old: BreakerState, new: BreakerState) -> None:
            key = f"{old.value}->{new.value}"
            self.metrics.circuit_transitions[key] = \
                self.metrics.circuit_transitions.get(key, 0) + 1
            self._tracer.on_fleet_event(
                "circuit", replica=slot.replica_id, transition=key)
        return observe

    @property
    def tracer(self):
        return self._tracer

    def set_tracer(self, tracer) -> None:
        self._tracer = NULL_TRACER if tracer is None else tracer

    # ----------------------------------------------------------- plumbing
    @property
    def replicas(self) -> List[_ReplicaSlot]:
        return list(self._slots)

    @property
    def healthy_replicas(self) -> int:
        return sum(s.available for s in self._slots)

    @property
    def disagg_armed(self) -> bool:
        """Disaggregated serving armed (ISSUE 17): the fleet holds at
        least one strict-``prefill`` AND one strict-``decode`` replica.
        A fleet-SHAPE property, not a health one — a split fleet whose
        prefill pool momentarily died stays armed (routing degrades to
        the unified path until a prefill replica returns); an
        all-unified fleet never arms, which is the backward-compat
        guarantee."""
        roles = {role_of(s.driver) for s in self._slots}
        return "prefill" in roles and "decode" in roles

    @property
    def has_work(self) -> bool:
        return any(not fh.done for fh in self._by_rid.values()) \
            or bool(self._orphans)

    def warmup(self) -> None:
        for slot in self._slots:
            if slot.state is ReplicaLifecycle.UP:
                slot.driver.warmup()

    def compile_counts(self) -> Dict[str, int]:
        """Aggregated per-replica compiled-program counts, keyed
        ``r<id>/<site>`` — the zero-recompiles pin applied to every
        queryable replica (a hard-killed worker is skipped: there is
        nothing left to recompile OR to query)."""
        counts: Dict[str, int] = {}
        for slot in self._slots:
            try:
                for site, n in slot.driver.compile_counts().items():
                    counts[f"r{slot.replica_id}/{site}"] = n
            except ReplicaDied:
                continue
        return counts

    # ------------------------------------------------------------ routing
    def _prompt_head(self, prompt: List[int]) -> bytes:
        head = prompt[:self._affinity_blocks * self._block_size]
        return (",".join(str(t) for t in head)).encode()

    def _rendezvous(self, prompt: List[int],
                    candidates: List[_ReplicaSlot]) -> _ReplicaSlot:
        head = self._prompt_head(prompt)

        def score(slot: _ReplicaSlot) -> int:
            h = hashlib.blake2b(head + b"|" + str(slot.replica_id).encode(),
                                digest_size=8)
            return int.from_bytes(h.digest(), "big")
        return max(candidates, key=score)

    def _session_pin(self, session: str, slot: _ReplicaSlot) -> None:
        self._sessions[session] = slot
        self._sessions.move_to_end(session)
        while len(self._sessions) > self._max_sessions:
            self._sessions.popitem(last=False)

    def _route(self, prompt: List[int], session: Optional[str],
               healthy: List[_ReplicaSlot],
               priority: Priority = Priority.INTERACTIVE,
               adapter: Optional[str] = None,
               ) -> Tuple[_ReplicaSlot, str, Dict[int, int], Dict[int, int]]:
        """Returns ``(slot, how, device_depths, host_depths)`` — the
        depth maps (replica_id -> matched blocks) record exactly the
        shadow walks this call performed, so ``_maybe_pull_chain`` can
        reuse them instead of re-walking every shadow on the routing
        path (a sticky/adapter return walked nothing; a device-affinity
        return never walked the host shadows)."""
        dev_depths: Dict[int, int] = {}
        host_depths: Dict[int, int] = {}
        if session is not None:
            stuck = self._sessions.get(session)
            if stuck is not None:
                self._sessions.move_to_end(session)  # LRU touch
                if stuck.available:
                    return stuck, "sticky", dev_depths, host_depths
        if self.disagg_armed:
            # Disaggregated fleet (ISSUE 17): every non-sticky
            # admission lands on the PREFILL pool — cold prompts
            # chunk-prefill there and hand off at first token, so a
            # decode replica never stalls a tick on one. Prefix
            # affinity applies WITHIN the pool (a shared system prompt
            # still lands where its KV lives), least-loaded breaks
            # cold ties. Adapter affinity is intentionally skipped:
            # its home would drag long prompts onto whatever decode
            # replica the stream handed off to last time. With the
            # whole pool down, routing degrades to the unified path
            # below — slow beats refused.
            pool = [s for s in healthy
                    if role_of(s.driver) == "prefill"]
            if pool:
                best = min(pool, key=lambda s: (
                    -s.shadow.match_blocks(
                        prompt, max_blocks=self._affinity_blocks),
                    s.load))
                return best, "prefill", dev_depths, host_depths
        if adapter is not None:
            # Adapter affinity outranks prefix affinity (reloading
            # LoRA factors costs more than a cold prefix chunk) but
            # yields to stickiness — a multi-turn session's KV lives
            # where the session lives — and to the SAME interactive
            # pressure escape prefix affinity has: a popular adapter
            # must not funnel interactive traffic onto one replica
            # until it hard-QueueFulls while siblings idle.
            home = self._adapter_homes.get(adapter)
            if home is not None and home.available:
                escape = self._interactive_load_escape(home, healthy,
                                                       priority)
                if escape is not None:
                    return escape, "load", dev_depths, host_depths
                return home, "adapter", dev_depths, host_depths
        best, best_blocks = None, 0
        for slot in healthy:
            m = slot.shadow.match_blocks(prompt,
                                         max_blocks=self._affinity_blocks)
            dev_depths[slot.replica_id] = m
            if m > best_blocks or (m == best_blocks and m > 0
                                   and best is not None
                                   and slot.load < best.load):
                best, best_blocks = slot, m
        if best is not None and best_blocks > 0:
            escape = self._interactive_load_escape(best, healthy,
                                                   priority)
            if escape is not None:
                return escape, "load", dev_depths, host_depths
            return best, "affinity", dev_depths, host_depths
        # Second-tier affinity (ISSUE 13): no replica holds the prefix
        # in HBM — route to the one whose HOST tier holds it (the
        # engine promotes instead of re-prefilling), under the same
        # interactive pressure escape HBM affinity has.
        hbest, hblocks = None, 0
        for slot in healthy:
            hm = slot.shadow.match_blocks_host(
                prompt, max_blocks=self._affinity_blocks)
            host_depths[slot.replica_id] = hm
            if hm > hblocks or (hm == hblocks and hm > 0
                                and hbest is not None
                                and slot.load < hbest.load):
                hbest, hblocks = slot, hm
        if hbest is not None and hblocks > 0:
            escape = self._interactive_load_escape(hbest, healthy,
                                                   priority)
            if escape is not None:
                return escape, "load", dev_depths, host_depths
            return hbest, "host_tier", dev_depths, host_depths
        return (self._rendezvous(prompt, healthy), "hash",
                dev_depths, host_depths)

    def _maybe_pull_chain(self, prompt: List[int], chosen: _ReplicaSlot,
                          healthy: List[_ReplicaSlot],
                          dev_depths: Optional[Dict[int, int]] = None,
                          host_depths: Optional[Dict[int, int]] = None,
                          ) -> None:
        """Replica-to-replica prefix transfer (the ``chain_pull_blocks``
        arg docs): when a sibling's shadow (HBM or host tier) holds
        meaningfully more of the prompt's prefix than the routing
        target, export the chain from the sibling and import it into
        the target's host tier — the admission then PROMOTES instead of
        re-prefilling, eliminating the duplicate prefill the cold route
        would have paid. Best-effort end to end: a dead source, a
        refused import (target tier off / budget / foreign config), or
        an empty export all degrade to the plain cold admission.

        ``dev_depths``/``host_depths`` are ``_route``'s own shadow-walk
        results (replica_id -> matched blocks) — a depth already
        computed on the routing path is reused, only the components
        the route never walked (e.g. host shadows when device affinity
        decided first) are walked here."""
        blocks = self._affinity_blocks
        dev_depths = dev_depths or {}
        host_depths = host_depths or {}

        def depth_of(slot: _ReplicaSlot) -> int:
            d = dev_depths.get(slot.replica_id)
            if d is None:
                d = slot.shadow.match_blocks(prompt, max_blocks=blocks)
            h = host_depths.get(slot.replica_id)
            if h is None:
                h = slot.shadow.match_blocks_host(prompt,
                                                  max_blocks=blocks)
            return max(d, h)

        own = depth_of(chosen)
        best_src, depth = None, own
        for slot in healthy:
            if slot is chosen:
                continue
            d = depth_of(slot)
            if d > depth:
                best_src, depth = slot, d
        if best_src is None or depth - own < self._chain_pull_blocks:
            return
        export = getattr(best_src.driver, "export_chain", None)
        import_fn = getattr(chosen.driver, "import_chain", None)
        if export is None or import_fn is None:
            return
        try:
            entry = export(list(prompt), depth)
        except Exception:  # noqa: BLE001 - source may be dying; the
            return         # next step() settles it, the pull just skips
        if not entry:
            return
        try:
            n = import_fn(entry)
        except Exception:  # noqa: BLE001 - same best-effort contract
            return
        if n > 0:
            self.metrics.chain_pulls += 1
            self.metrics.chain_pull_tokens += n * self._block_size
            # The target's host tier now covers the EXPORTED chain's
            # depth (the import walks it from block 0, skipping blocks
            # already resident) — NOT own + n: `own` may be a device-
            # shadow match the host tier never held, and over-recording
            # would suppress deeper pulls for every later sharer.
            pulled_depth = (len(entry.get("tokens", []))
                            // self._block_size
                            if isinstance(entry, dict) else n)
            chosen.shadow.observe_host(
                prompt, max_blocks=min(blocks, pulled_depth))
            self._tracer.on_fleet_event(
                "chain_pull", from_replica=best_src.replica_id,
                to_replica=chosen.replica_id, blocks=n)

    def _interactive_load_escape(self, chosen: _ReplicaSlot,
                                 healthy: List[_ReplicaSlot],
                                 priority: Priority,
                                 ) -> Optional[_ReplicaSlot]:
        """Priority-aware load shedding of an affinity choice (warm
        prefix OR warm adapter): a warm cache is worth a queue wait to
        a BATCH request, but an INTERACTIVE one under an SLO prefers a
        cold start on an idle replica over queueing behind a hot spot.
        When the affinity winner's load crosses the threshold and a
        meaningfully lighter healthy replica exists, returns it
        (routed/labeled "load" — the runbook's signal that affinity is
        saturating); else None (keep the affinity choice)."""
        if (self._interactive_reroute_load is None
                or priority is not Priority.INTERACTIVE
                or chosen.load < self._interactive_reroute_load):
            return None
        lightest = min(healthy, key=lambda s: s.load)
        if lightest is not chosen and lightest.load < chosen.load:
            return lightest
        return None

    def submit(self, prompt, max_new_tokens: int, *,
               sampling: Optional[SamplingParams] = None,
               deadline_s: Optional[float] = None,
               session: Optional[str] = None,
               priority: Priority = Priority.INTERACTIVE,
               adapter: Optional[str] = None,
               constraint: Optional[dict] = None) -> FleetHandle:
        """Route one request; returns its fleet stream handle.

        ``adapter``/``constraint`` (the tenant fields, `serve/tenant/`)
        pass through to the replica engines; same-adapter traffic
        routes to the replica whose pool already holds the factors
        (adapter affinity — sticky sessions still outrank it).

        Raises :class:`NoHealthyReplica` when every circuit is open,
        :class:`~pddl_tpu.serve.request.AdmissionRejected` when the
        admission front door refused it (rate limit or brownout — the
        hint covers the ladder's recovery horizon), and
        :class:`~pddl_tpu.serve.request.QueueFull` (with the smallest
        ``retry_after_s`` hint any replica offered) when every healthy
        replica shed it."""
        if self._closed:
            raise RuntimeError("fleet router is closed")
        priority = Priority(priority)
        prompt = [int(t) for t in prompt]
        sampling = sampling or SamplingParams()
        healthy = [s for s in self._slots if s.available]
        if not healthy:
            raise NoHealthyReplica(
                f"no healthy replica among {len(self._slots)} "
                "(all circuits open)")
        chosen, how, dev_depths, host_depths = self._route(
            prompt, session, healthy, priority, adapter)
        now = self._clock()
        if self._admission is not None:
            self._admission.update(now, self._degraded_replica_count())
            # `cold` = neither sticky nor affinity matched: the
            # admission the top brownout rung refuses to buy. The
            # front door's own rejections are NOT fed to the overload
            # detector — the ladder must unwind on engine-side calm,
            # not sustain itself on the pressure of its own shedding.
            ok, reason, hint = self._admission.admit(
                now, priority, cold=(how == "hash"))
            if not ok:
                self.metrics.rejected_by_priority[priority.value] += 1
                if reason == "rate_limit":
                    self.metrics.admission_rate_limited += 1
                elif reason == "brownout_shed":
                    self.metrics.brownout_shed_best_effort += 1
                else:
                    self.metrics.brownout_rejected_cold += 1
                self._tracer.on_fleet_event(
                    "admission_rejected", reason=reason,
                    priority=priority.value)
                raise AdmissionRejected(reason, retry_after_s=hint,
                                        priority=priority)
            capped = self._admission.brownout.cap_new_tokens(
                max_new_tokens)
            if capped < int(max_new_tokens):
                self.metrics.brownout_capped_output += 1
                max_new_tokens = capped
        if self._chain_pull_blocks is not None and how in ("hash", "load"):
            # The request is landing COLD somewhere even though a
            # sibling may hold its prefix: pull the chain to the target
            # before the engine sees the prompt (ISSUE 13).
            self._maybe_pull_chain(prompt, chosen, healthy,
                                   dev_depths, host_depths)
        # Gray hedging (ISSUE 14): an INTERACTIVE request the routing
        # sent at a suspected-gray replica is duplicated to the
        # least-loaded healthy NON-suspected sibling — first result
        # wins, the other copy is cancelled. Batch/best_effort keep the
        # single copy: they can afford the suspect's tail.
        hedge_to: Optional[_ReplicaSlot] = None
        if (self._gray is not None and self._gray_hedge
                and priority is Priority.INTERACTIVE
                and self._gray.is_suspected(chosen.replica_id)):
            siblings = [s for s in healthy if s is not chosen
                        and not self._gray.is_suspected(s.replica_id)]
            if siblings:
                hedge_to = min(siblings, key=lambda s: s.load)
        order = [chosen] + sorted((s for s in healthy if s is not chosen),
                                  key=lambda s: s.load)
        hints: List[float] = []
        depth_sum = cap_sum = sheds_seen = 0
        for slot in order:
            rid = self._new_rid()
            # Stamp-only-when-armed: an unarmed router (epoch None)
            # emits the exact pre-HA call shape, so drivers and test
            # doubles that predate fencing keep working untouched.
            extra: Dict[str, object] = {}
            if self._dtrace is not None:
                extra["trace"] = self._dtrace.context_for(rid)
            if self._epoch is not None:
                extra["epoch"] = self._epoch
            try:
                slot.driver.submit(rid, prompt, max_new_tokens,
                                   sampling, deadline_s, priority,
                                   adapter, constraint, **extra)
            except EpochFenced as e:
                # Deposed router: the fleet refused us by design. No
                # point trying siblings — they share the fence floor.
                self._count_fenced(e)
                raise
            except QueueFull as e:
                sheds_seen += 1
                if e.retry_after_s is not None:
                    hints.append(e.retry_after_s)
                depth_sum += e.queue_depth
                cap_sum += e.max_queue_depth
                continue
            except ReplicaDied as e:
                self._on_death(slot, e)
                continue
            fh = FleetHandle(
                Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                        sampling=sampling, deadline_s=deadline_s,
                        priority=priority, adapter=adapter,
                        constraint=constraint),
                arrival_s=self._clock(), session=session)
            fh.replica_id = slot.replica_id
            fh.state = RequestState.QUEUED
            self._by_rid[rid] = fh
            slot.assigned[rid] = fh
            slot.shadow.observe(prompt, max_blocks=self._affinity_blocks)
            if session is not None:
                self._session_pin(session, slot)
            if adapter is not None:
                # The home follows where the request actually LANDED
                # (a shed reroute moves it): that replica's pool holds
                # — or is about to load — the factors.
                self._adapter_homes[adapter] = slot
            self.metrics.requests_routed += 1
            # Only a reroute forced by an actual QueueFull is load
            # shedding (the runbook reads shed_rerouted as
            # backpressure); skipping past a replica that DIED during
            # submit keeps the original routing label — the death
            # already traced replica_down.
            if sheds_seen:
                how = "shed"
                self.metrics.shed_rerouted += 1
                self._tracer.on_fleet_event(
                    "shed", request_id=fh.request.request_id,
                    to_replica=slot.replica_id)
            elif how == "sticky":
                self.metrics.routed_sticky += 1
            elif how == "adapter":
                self.metrics.routed_adapter += 1
            elif how == "affinity":
                self.metrics.routed_affinity += 1
            elif how == "load":
                self.metrics.routed_load_balanced += 1
            elif how == "host_tier":
                self.metrics.routed_host_tier += 1
            elif how == "prefill":
                self.metrics.routed_prefill += 1
            else:
                self.metrics.routed_hash += 1
            if self._admission is not None:
                # Engine-side signal: a reroute forced by QueueFull is
                # pressure even though the request landed.
                self._admission.observe(now, rejected=sheds_seen > 0)
            if self._dtrace is not None:
                # After the shed relabel, so the trace's route label
                # matches the journal's.
                self._dtrace.on_submit(rid, prompt_len=len(prompt),
                                       priority=priority.value,
                                       session=session)
                self._dtrace.on_route(rid, slot.replica_id, how)
            if hedge_to is not None and slot is not hedge_to:
                self._launch_hedge(fh, rid, slot, hedge_to,
                                   max_new_tokens)
            if self._journal is not None:
                # WAL contract: the admission + binding are DURABLE
                # before the caller holds an acked handle — a router
                # SIGKILL after this return can never lose the
                # request (`fleet/journal.py`).
                self._journal.append(
                    journal_io.encode_admit(rid, fh.request, session))
                self._journal.append(
                    journal_io.encode_route(rid, slot.replica_id, how),
                    durable=True)
            return fh
        if cap_sum == 0 and not hints:
            # Nothing actually reported a full queue — every attempt hit
            # a dying replica. That is the 503 case, not backpressure.
            raise NoHealthyReplica(
                f"every healthy replica died during submit "
                f"({len(order)} attempted)")
        self.metrics.shed_rejected += 1
        self.metrics.rejected_by_priority[priority.value] += 1
        if self._admission is not None:
            self._admission.observe(now, rejected=True)
        raise QueueFull(depth_sum, max(cap_sum, depth_sum),
                        retry_after_s=min(hints) if hints else None,
                        priority=priority)

    # ------------------------------------------------------------ hedging
    def _launch_hedge(self, fh: FleetHandle, primary_rid: int,
                      primary: _ReplicaSlot, hedge_to: _ReplicaSlot,
                      max_new_tokens: int) -> None:
        """Duplicate one admitted request onto ``hedge_to`` (the
        suspected-primary case). Best-effort: a full or dying hedge
        target simply leaves the single copy — hedging must never turn
        one admission into a failure it would not otherwise have."""
        req = fh.request
        hrid = self._new_rid()
        trace = None
        if self._dtrace is not None:
            # Alias FIRST so the hedge copy's wire context carries the
            # primary's trace id (one trace, two replicas racing).
            self._dtrace.alias(hrid, primary_rid)
            trace = self._dtrace.context_for(hrid)
        extra: Dict[str, object] = {}
        if trace is not None:
            extra["trace"] = trace
        if self._epoch is not None:
            extra["epoch"] = self._epoch
        try:
            hedge_to.driver.submit(hrid, list(req.prompt),
                                   int(max_new_tokens), req.sampling,
                                   req.deadline_s, req.priority,
                                   req.adapter, req.constraint, **extra)
        except EpochFenced as e:
            self._count_fenced(e)
            return
        except Exception:  # noqa: BLE001 - QueueFull / ReplicaDied /
            return         # anything: the single copy stands alone
        self._by_rid[hrid] = fh
        hedge_to.assigned[hrid] = fh
        self._hedge_peer[primary_rid] = hrid
        self._hedge_peer[hrid] = primary_rid
        self._hedge_rids.add(hrid)
        self._hedge_alias[hrid] = primary_rid
        self.metrics.hedges_launched += 1
        if self._dtrace is not None:
            self._dtrace.on_hedge(hrid, primary_rid,
                                  hedge_to.replica_id)
        if self._journal is not None:
            self._journal.append(journal_io.encode_route(
                hrid, hedge_to.replica_id, "hedge"))
        self._tracer.on_fleet_event(
            "hedge", request_id=req.request_id,
            suspected_replica=primary.replica_id,
            hedge_replica=hedge_to.replica_id)

    def _settle_hedge(self, winner_rid: int) -> None:
        """First-result-wins: the other copy of the pair is unbound
        from the fleet handle and cancelled on its replica; its later
        events fall into the void (``_by_rid`` miss). Idempotent — a
        rid with no live peer is a no-op."""
        loser_rid = self._hedge_peer.pop(winner_rid, None)
        if loser_rid is None:
            return
        self._hedge_peer.pop(loser_rid, None)
        self._hedge_alias.pop(loser_rid, None)  # winner's alias stays:
        #   its tokens/finish keep journaling under the primary rid
        fh = self._by_rid.pop(loser_rid, None)
        for slot in self._slots:
            if loser_rid in slot.assigned:
                slot.assigned.pop(loser_rid, None)
                try:
                    if self._epoch is not None:
                        slot.driver.cancel(loser_rid, epoch=self._epoch)
                    else:
                        slot.driver.cancel(loser_rid)
                except EpochFenced as e:
                    self._count_fenced(e)
                except Exception:  # noqa: BLE001 - loser may be dying;
                    pass           # either way its events are unbound
        winner_hedge = winner_rid in self._hedge_rids
        self._hedge_rids.discard(winner_rid)
        self._hedge_rids.discard(loser_rid)
        if winner_hedge:
            self.metrics.hedge_wins += 1
            # The handle follows the winner: the hedge replica now
            # runs the stream.
            fh = fh if fh is not None else self._by_rid.get(winner_rid)
            if fh is not None:
                for slot in self._slots:
                    if winner_rid in slot.assigned:
                        fh.replica_id = slot.replica_id
                        break
        self.metrics.hedge_cancelled += 1
        self._tracer.on_fleet_event(
            "hedge_settled", winner_rid=winner_rid,
            hedge_won=winner_hedge)

    def _abandon_hedge_copy(self, rid: int) -> None:
        """Dissolve a hedge pair in the PEER's favor without a winner
        ceremony: this copy failed/was shed with nothing emitted, so
        the peer simply continues as the (now sole) stream."""
        peer = self._hedge_peer.pop(rid, None)
        if peer is not None:
            self._hedge_peer.pop(peer, None)
        self._by_rid.pop(rid, None)
        self._hedge_rids.discard(rid)
        self._hedge_alias.pop(rid, None)
        self._tracer.on_fleet_event("hedge_copy_abandoned", rid=rid)

    # ------------------------------------------------------------ serving
    def step(self) -> int:
        """One router round: probe dead replicas whose backoff expired,
        pump/step every live replica (catching deaths and migrating
        their work), apply the resulting stream events. Returns tokens
        streamed to fleet handles this round."""
        now = self._clock()
        tokens = 0
        if self._admission is not None:
            # Ladder recovery must not depend on new submits arriving:
            # a browned-out fleet that traffic abandoned entirely still
            # unwinds to NORMAL on the step cadence.
            self._admission.update(now, self._degraded_replica_count())
        # Cancelled orphans settle HERE: no replica holds them, so the
        # per-slot cancel forwarding never sees them, and without this
        # an unbounded run() would spin on has_work through a total
        # outage whose probes keep failing — cancel() must always lead
        # to a terminal state.
        if self._orphans:
            kept = []
            for rid, fh in self._orphans:
                if fh.cancelled and not fh.done:
                    fh.state = RequestState.CANCELLED
                    fh.finish_reason = FinishReason.CANCELLED
                    fh.finish_s = now
                    self._by_rid.pop(rid, None)
                    if self._dtrace is not None:
                        self._dtrace.on_finish(
                            rid, fh.state.value, fh.finish_reason.value,
                            len(fh.tokens))
                elif not fh.done:
                    kept.append((rid, fh))
            self._orphans = kept
        for slot in self._slots:
            if slot.state is ReplicaLifecycle.DEAD:
                self._maybe_probe(slot, now)
                continue
            beat_fn = getattr(slot.driver, "beat_age_s", None)
            # One reading per round: each call drains the pipe, and the
            # pre-step value is the conservative one to credit against.
            beat_age = None if beat_fn is None else beat_fn()
            if beat_age is not None \
                    and beat_age > self._heartbeat_timeout_s:
                self.metrics.heartbeat_failures += 1
                slot.breaker.record_failure(now)
                self._tracer.on_fleet_event(
                    "heartbeat_missed", replica=slot.replica_id)
                if slot.breaker.state is BreakerState.OPEN:
                    self._on_death(
                        slot, ReplicaDied(slot.replica_id,
                                          "heartbeat timeout"))
                    continue
            step_t0 = self._gray_timer() if self._gray is not None \
                else 0.0
            try:
                events = slot.driver.step()
            except (KillPoint, ReplicaDied) as e:
                self._on_death(slot, e)
                continue
            except Exception as e:  # noqa: BLE001 - replica failure, not ours
                slot.breaker.record_failure(now)
                self._tracer.on_fleet_event(
                    "replica_error", replica=slot.replica_id,
                    error=type(e).__name__)
                if slot.breaker.state is BreakerState.OPEN:
                    self._on_death(slot, e)
                continue
            if self._gray is not None:
                # The per-tick latency samples the gray band judges. A
                # self-driving process replica SELF-REPORTS its engine
                # tick walls (on pongs): the router's pump wall cannot
                # see a slow worker across a pipe. In-process drivers
                # have no such channel — there, stepping IS the work,
                # so the step wall is the honest sample.
                take = getattr(slot.driver, "take_latency_samples",
                               None)
                if take is not None:
                    for sample in take():
                        self._gray.observe(slot.replica_id, sample)
                else:
                    self._gray.observe(slot.replica_id,
                                       self._gray_timer() - step_t0)
            self._fold_wire_stats(slot)
            if self._dtrace is not None:
                self._collect_spans(slot)
            # A successful pump only counts as breaker success when the
            # heartbeat (if the driver has one) is actually fresh — a
            # hung-but-alive worker keeps accepting pings into its pipe
            # buffer, and crediting that would reset the silence count
            # so the breaker could never reach OPEN.
            if beat_age is None or beat_age <= self._heartbeat_timeout_s:
                slot.breaker.record_success(now)
            tokens += self._apply_events(slot, events)
            self._forward_cancels(slot)
        # Prefill->decode hand-offs run AFTER the slot loop (a hand-off
        # restores onto another slot — same no-mutation-under-iteration
        # discipline the autoscaler tick below rides).
        if self._handoff.pending:
            self._handoff.execute()
        self._maybe_gray_drain()
        if self._autoscaler is not None:
            # One controller decision per routing round, AFTER the slot
            # loop: a scale-down mutates the slot list, which must never
            # happen under the iteration above.
            self._autoscaler.step(self._clock())
        if self._journal is not None:
            # emergency_checkpoint_due: the WAL hit ENOSPC — an
            # immediate checkpoint+rotate retires the oldest segment
            # (the only space the journal owns) instead of blind-
            # retrying writes against a full disk.
            if (self._journal.checkpoint_due
                    or getattr(self._journal,
                               "emergency_checkpoint_due", False)):
                self._journal_checkpoint()
            self._journal.tick()
        return tokens

    def _collect_spans(self, slot: _ReplicaSlot) -> None:
        """Drain a driver's shipped span records into the collector
        and refresh the replica's clock-offset estimate (ISSUE 19).
        Driver-agnostic via getattr — a test double without the trace
        surface simply contributes nothing."""
        take = getattr(slot.driver, "take_span_records", None)
        if take is not None:
            try:
                records = take()
            except Exception:  # noqa: BLE001 - a dying pipe settles later
                records = []
            if records:
                self._dtrace.add_replica_records(slot.replica_id,
                                                 records)
        off = getattr(slot.driver, "clock_offset", None)
        if off is not None:
            try:
                self._dtrace.set_offset(slot.replica_id, off())
            except Exception:  # noqa: BLE001 - same
                pass
        dropped = getattr(slot.driver, "spans_dropped", None)
        if dropped:
            self._dtrace.note_remote_drops(int(dropped))

    def _fold_wire_stats(self, slot: _ReplicaSlot) -> None:
        """Aggregate a framed driver's transport counters into
        FleetMetrics as deltas against the slot's last reading."""
        ws = getattr(slot.driver, "wire_stats", None)
        if ws is None:
            return
        try:
            stats = dict(ws())
        except Exception:  # noqa: BLE001 - a dying pipe settles later
            return
        base = slot.wire_base or {}
        self.metrics.wire_retries += max(
            0, stats.get("retries", 0) - base.get("retries", 0))
        self.metrics.wire_crc_rejects += max(
            0, stats.get("crc_rejects", 0) - base.get("crc_rejects", 0))
        slot.wire_base = stats

    def _maybe_gray_drain(self) -> None:
        """Proactively retire suspected-gray replicas through the r16
        ``scale_down`` live-migration path — the whole point of a gray
        DETECTOR is acting before the failure. Refuses to drain the
        last available replica (slow beats gone)."""
        if self._gray is None or not self._gray_drain:
            return
        for rid in sorted(self._gray.suspected):
            slot = next((s for s in self._slots
                         if s.replica_id == rid
                         and s.state is ReplicaLifecycle.UP), None)
            if slot is None:
                self._gray.forget(rid)
                continue
            try:
                migrated = self.scale_down(rid)
            except ValueError:
                return  # no survivor to absorb it: keep serving slow
            self.metrics.gray_drains += 1
            self._gray.forget(rid)
            self._tracer.on_fleet_event(
                "gray_drain", replica=rid, migrated=migrated)

    def _journal_entries(self) -> List[Tuple[int, Dict]]:
        """The checkpoint body: every in-flight stream's mirror as a
        rid-tagged drain wire entry (one per HANDLE — a hedged pair
        checkpoints its primary rid only, so recovery revives one
        copy, not a duplicate race)."""
        now = self._clock()
        out: List[Tuple[int, Dict]] = []
        seen = set()
        for rid, fh in sorted(self._by_rid.items()):
            if fh.done or rid in self._hedge_rids or id(fh) in seen:
                continue
            seen.add(id(fh))
            entry = drain_io.encode_handle(fh, now)
            entry["session"] = fh.session
            # A won hedge runs under its hedge rid; the journal's key
            # for the stream is the primary rid its admit used.
            out.append((self._hedge_alias.get(rid, rid), entry))
        return out

    def _journal_checkpoint(self) -> None:
        self._journal.checkpoint(self._journal_entries(),
                                 next_rid=self._rid_counter)
        if self._epoch is not None:
            # The checkpoint truncated the WAL: re-assert the writer's
            # epoch so the fresh segment — the suffix a standby tails —
            # always opens by naming who is allowed to write it.
            self._journal.append(journal_io.encode_fence_epoch(self._epoch))

    def _on_journal_storage_event(self, event: str, detail: Dict) -> None:
        """The WAL's degradation observer: mirror storage health into
        FleetMetrics and the trace. ``journal_storage_error`` fires per
        OSError (retries included); ``journal_degraded`` /
        ``journal_rearmed`` bracket the NON_DURABLE window the
        ``journal_non_durable`` gauge alarms."""
        if event == "journal_storage_error":
            self.metrics.journal_storage_errors += 1
        elif event == "journal_degraded":
            self.metrics.journal_degraded_events += 1
        elif event == "journal_rearmed":
            self.metrics.journal_rearms += 1
        elif event == "journal_checkpoint_failed":
            self.metrics.journal_storage_errors += 1
        self._tracer.on_fleet_event(event, **detail)

    def run(self, max_steps: Optional[int] = None,
            idle_sleep_s: Optional[float] = None) -> None:
        """Drive :meth:`step` until every fleet handle settles (or the
        budget runs out). ``idle_sleep_s`` throttles the poll loop;
        the default (``None``) auto-selects — 2 ms when any replica is
        a self-driving process (a tight non-blocking pipe poll would
        steal a whole core from the very workers it waits on), 0 for
        purely in-process fleets, where stepping IS the work."""
        if idle_sleep_s is None:
            idle_sleep_s = (0.002 if any(
                hasattr(s.driver, "beat_age_s") for s in self._slots)
                else 0.0)
        steps = 0
        while self.has_work:
            emitted = self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            if emitted == 0 and idle_sleep_s > 0:
                time.sleep(idle_sleep_s)

    def _forward_cancels(self, slot: _ReplicaSlot) -> None:
        for rid, fh in list(slot.assigned.items()):
            if fh.cancelled and not fh.done:
                try:
                    if self._epoch is not None:
                        slot.driver.cancel(rid, epoch=self._epoch)
                    else:
                        slot.driver.cancel(rid)
                except EpochFenced as e:
                    self._count_fenced(e)
                except (ReplicaDied, OSError):
                    pass  # death handling will settle it

    def _apply_events(self, slot: _ReplicaSlot,
                      events: List[Dict[str, object]]) -> int:
        tokens = 0
        now = self._clock()
        for ev in events:
            kind = ev.get("ev")
            if kind == "tokens":
                for rid, toks in ev["toks"]:
                    if toks and rid in self._hedge_peer:
                        # First result wins: this copy takes the
                        # stream, the peer is cancelled and unbound
                        # (its later events miss `_by_rid` below).
                        self._settle_hedge(rid)
                    fh = self._by_rid.get(rid)
                    if fh is None:
                        continue
                    if fh.ttft_s is None and toks:
                        fh.ttft_s = now - fh.arrival_s
                        if self._dtrace is not None:
                            self._dtrace.on_first_token(rid, fh.ttft_s)
                    if fh.state is RequestState.QUEUED:
                        fh.state = RequestState.RUNNING
                    fh.tokens.extend(int(t) for t in toks)
                    tokens += len(toks)
                    self.metrics.tokens_streamed_by_priority[
                        fh.request.priority.value] += len(toks)
                    if toks and role_of(slot.driver) == "prefill" \
                            and rid not in self._hedge_rids:
                        # First token on a PREFILL slot: prefill is
                        # done, decode has begun in the wrong place —
                        # queue the stream's hand-off (executed after
                        # the slot loop, `fleet/disagg.py`).
                        self._handoff.note(rid)
                    if self._journal is not None:
                        # The emitted-token mirror delta: fsync-BATCHED
                        # (losing a tail is safe — replay regenerates
                        # the identical tokens). Hedge copies journal
                        # under the PRIMARY rid their admit used.
                        self._journal.append(journal_io.encode_tokens(
                            self._hedge_alias.get(rid, rid),
                            list(toks)))
            elif kind == "finish":
                rid = ev["rid"]
                if rid in self._hedge_peer:
                    # Only a SUCCESSFUL first result wins the race: a
                    # copy that failed/was shed with nothing emitted
                    # must not drag down the healthy peer — hedging
                    # can never turn one admission into a failure it
                    # would not otherwise have. The failed copy is
                    # quietly unlinked; the peer keeps the stream.
                    if ev.get("state") == RequestState.FAILED.value \
                            and not ev.get("n_tokens"):
                        self._abandon_hedge_copy(rid)
                        slot.assigned.pop(rid, None)
                        continue
                    self._settle_hedge(rid)
                fh = self._by_rid.pop(rid, None)
                slot.assigned.pop(rid, None)
                if fh is None:
                    continue
                # Adopt the ENGINE-measured TTFT when the driver
                # reports one: the router-side stamp above measures
                # first-token EVENT ARRIVAL, which under load includes
                # however long the router spent between pipe pumps —
                # loop latency, not scheduling quality. The engine's
                # number (queue wait + prefill, on the replica's own
                # clock) is what the SLO machinery actually controls
                # and what the per-priority dashboards read.
                if ev.get("ttft_s") is not None:
                    fh.ttft_s = float(ev["ttft_s"])
                fh.state = RequestState(ev["state"])
                fh.finish_reason = (FinishReason(ev["reason"])
                                    if ev.get("reason") else None)
                fh.finish_s = now
                if fh.state is RequestState.FINISHED:
                    self.metrics.requests_finished += 1
                elif fh.state is RequestState.FAILED:
                    self.metrics.requests_failed += 1
                if self._dtrace is not None:
                    self._dtrace.on_finish(
                        rid, fh.state.value,
                        fh.finish_reason.value
                        if fh.finish_reason is not None else None,
                        len(fh.tokens),
                        ttft_s=ev.get("ttft_s"))
                if self._journal is not None:
                    self._journal.append(journal_io.encode_finish(
                        self._hedge_alias.pop(rid, rid),
                        fh.state.value,
                        fh.finish_reason.value
                        if fh.finish_reason is not None else None))
                else:
                    self._hedge_alias.pop(rid, None)
            elif kind == "fenced":
                # A fire-and-forget command (cancel, a restore chunk)
                # bounced off the worker's fence floor asynchronously.
                # The replica is healthy — this router is just not the
                # writer any more; count it, the chaos referee reads
                # the counter as the split-brain discriminant.
                self._count_fenced(EpochFenced(
                    slot.replica_id, int(ev.get("epoch", -1)),
                    int(ev.get("highest", -1))))
        self.metrics.tokens_streamed += tokens
        return tokens

    # --------------------------------------------------------- resilience
    def _wire_entry(self, fh: FleetHandle) -> Dict[str, object]:
        """A drain wire entry from the router's own mirror (the hard-
        kill fallback: prompt + emitted tokens replay)."""
        return drain_io.encode_handle(fh, self._clock())

    def _on_death(self, slot: _ReplicaSlot, cause: BaseException) -> None:
        if slot.state is ReplicaLifecycle.DEAD:
            return
        now = self._clock()
        slot.state = ReplicaLifecycle.DEAD
        slot.breaker.trip(now)
        if self._gray is not None:
            self._gray.forget(slot.replica_id)  # dead outranks gray
        # Its adapter pool died with it: drop only ITS homes, so the
        # next same-adapter submission re-homes wherever it lands.
        self._adapter_homes = {name: home for name, home
                               in self._adapter_homes.items()
                               if home is not slot}
        self.metrics.replica_down_events += 1
        self._tracer.on_fleet_event(
            "replica_down", replica=slot.replica_id,
            cause=type(cause).__name__, in_flight=len(slot.assigned))
        # Mirror summary for the postmortem bundle BEFORE _evacuate
        # clears the assignment map.
        mirrors = ([[rid, len(fh.tokens)]
                    for rid, fh in slot.assigned.items()]
                   if self._dtrace is not None else None)
        # Live migration: the replica's own drain snapshot when it can
        # still produce one (`serve/drain.py` wire format, rid-tagged);
        # otherwise rebuild from the router mirrors — same format, the
        # prompt+emitted-token replay r08 pinned in-engine.
        migrate, leftovers, via = self._evacuate(slot, now)
        if self._dtrace is not None:
            self._harvest_flight(slot, mirrors)
        self._distribute(migrate, via)
        if leftovers:
            self._distribute(leftovers, "replay")

    def _harvest_flight(self, slot: _ReplicaSlot,
                        mirrors: Optional[List[List[int]]]) -> None:
        """Post-mortem span recovery for a dead replica: flush whatever
        the driver can still surface in-process, then read the crash-
        durable flight-recorder segments off disk (`obs/flightrec.py`)
        — the SIGKILL path, where the worker never shipped its final
        batches — and leave a postmortem bundle beside the WAL and
        drain mirrors for the operator runbook (docs/OPERATIONS.md)."""
        flush = getattr(slot.driver, "flush_spans", None)
        if flush is not None:
            try:
                flush()
            except Exception:  # noqa: BLE001 - dead replica, best effort
                pass
        self._collect_spans(slot)
        frdir = getattr(slot.driver, "flightrec_dir", None)
        if frdir is None:
            return
        records = flightrec_io.harvest(str(frdir))
        spans = [r for r in records if r.get("kind") == "span"]
        if spans:
            self._dtrace.add_replica_records(slot.replica_id, spans,
                                             source="flightrec")
        flightrec_io.write_postmortem(str(frdir), {
            "replica": slot.replica_id,
            "harvested_records": len(records),
            "harvested_spans": len(spans),
            "mirrors": mirrors or [],
        })

    def _evacuate(self, slot: _ReplicaSlot, now: float) -> Tuple[
            List[Tuple[int, Dict, FleetHandle]],
            List[Tuple[int, Dict, FleetHandle]], str]:
        """The capture-and-adopt half shared by death handling and
        scale-down retirement: snapshot the replica (drain if it can,
        router mirrors if not), adopt snapshot tokens into the fleet
        handles, and return ``(migrate, leftovers, via)`` ready for
        :meth:`_distribute` — the slot's assignment map is cleared."""
        pairs = self._capture(slot, now)
        via = "drain" if pairs is not None else "replay"
        if pairs is None:
            pairs = [(rid, self._wire_entry(fh))
                     for rid, fh in slot.assigned.items() if not fh.done]
        migrate: List[Tuple[int, Dict, FleetHandle]] = []
        for rid, entry in pairs:
            if rid in self._hedge_peer:
                # A hedged copy leaving with its host is not migrated:
                # the surviving peer IS the stream — settle the pair
                # in its favor instead of reviving a duplicate race.
                self._settle_hedge(self._hedge_peer[rid])
                continue
            fh = self._by_rid.get(rid)
            if fh is None or fh.done:
                continue
            etoks = [int(t) for t in entry.get("tokens", [])]
            # The entry is authoritative: tokens the engine emitted in
            # its dying step may not have streamed yet — adopt them so
            # the restored stream and the caller's view agree exactly.
            # A divergence means the snapshot and the caller's stream
            # disagree: fail THAT request terminally rather than abort
            # the whole death-handling pass mid-migration (and never
            # restore a stream we know would not be token-exact).
            if etoks[:len(fh.tokens)] != fh.tokens:
                self._tracer.on_fleet_event(
                    "migration_token_mismatch", request_id=rid)
                self._fail_handle(fh, rid)
                continue
            if fh.ttft_s is None and len(etoks) > len(fh.tokens):
                fh.ttft_s = now - fh.arrival_s
            fh.tokens.extend(etoks[len(fh.tokens):])
            migrate.append((rid, entry, fh))
        leftovers = self._mirror_leftovers(slot, {rid for rid, _ in pairs})
        slot.assigned.clear()
        return migrate, leftovers, via

    def _capture(self, slot: _ReplicaSlot,
                 now: float) -> Optional[List[Tuple[int, Dict]]]:
        """The capture discipline shared by death handling and graceful
        drain: ask the driver for its snapshot (None = hard kill / no
        snapshot possible), then fold whatever backlog the driver read
        before or while capturing into the mirrors — finish events
        settle their handles (so done streams are not migrated), token
        events freshen the replay mirrors — BEFORE the caller judges
        which entries still need moving."""
        try:
            pairs = slot.driver.drain_entries(now)
        except Exception:  # noqa: BLE001 - incl. ReplicaDied: hard kill
            pairs = None
        take = getattr(slot.driver, "take_pending", None)
        if take is not None:
            try:
                self._apply_events(slot, take())
            except Exception:  # noqa: BLE001 - backlog is best-effort
                pass
        return pairs

    def _mirror_leftovers(self, slot: _ReplicaSlot, in_snapshot) -> List[
            Tuple[int, Dict, FleetHandle]]:
        """Requests assigned to the replica but absent from its snapshot
        — e.g. a migration restore the worker never read off its pipe —
        must not be silently dropped: rebuild them from the router
        mirrors (the replay wire entry), same rule for death and drain."""
        return [(rid, self._wire_entry(fh), fh)
                for rid, fh in slot.assigned.items()
                if rid not in in_snapshot and not fh.done]

    def _distribute(self, migrate: List[Tuple[int, Dict, FleetHandle]],
                    via: str) -> None:
        if not migrate:
            return
        survivors = [s for s in self._slots if s.available]
        if not survivors:
            if self._can_ever_recover():
                self._orphans.extend((rid, fh) for rid, _, fh in migrate)
                # Count each REQUEST once, ever: a flapping revive
                # (probe succeeds, restore target dies, re-park) would
                # otherwise inflate the counter the runbook keys manual
                # intervention off to M*K for K real requests.
                fresh = [fh for _, _, fh in migrate
                         if not fh._orphan_counted]
                for fh in fresh:
                    fh._orphan_counted = True
                self.metrics.requests_orphaned += len(fresh)
                self._tracer.on_fleet_event("orphaned", n=len(migrate))
            else:
                for rid, _, fh in migrate:
                    self._fail_handle(fh, rid)
            return
        self.metrics.migrations += 1
        per_target: Dict[int, List[Tuple[int, Dict, FleetHandle]]] = {}
        # Least-loaded-first round robin keeps the redistributed load
        # balanced without a second routing pass per request.
        ordered = sorted(survivors, key=lambda s: s.load)
        for i, item in enumerate(migrate):
            target = ordered[i % len(ordered)]
            per_target.setdefault(target.replica_id, []).append(item)
        by_id = {s.replica_id: s for s in self._slots}
        for tid, items in per_target.items():
            target = by_id[tid]
            try:
                pairs = [(rid, entry) for rid, entry, _ in items]
                extra: Dict[str, object] = {}
                if self._epoch is not None:
                    extra["epoch"] = self._epoch
                if self._dtrace is not None:
                    traces = {}
                    for rid, _entry, _fh in items:
                        self._dtrace.on_restore(rid, target.replica_id,
                                                via)
                        traces[rid] = self._dtrace.context_for(rid)
                    target.driver.restore(pairs, traces=traces, **extra)
                else:
                    target.driver.restore(pairs, **extra)
            except EpochFenced as e:
                # A fenced restore means WE are the deposed router —
                # the new primary owns these streams now. Do not park
                # them as orphans (that would double-drive on revive).
                self._count_fenced(e)
                raise
            except (ReplicaDied, KillPoint) as e:
                self._on_death(target, e)
                # Re-distribute this shard over whoever remains — from
                # FRESH mirror entries, not the originals: the target
                # may have applied part of a chunked restore and
                # streamed tokens past the old snapshot before dying
                # (_on_death just folded that backlog into the
                # mirrors), so restoring a stale entry would re-emit
                # tokens the caller already holds.
                retry = [(rid, self._wire_entry(fh), fh)
                         for rid, _, fh in items if not fh.done]
                self._distribute(retry, "replay")
                continue
            for rid, _, fh in items:
                fh.replica_id = tid
                fh.migrations += 1
                target.assigned[rid] = fh
                self._by_rid[rid] = fh
                target.shadow.observe(
                    list(fh.request.prompt),
                    max_blocks=self._affinity_blocks)
                if fh.session is not None:
                    self._session_pin(fh.session, target)
                if self._journal is not None:
                    # The re-bind is a ledger event too: recovery
                    # ignores it (fresh fleet, fresh routing) but the
                    # decision history stays auditable.
                    self._journal.append(journal_io.encode_route(
                        rid, tid, "migration"))
            self.metrics.requests_migrated += len(items)
            if via == "drain":
                self.metrics.migrated_via_drain += len(items)
            else:
                self.metrics.migrated_via_replay += len(items)
            self._tracer.on_fleet_event(
                "migration", to_replica=tid, n=len(items), via=via)

    def _fail_handle(self, fh: FleetHandle,
                     rid: Optional[int] = None) -> None:
        fh.state = RequestState.FAILED
        fh.finish_reason = FinishReason.ERROR
        fh.finish_s = self._clock()
        self.metrics.requests_failed += 1
        if self._dtrace is not None and rid is not None:
            self._dtrace.on_finish(rid, fh.state.value,
                                   fh.finish_reason.value, len(fh.tokens))
        # Drop the routing entry too: a terminally-failed handle left in
        # `_by_rid` is scanned by every subsequent `has_work` forever —
        # a slow leak across total-outage windows on a long-lived router.
        if rid is not None:
            self._by_rid.pop(rid, None)

    def _can_ever_recover(self) -> bool:
        return self._respawn and any(
            getattr(s.driver, "can_respawn", False) for s in self._slots)

    def _maybe_probe(self, slot: _ReplicaSlot, now: float) -> None:
        if not (self._respawn and getattr(slot.driver, "can_respawn",
                                          False)):
            return
        if not slot.breaker.probe_due(now):
            return
        slot.breaker.begin_probe(now)
        self.metrics.probes += 1
        try:
            slot.driver.respawn()
            slot.driver.warmup()
        except Exception as e:  # noqa: BLE001 - probe failed, stay open
            self.metrics.probe_failures += 1
            slot.breaker.record_failure(self._clock())
            self._tracer.on_fleet_event(
                "probe_failed", replica=slot.replica_id,
                error=type(e).__name__)
            return
        slot.breaker.record_success(self._clock())
        slot.state = ReplicaLifecycle.UP
        slot.reset_shadow()  # the fresh engine's radix cache is empty
        slot.wire_base = None  # fresh transport: counters restart at 0
        if self._gray is not None:
            self._gray.forget(slot.replica_id)  # fresh baseline too
        self.metrics.replica_up_events += 1
        self._tracer.on_fleet_event("replica_up", replica=slot.replica_id)
        if self._orphans:
            orphans, self._orphans = self._orphans, []
            self._distribute(
                [(rid, self._wire_entry(fh), fh) for rid, fh in orphans
                 if not fh.done],
                "replay")

    # ----------------------------------------------------- crash recovery
    @classmethod
    def recover(cls, journal_dir: str, replicas: Sequence[object], *,
                journal=None, **router_kw
                ) -> Tuple["FleetRouter", Dict[int, FleetHandle]]:
        """Rebuild a crashed router from its WAL (ISSUE 14): the
        control-plane answer to a SIGKILL with no drain possible.

        ``replicas`` are FRESH drivers (fresh engines / re-spawned
        worker processes — the old ones died with the old router);
        ``journal`` defaults to a new :class:`~.journal.RouterJournal`
        over the same directory, which the recovered router keeps
        appending to. Every stream that was durably admitted and had
        not finished re-enters through the r11 mirror-replay path —
        the same contract hard-killed REPLICAS already recover by, so
        the streams continue token-exactly — and the first act of the
        recovered router is a fresh checkpoint: recovery is the
        snapshot path's second "normal case", not a special one.

        Returns ``(router, {rid: FleetHandle})`` — the caller's old
        handles died with the old process; these are their reborn
        equivalents, carrying the full mirrored stream so far.
        """
        entries, next_rid = journal_io.read_state(journal_dir)
        if journal is None:
            journal = journal_io.RouterJournal(journal_dir)
        router = cls(replicas, journal=journal, **router_kw)
        router._rid_counter = max(router._rid_counter, int(next_rid))
        now = router._clock()
        migrate: List[Tuple[int, Dict, FleetHandle]] = []
        for rid, entry in sorted(entries.items()):
            fh = router._handle_from_entry(entry, now)
            router._by_rid[rid] = fh
            migrate.append((rid, entry, fh))
        router._distribute(migrate, "replay")
        router._journal_checkpoint()
        router._tracer.on_fleet_event(
            "router_recovered", revived=len(migrate),
            replicas=len(router._slots))
        return router, {rid: fh for rid, _, fh in migrate}

    def _handle_from_entry(self, entry: Dict,
                           now: float) -> FleetHandle:
        """A reborn :class:`FleetHandle` from a journal mirror entry
        (the drain wire shape plus the router-level ``session``)."""
        req = Request(
            prompt=[int(t) for t in entry.get("prompt", [])],
            max_new_tokens=int(entry.get("max_new_tokens", 0)),
            sampling=drain_io.decode_sampling(entry.get("sampling")),
            deadline_s=entry.get("deadline_s"),
            priority=Priority(entry.get(
                "priority", Priority.INTERACTIVE.value)),
            adapter=entry.get("adapter"),
            constraint=entry.get("constraint"))
        fh = FleetHandle(
            req,
            arrival_s=now - float(entry.get("elapsed_s") or 0.0),
            session=entry.get("session"))
        fh.tokens = [int(t) for t in entry.get("tokens", [])]
        if entry.get("ttft_s") is not None:
            fh.ttft_s = float(entry["ttft_s"])
        return fh

    # ----------------------------------------------------- elastic scaling
    def _new_slot(self, driver) -> _ReplicaSlot:
        ids = [s.replica_id for s in self._slots]
        if driver.replica_id in ids:
            raise ValueError(
                f"replica ids must be unique, got {driver.replica_id} "
                f"already in {ids}")
        # Fleet-wide probe desynchronization (ISSUE 18): a mass-kill
        # must not schedule every replica's HALF_OPEN probe on the
        # same doubling schedule, so each breaker gets subtractive
        # jitter seeded by its replica id — deterministic per replica,
        # divergent across the fleet. An explicit breaker= policy can
        # still pin either knob.
        kw = dict(self._breaker_kw)
        kw.setdefault("jitter_frac", 0.1)
        kw.setdefault("seed", int(driver.replica_id))
        slot = _ReplicaSlot(driver, CircuitBreaker(**kw),
                            self._block_size, self._shadow_capacity,
                            self._shadow_host_capacity)
        slot.breaker.on_transition = self._circuit_observer(slot)
        if self._dtrace is not None:
            # In-process replicas arm their engine tracer here (worker
            # processes arm from their spawn config instead) — covers
            # both the initial fleet and elastic scale-up.
            arm = getattr(driver, "arm_tracing", None)
            if arm is not None:
                arm()
        self._slots.append(slot)
        return slot

    def attach_autoscaler(self, autoscaler) -> None:
        """Wire a :class:`~.autoscaler.FleetAutoscaler` into the step
        cadence: the router pumps replicas, then the controller gets
        one decision tick per round — so every existing entry point
        (``run()``, bench loops, chaos harnesses) drives the control
        loop without a second scheduler."""
        self._autoscaler = autoscaler

    @property
    def autoscaler(self):
        return self._autoscaler

    def scale_up(self, driver) -> None:
        """Add a READY replica driver to the rotation (the elastic
        scale-up mechanism; the autoscaler is the policy deciding when,
        and it spawns/warms the driver CONCURRENTLY before handing it
        here — this call itself never blocks on a warmup). Parked
        orphans re-enter service on the new replica immediately: a
        scale-up during a total outage is also a recovery."""
        if self._closed:
            raise RuntimeError("fleet router is closed")
        slot = self._new_slot(driver)
        self.metrics.scale_up_events += 1
        self._tracer.on_fleet_event(
            "scale_up", replica=slot.replica_id,
            replicas=len(self._slots))
        if self._orphans:
            orphans, self._orphans = self._orphans, []
            self._distribute(
                [(rid, self._wire_entry(fh), fh) for rid, fh in orphans
                 if not fh.done],
                "replay")

    def scale_down(self, replica_id: int) -> int:
        """Retire one replica by LIVE-MIGRATING its queued+running
        streams onto the survivors, then removing it from the rotation
        — zero lost requests by construction: the capture is the same
        drain-snapshot discipline death handling uses (`serve/drain.py`
        wire format; router-mirror replay as the fallback), but taken
        GRACEFULLY, so the snapshot path is the normal case rather than
        the lucky one. Returns the number of requests migrated off the
        victim. Refuses (``ValueError``) when no OTHER available
        replica exists to absorb them — a scale-down must never orphan
        work, that is the whole contract."""
        slot = next((s for s in self._slots
                     if s.replica_id == int(replica_id)), None)
        if slot is None:
            raise ValueError(f"no replica {replica_id} in the fleet")
        survivors = [s for s in self._slots
                     if s is not slot and s.available]
        if not survivors:
            raise ValueError(
                f"refusing to retire replica {replica_id}: no other "
                "available replica to migrate its work onto")
        now = self._clock()
        migrate, leftovers, via = self._evacuate(slot, now)
        slot.state = ReplicaLifecycle.RETIRED
        self._slots.remove(slot)
        if self._gray is not None:
            self._gray.forget(slot.replica_id)
        self._adapter_homes = {name: home for name, home
                               in self._adapter_homes.items()
                               if home is not slot}
        # Sticky sessions pinned here must not keep the retired slot
        # (and, for local replicas, its whole closed engine) alive
        # until LRU eviction: unlike a DEAD slot — which stays in
        # `_slots` awaiting a probe — a retirement is final. Dropped
        # sessions simply re-route by affinity; migration re-pins the
        # in-flight ones to their new replica below.
        for name in [n for n, s in self._sessions.items() if s is slot]:
            del self._sessions[name]
        n_moved = len(migrate) + len(leftovers)
        self.metrics.scale_down_events += 1
        self.metrics.scale_down_migrated += n_moved
        self._tracer.on_fleet_event(
            "scale_down", replica=slot.replica_id, migrated=n_moved,
            via=via, replicas=len(self._slots))
        self._distribute(migrate, via)
        if leftovers:
            self._distribute(leftovers, "replay")
        try:
            slot.driver.close()
        except Exception:  # noqa: BLE001 - retirement is best-effort
            pass
        return n_moved

    # ------------------------------------------------------------ teardown
    def drain(self) -> Dict[str, object]:
        """Graceful fleet-wide drain: every live replica's in-flight
        requests in one `serve/drain.py`-format snapshot (restorable
        into a fresh engine or fleet). The router stops accepting."""
        now = self._clock()
        entries: List[Dict[str, object]] = []
        for slot in self._slots:
            if slot.state is not ReplicaLifecycle.UP:
                continue
            pairs = self._capture(slot, now)
            if pairs is None:
                entries.extend(self._wire_entry(fh)
                               for fh in slot.assigned.values()
                               if not fh.done)
                continue
            in_snapshot = set()
            for rid, entry in pairs:
                in_snapshot.add(rid)
                fh = self._by_rid.get(rid)
                if fh is not None and fh.done:
                    continue  # settled by the backlog applied above
                entries.append(entry)
            entries.extend(
                entry for _, entry, _ in
                self._mirror_leftovers(slot, in_snapshot))
        entries.extend(self._wire_entry(fh) for _, fh in self._orphans
                       if not fh.done)
        self._closed = True
        if self._journal is not None:
            self._journal.commit()
        return {"version": drain_io.SNAPSHOT_VERSION,
                "drained_unix_s": time.time(), "requests": entries}

    def close(self) -> None:
        self._closed = True
        if self._autoscaler is not None:
            self._autoscaler.close()  # an in-flight spawn dies too
        for slot in self._slots:
            try:
                slot.driver.close()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass
        if self._journal is not None:
            try:
                self._journal.close()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass
