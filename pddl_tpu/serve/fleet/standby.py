"""Router high availability: WAL-shipped hot standby with fenced
takeover (ISSUE 20).

The r18 journal made the router's control-plane state durable and r21
made the STORAGE under it untrusted — but recovery stayed cold:
``FleetRouter.recover()`` is an offline restart someone must invoke
(12.2 s median in the r21 campaigns, every stream stalled throughout),
and nothing defends against the failure mode the gray-failure
literature calls the worst one: an alive-yet-partitioned primary that
keeps issuing commands. This module closes both gaps with the
primary/backup discipline production control planes use (Borg;
ZooKeeper/Raft-style leases):

- :class:`WalShipper` — primary side. Hooks the journal's
  ``on_record`` observer and ships EVERY append (NON_DURABLE buffered
  ones included) as an r19 CRC-framed line to a sink, so the standby's
  view is bounded by the wire, not by fsync latency.
- :class:`WalTail` — standby side. Feeds shipped lines through a
  :class:`~.transport.FrameReceiver` (validated, deduplicated,
  re-ordered) and folds the records incrementally into exactly the
  state ``journal.read_state`` would recover: ``{rid: drain entry}``
  mirrors plus ``next_rid``, plus the rid->replica bindings and the
  writer's fencing epoch. Joining mid-stream — or losing frames a
  one-way replication stream can never resend — falls back to a disk
  catch-up from checkpoint+segment (counted: ``standby_catchups``).
- :class:`Lease` / :class:`LeaseKeeper` — file-backed single-writer
  lease. The holder renews on a seeded SUBTRACTIVE jitter schedule
  (the r21 breaker/spawn discipline: jitter only ever fires renewal
  EARLY, so it can never eat the lease's safety margin); a standby
  promotes when the lease lapses. Epochs increment on every change of
  holder — the epoch IS the single-writer token.
- :class:`HotStandby` — ties them together. ``step()`` watches the
  lease and tails the stream; ``promote()`` fences every live replica
  at the new epoch FIRST (a deposed-but-alive primary physically
  cannot double-drive the fleet — workers refuse its stale-epoch
  commands with a typed reject), cancels the stale in-flight streams,
  then rebuilds a :class:`~.router.FleetRouter` over the SAME live
  driver objects (no respawn, no weight reload, no recompile — that is
  the sub-second hot path) and re-enters every unfinished stream
  through the r11 mirror-replay contract, token-exact under fresh
  rids.

Loss-window semantics under r21 storage faults: a primary whose
journal degraded NON_DURABLE still ships every record over the wire,
so a healthy stream loses nothing; if frames are ALSO lost (the
partition case), the window is exactly the fsync-batched token deltas
— whose replay regenerates identical token values, because decoding is
a pure function of (params, prompt, tokens-so-far).
"""

from __future__ import annotations

import json
import os
import random
import time
from typing import Callable, Dict, List, Optional, Tuple

from pddl_tpu.serve.fleet import journal as journal_io
from pddl_tpu.serve.fleet.replica import EpochFenced, ReplicaDied
from pddl_tpu.serve.fleet.router import FleetHandle, FleetRouter
from pddl_tpu.serve.fleet.transport import FrameReceiver, FrameSender, \
    decode_frame, FrameError


class LeaseHeld(RuntimeError):
    """Acquisition refused: another holder's lease has not expired.
    The standby's promotion path treats this as "the primary is alive
    after all" — it keeps tailing instead of splitting the brain."""

    def __init__(self, holder: str, other: str, remaining_s: float):
        self.holder = holder
        self.other = other
        self.remaining_s = float(remaining_s)
        super().__init__(
            f"lease held by {other!r} for another "
            f"{remaining_s:.3f}s (requested by {holder!r})")


class Lease:
    """File-backed single-writer lease: ``{holder, epoch, renewed_s,
    expires_s}`` written atomically (tmp + replace, the checkpoint
    discipline). The EPOCH increments exactly when the holder CHANGES
    — re-acquisition and renewal by the same holder keep it — so two
    routers can never both believe they own the same epoch interval.

    Clocks: ``clock`` must be shared by every contender (the default
    ``time.monotonic`` is per-host — which is the deployment unit here;
    a cross-host lease store would bring its own clock, like every
    lease service does).
    """

    def __init__(self, path: str, *, ttl_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if ttl_s <= 0.0:
            raise ValueError(f"ttl_s must be positive, got {ttl_s}")
        self.path = str(path)
        self.ttl_s = float(ttl_s)
        self._clock = clock

    def read(self) -> Optional[Dict[str, object]]:
        """The current lease body, or None when absent/unreadable (a
        torn write is impossible by construction; a missing file means
        nobody has ever held it)."""
        try:
            with open(self.path) as f:
                body = json.load(f)
        except (OSError, ValueError):
            return None
        return body if isinstance(body, dict) else None

    def _write(self, body: Dict[str, object]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(body, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def acquire(self, holder: str, *, steal: bool = False) -> int:
        """Take (or retake) the lease; returns the epoch now owned.
        Raises :class:`LeaseHeld` when another holder's lease is still
        live — unless ``steal=True``, the operator's forced-failover
        override (the deposed holder is still fenced out by the epoch
        bump, so a steal is rude but never unsafe)."""
        now = self._clock()
        cur = self.read()
        epoch = 0
        if cur is not None:
            epoch = int(cur.get("epoch", 0))
            if str(cur.get("holder")) != holder:
                remaining = float(cur.get("expires_s", 0.0)) - now
                if remaining > 0.0 and not steal:
                    raise LeaseHeld(holder, str(cur.get("holder")),
                                    remaining)
                epoch += 1  # holder change: new single-writer interval
        else:
            epoch = 1  # first holder ever arms epoch 1
        self._write({"holder": str(holder), "epoch": int(epoch),
                     "renewed_s": now, "expires_s": now + self.ttl_s})
        return int(epoch)

    def renew(self, holder: str) -> bool:
        """Extend the expiry iff ``holder`` still owns the lease.
        False means deposed: someone else took over (or the file is
        gone) — the caller must stop acting as primary."""
        cur = self.read()
        if cur is None or str(cur.get("holder")) != holder:
            return False
        now = self._clock()
        cur["renewed_s"] = now
        cur["expires_s"] = now + self.ttl_s
        self._write(cur)
        return True

    def age_s(self) -> Optional[float]:
        """Seconds since the current holder last renewed — the
        ``lease_age_s`` gauge. None when nobody holds it (rendered
        NaN by the exposition)."""
        cur = self.read()
        if cur is None:
            return None
        return max(0.0, self._clock() - float(cur.get("renewed_s", 0.0)))

    def expired(self) -> bool:
        cur = self.read()
        if cur is None:
            return True
        return self._clock() >= float(cur.get("expires_s", 0.0))


class LeaseKeeper:
    """Drives one holder's acquisition + renewal on a seeded-jitter
    schedule (the r21 breaker/spawn discipline). Renewal is scheduled
    every ``renew_every_s`` (default: a third of the TTL) minus a
    SUBTRACTIVE jitter — ``interval *= 1 - jitter_frac * U[0,1)`` —
    so two keepers restarting together desynchronize, yet a jittered
    renewal always lands EARLIER than the unjittered one: jitter can
    never push a renewal past the lease's safety margin."""

    def __init__(self, lease: Lease, holder: str, *,
                 renew_every_s: Optional[float] = None,
                 jitter_frac: float = 0.1,
                 seed: Optional[int] = None):
        if not 0.0 <= jitter_frac < 1.0:
            raise ValueError(
                f"jitter_frac must be in [0, 1), got {jitter_frac}")
        if renew_every_s is None:
            renew_every_s = lease.ttl_s / 3.0
        if not 0.0 < renew_every_s < lease.ttl_s:
            raise ValueError(
                f"renew_every_s must sit inside the TTL "
                f"(0, {lease.ttl_s}), got {renew_every_s}")
        self.lease = lease
        self.holder = str(holder)
        self.renew_every_s = float(renew_every_s)
        self.jitter_frac = float(jitter_frac)
        self._rng = random.Random(seed)
        self._next_renew_s: Optional[float] = None
        self.epoch: Optional[int] = None
        self.renewals = 0
        self.deposed = False

    def _interval_s(self) -> float:
        return self.renew_every_s * (
            1.0 - self.jitter_frac * self._rng.random())

    def acquire(self, *, steal: bool = False) -> int:
        self.epoch = self.lease.acquire(self.holder, steal=steal)
        self.deposed = False
        self._next_renew_s = self.lease._clock() + self._interval_s()
        return self.epoch

    def step(self, now: Optional[float] = None) -> bool:
        """Renew when due. Returns False ONCE the keeper discovers it
        was deposed — the caller (a primary's driver loop) must stop
        commanding the fleet immediately."""
        if self.deposed:
            return False
        if self._next_renew_s is None:
            return True  # never acquired: nothing to keep
        if now is None:
            now = self.lease._clock()
        if now >= self._next_renew_s:
            if not self.lease.renew(self.holder):
                self.deposed = True
                return False
            self.renewals += 1
            self._next_renew_s = now + self._interval_s()
        return True

    def lease_age_s(self) -> Optional[float]:
        return self.lease.age_s()

    def lag_records(self) -> Optional[int]:
        return None  # a primary has no replication lag: gauge NaN


class WalShipper:
    """Primary-side record streaming: ``journal.on_record`` -> one
    CRC-framed line per append, pushed at a sink callable (a pipe
    write, a socket send, or — in tests and the single-host bench —
    the standby's ``feed`` directly). Fire-and-forget: a sink failure
    drops the frame and the standby's disk catch-up covers it; the
    observer must never be able to wedge the primary's append path."""

    def __init__(self, journal, sink: Callable[[bytes], None], *,
                 resend_buffer: int = 512):
        self.sender = FrameSender(resend_buffer=resend_buffer)
        self._sink = sink
        self.shipped = 0
        self.ship_errors = 0
        journal.on_record = self._on_record

    def _on_record(self, seq: int, record: Dict) -> None:
        payload = json.dumps({"seq": int(seq), "record": record},
                             separators=(",", ":")).encode()
        line = self.sender.encode(payload)
        try:
            self._sink(line)
            self.shipped += 1
        except Exception:  # noqa: BLE001 - replication is best-effort;
            self.ship_errors += 1  # durability lives in the journal


class WalTail:
    """Standby-side fold of the replicated record stream into the
    exact state ``journal.read_state`` recovers: ``entries`` ({rid:
    drain-format mirror entry} for every admitted-unfinished stream),
    ``next_rid``, ``bindings`` (rid -> last routed replica id), and
    ``primary_epoch`` (the newest ``epoch`` record — who is allowed to
    be writing this WAL). Records are deduplicated by the JOURNAL
    sequence, so the live stream and a disk catch-up can overlap
    freely."""

    def __init__(self, journal_dir: str, *,
                 gap_feeds: int = 8, first_seq: int = 1):
        self.journal_dir = str(journal_dir)
        self._receiver = FrameReceiver(first_seq=first_seq)
        self._gap_feeds = int(gap_feeds)
        self._gap_streak = 0
        self.entries: Dict[int, Dict] = {}
        self._finished: set = set()
        self.bindings: Dict[int, int] = {}
        self.next_rid = 0
        self.covered_seq = 0     # newest journal seq folded
        self.last_seen_seq = 0   # newest journal seq OBSERVED (gauge)
        self.primary_epoch: Optional[int] = None
        self.records_folded = 0
        self.catchups = 0

    # ------------------------------------------------------------- fold
    def _fold(self, seq: int, record: Dict) -> None:
        seq = int(seq)
        if seq <= self.covered_seq:
            return  # already folded (catch-up / duplicate overlap)
        # Jumping a hole here is deliberate: on a one-way stream the
        # missing records are either on disk (the next catch-up folds
        # them — it refolds from the checkpoint wholesale) or gone
        # with a NON_DURABLE primary, in which case they were token
        # deltas the r11 replay regenerates identically.
        self.covered_seq = seq
        self.last_seen_seq = max(self.last_seen_seq, seq)
        self.records_folded += 1
        kind = record.get("rec")
        rid = int(record.get("rid", -1))
        self.next_rid = max(self.next_rid, rid + 1)
        if kind == "admit" and rid not in self._finished:
            entry = {k: record.get(k) for k in
                     ("prompt", "max_new_tokens", "sampling",
                      "deadline_s", "priority", "adapter", "constraint")}
            entry["tokens"] = []
            entry["elapsed_s"] = 0.0
            entry["ttft_s"] = None
            entry["session"] = record.get("session")
            self.entries[rid] = entry
        elif kind == "tokens" and rid in self.entries:
            self.entries[rid]["tokens"] = (
                list(self.entries[rid].get("tokens", []))
                + [int(t) for t in record.get("toks", [])])
        elif kind == "finish":
            self._finished.add(rid)
            self.entries.pop(rid, None)
            self.bindings.pop(rid, None)
        elif kind in ("route", "handoff"):
            self.bindings[rid] = int(record.get("replica", -1))
        elif kind == "epoch":
            self.primary_epoch = int(record.get("epoch", 0))

    # ------------------------------------------------------------- wire
    def feed(self, line: bytes) -> int:
        """One shipped line in; the number of records folded out. A
        gap that persists across ``gap_feeds`` consecutive feeds (a
        dropped frame no one can resend) triggers a disk catch-up."""
        before = self.records_folded
        # Track the newest seq OBSERVED even when delivery is stalled
        # behind a gap — it is what the lag gauge measures against.
        try:
            _, raw = decode_frame(line.rstrip(b"\n"))
            peek = json.loads(raw)
            self.last_seen_seq = max(self.last_seen_seq,
                                     int(peek.get("seq", 0)))
        except (FrameError, ValueError):
            pass
        for payload in self._receiver.feed(line.rstrip(b"\n")):
            try:
                body = json.loads(payload)
            except ValueError:
                continue
            self._fold(int(body.get("seq", 0)), body.get("record") or {})
        if self._receiver.has_gap:
            self._gap_streak += 1
            if self._gap_streak >= self._gap_feeds:
                self.catch_up()
        else:
            self._gap_streak = 0
        return self.records_folded - before

    def resync(self, first_seq: int) -> None:
        """Re-point the FRAME sequence space (a standby attaching to a
        shipper that already sent frames). Journal-seq dedup makes the
        record fold immune to where the frame numbering starts."""
        self._receiver = FrameReceiver(first_seq=first_seq)
        self._gap_streak = 0

    # ------------------------------------------------------------- disk
    def catch_up(self) -> int:
        """Refold from checkpoint+segment (the join path, and the heal
        for wire gaps / NON_DURABLE backlogs). Wholesale: disk is the
        durable truth up to its tip, and any fresher wire-only state
        is re-applied on top by seq dedup — first from the frames a
        gap left buffered in the receiver, then by the live feed."""
        self.catchups += 1
        entries, next_rid = journal_io.read_state(self.journal_dir)
        self.entries = entries
        self._finished = set()
        self.next_rid = max(self.next_rid, int(next_rid))
        disk_tip = 0
        for name in ("wal.prev.log", "wal.log"):
            path = os.path.join(self.journal_dir, name)
            for seq, record in journal_io.iter_wal_records(path):
                disk_tip = max(disk_tip, int(seq))
                kind = record.get("rec")
                if kind in ("route", "handoff"):
                    rid = int(record.get("rid", -1))
                    if rid in self.entries:
                        self.bindings[rid] = int(
                            record.get("replica", -1))
                elif kind == "finish":
                    self._finished.add(int(record.get("rid", -1)))
                elif kind == "epoch":
                    self.primary_epoch = int(record.get("epoch", 0))
        cp = journal_io.load_checkpoint(self.journal_dir)
        if cp is not None:
            disk_tip = max(disk_tip, int(cp.get("covered_seq", 0)))
        self.covered_seq = max(self.covered_seq, disk_tip)
        self.last_seen_seq = max(self.last_seen_seq, self.covered_seq)
        self.bindings = {rid: b for rid, b in self.bindings.items()
                         if rid in self.entries}
        # Frames stranded behind the unhealable gap: newer than disk
        # iff the primary was NON_DURABLE — fold them, dedup does the
        # rest.
        for _, payload in self._receiver.drain_pending():
            try:
                body = json.loads(payload)
            except ValueError:
                continue
            self._fold(int(body.get("seq", 0)), body.get("record") or {})
        self._gap_streak = 0
        return self.covered_seq

    def lag_records(self) -> int:
        """Journal records observed on the wire but not yet folded —
        the ``standby_lag_records`` gauge (0 = fully caught up)."""
        return max(0, self.last_seen_seq - self.covered_seq)


class HotStandby:
    """A warm second router: tails the primary's WAL, watches the
    lease, and takes over the SAME live replica drivers inside the
    detection budget when the lease lapses.

    Args:
      journal_dir: the primary's journal directory (shared storage —
        also where the promoted router keeps journaling).
      replicas: the LIVE driver objects (``LocalReplica`` /
        ``ProcessReplica``) the primary is commanding. Takeover
        re-binds these — no respawn, no weight reload, no recompile.
      lease: the shared :class:`Lease`; ``holder`` names this standby.
      router_kw / journal_kw: forwarded to the promoted
        :class:`FleetRouter` / :class:`~.journal.RouterJournal`.
      jitter_frac / seed: the keeper's renewal jitter (post-promotion
        this standby becomes the renewing primary).
    """

    def __init__(self, journal_dir: str, replicas, *, lease: Lease,
                 holder: str = "standby",
                 router_kw: Optional[Dict] = None,
                 journal_kw: Optional[Dict] = None,
                 jitter_frac: float = 0.1, seed: Optional[int] = None,
                 gap_feeds: int = 8):
        self.journal_dir = str(journal_dir)
        self.replicas = list(replicas)
        self.lease = lease
        self.holder = str(holder)
        self.keeper = LeaseKeeper(lease, self.holder,
                                  jitter_frac=jitter_frac, seed=seed)
        self.tail = WalTail(journal_dir, gap_feeds=gap_feeds)
        self._router_kw = dict(router_kw or {})
        self._journal_kw = dict(journal_kw or {})
        self.router: Optional[FleetRouter] = None
        self.promoted = False
        # Join = the first catch-up: fold whatever checkpoint+segment
        # already hold so the live stream only has to carry the suffix.
        self.tail.catch_up()

    # ------------------------------------------------------------ wiring
    def feed(self, line: bytes) -> None:
        """The shipper's sink (or a pipe pump's per-line callback)."""
        self.tail.feed(line)

    def attach(self, shipper: WalShipper) -> None:
        """In-process convenience: point ``shipper`` at this standby
        and align the frame sequence space with what it already sent
        (the mid-stream join; history comes from the disk catch-up
        the constructor already ran)."""
        self.tail.resync(shipper.sender.last_seq + 1)
        shipper._sink = self.feed

    # ---------------------------------------------------------- watching
    def lease_age_s(self) -> Optional[float]:
        return self.lease.age_s()

    def lag_records(self) -> Optional[int]:
        return self.tail.lag_records()

    def step(self, now: Optional[float] = None
             ) -> Optional[Tuple[FleetRouter, Dict[int, FleetHandle]]]:
        """One watch round: keep the post-promotion lease renewed, or
        — while still a standby — promote the moment the primary's
        lease lapses. Returns the ``(router, handles)`` pair ONCE, on
        the round that promoted; None otherwise."""
        if self.promoted:
            self.keeper.step(now)
            return None
        if not self.lease.expired():
            return None
        try:
            return self.promote()
        except LeaseHeld:
            return None  # raced another standby: keep tailing

    # --------------------------------------------------------- promotion
    def promote(self, *, steal: bool = False
                ) -> Tuple[FleetRouter, Dict[int, FleetHandle]]:
        """Fenced takeover. Order matters:

        1. Acquire the lease — the epoch bumps (holder change).
        2. FENCE every live replica at the new epoch. From this line
           on, the deposed primary's commands are typed rejects: it
           cannot admit, cancel, or restore anything, so the state we
           are about to rebuild from cannot be mutated under us.
        3. Final disk catch-up: everything the primary durably wrote
           up to the fence (its post-fence appends can only be flush
           stragglers for streams we are about to replay anyway).
        4. Cancel the stale in-flight rids (new epoch) — the streams
           re-enter under fresh rids; the old copies must not keep
           burning slots or emitting events.
        5. Rebuild a :class:`FleetRouter` over the SAME driver
           objects + a fresh journal over the same directory, arm the
           epoch, and mirror-replay every unfinished stream (r11
           contract: token-exact continuation, zero recompiles).

        Returns ``(router, {old_rid: FleetHandle})`` — handles keyed
        by the PRIMARY's rids, so callers correlate reborn streams
        with the ones they were awaiting.
        """
        epoch = self.keeper.acquire(steal=steal)
        fenced = 0
        for driver in self.replicas:
            try:
                driver.fence(epoch)
                fenced += 1
            except (ReplicaDied, EpochFenced, OSError):
                continue  # dead: the router's probe loop owns it now
        self.tail.catch_up()
        stale = sorted(self.tail.entries)
        by_id = {d.replica_id: d for d in self.replicas}
        for rid in stale:
            targets = ([by_id[self.tail.bindings[rid]]]
                       if self.tail.bindings.get(rid) in by_id
                       else self.replicas)
            for driver in targets:
                try:
                    driver.cancel(rid, epoch=epoch)
                except (ReplicaDied, EpochFenced, OSError):
                    continue
        journal = journal_io.RouterJournal(self.journal_dir,
                                           **self._journal_kw)
        router = FleetRouter(self.replicas, journal=journal,
                             **self._router_kw)
        router.set_epoch(epoch)
        router._rid_counter = max(router._rid_counter,
                                  int(self.tail.next_rid))
        now = router._clock()
        migrate: List[Tuple[int, Dict, FleetHandle]] = []
        handles: Dict[int, FleetHandle] = {}
        for old_rid in stale:
            entry = self.tail.entries[old_rid]
            fh = router._handle_from_entry(entry, now)
            rid = router._new_rid()
            router._by_rid[rid] = fh
            migrate.append((rid, entry, fh))
            handles[old_rid] = fh
        router._distribute(migrate, "replay")
        router._journal_checkpoint()
        router.metrics.takeovers += 1
        router.metrics.standby_catchups += self.tail.catchups
        router.ha = self  # the exposition's lease/lag gauge surface
        router._tracer.on_fleet_event(
            "takeover", epoch=epoch, revived=len(migrate),
            fenced_replicas=fenced, catchups=self.tail.catchups)
        self.router = router
        self.promoted = True
        return router, handles
