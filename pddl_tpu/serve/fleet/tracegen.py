"""Seeded scenario-diversity trace generator for fleet-scale replay.

The committed benches each invented their own workload shape (Poisson
for r06/r11, one bursty multi-turn schedule for r12); none can answer
"is this change better FOR PRODUCTION", because production traffic has
structure those shapes miss. This module is the one generator the
replay harness (`fleet/replay.py`) and the autoscale bench leg feed on,
with the three structures that matter baked in and SEEDED (every trace
is reproducible from its arguments):

- **Diurnal load curve.** Session arrivals follow a sinusoidal
  intensity with a configurable peak:trough ratio over a configurable
  number of periods — the day/night swing that makes static capacity
  either waste the trough or brown out the peak, i.e. exactly the
  regime an autoscaler is judged in. Arrival times come from
  inverse-CDF sampling of the integrated intensity, so the curve is
  exact, not a binned approximation.
- **Heavy-tail session mix, fitted from the r12 trace.** Multi-turn
  sessions over shared system prompts: the conversation grows per
  turn, think time is exponential, and output lengths draw from the
  bounded Pareto the r12 schedule used (``base + pareto(tail)*scale``,
  capped) — most replies short, a heavy tail of long ones. Priorities
  split interactive/batch/best_effort by a configurable mix, the
  interactive class deadlined.
- **Tenant/adapter popularity skew.** Sessions optionally carry a LoRA
  adapter (`serve/tenant/`) drawn Zipf-style over the adapter list —
  a few hot tenants, a long cold tail — which is what exercises
  adapter affinity and pool churn the way a real multi-tenant fleet
  sees them.

Events are plain dicts (``t``/``session``/``prompt``/``new_tokens``/
``priority``/``deadline_s``/``adapter``) on an absolute timeline of
``duration_s`` seconds, ready for :func:`~.replay.replay_trace`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pddl_tpu.serve.request import Priority


def diurnal_intensity(t, duration_s: float, *, periods: float = 2.0,
                      peak_to_trough: float = 6.0):
    """Relative arrival intensity at time ``t`` (array-ok): a sinusoid
    with mean 1 whose max/min ratio is ``peak_to_trough``, starting at
    the trough (the trace opens in the quiet hours, so an autoscaled
    fleet demonstrably STARTS small)."""
    if peak_to_trough < 1.0:
        raise ValueError(
            f"peak_to_trough must be >= 1, got {peak_to_trough}")
    a = (peak_to_trough - 1.0) / (peak_to_trough + 1.0)
    phase = 2.0 * np.pi * periods * np.asarray(t) / duration_s
    return 1.0 + a * np.sin(phase - np.pi / 2.0)


def _arrival_times(rng, n: int, duration_s: float, periods: float,
                   peak_to_trough: float) -> np.ndarray:
    """``n`` arrival times on [0, duration_s] following the diurnal
    curve, by inverse-CDF sampling over the integrated intensity."""
    grid = np.linspace(0.0, duration_s, 4096)
    lam = diurnal_intensity(grid, duration_s, periods=periods,
                            peak_to_trough=peak_to_trough)
    cdf = np.cumsum(lam)
    cdf = cdf / cdf[-1]
    return np.interp(rng.random(n), cdf, grid)


def diurnal_trace(n_requests: int, vocab: int, seed: int, *,
                  duration_s: float = 120.0,
                  periods: float = 2.0,
                  peak_to_trough: float = 6.0,
                  n_system_prompts: int = 4,
                  prompt_base: int = 16, prompt_cap: int = 60,
                  priority_mix: Tuple[float, float, float] =
                  (0.35, 0.15, 0.50),
                  interactive_deadline_s: Optional[float] = 8.0,
                  adapters: Optional[Sequence[str]] = None,
                  adapter_skew: float = 1.1,
                  adapter_frac: float = 0.75,
                  max_turns: int = 3,
                  think_time_s: float = 0.8,
                  new_tokens_base: int = 4, new_tokens_scale: float = 4.0,
                  new_tokens_tail: float = 1.3, new_tokens_cap: int = 48,
                  ) -> Tuple[List[Dict[str, object]], float]:
    """The scaled replay trace: ``(events, mean_new_tokens)``.

    Exactly ``n_requests`` events (turns), sorted by time over
    ``duration_s`` seconds. ``priority_mix`` is the
    interactive/batch/best_effort session split (best_effort is the
    remainder — the sheddable bulk a brownout eats first). With
    ``adapters`` given, ``adapter_frac`` of sessions carry one, chosen
    with Zipf(``adapter_skew``) popularity; sessions keep their tenant
    across turns (tenancy is a property of the caller, not the turn).
    """
    if sum(priority_mix[:2]) > 1.0:
        raise ValueError(f"priority_mix fractions exceed 1: "
                         f"{priority_mix}")
    rng = np.random.default_rng(seed)
    sys_prompts = [rng.integers(0, vocab, size=prompt_base)
                   for _ in range(n_system_prompts)]
    adapter_p = None
    if adapters:
        ranks = np.arange(1, len(adapters) + 1, dtype=np.float64)
        adapter_p = ranks ** -float(adapter_skew)
        adapter_p /= adapter_p.sum()
    events: List[Dict[str, object]] = []
    s = 0
    # Heavy-tail turn counts mean ~2 events/session at the default
    # max_turns; oversample sessions, then truncate to n_requests.
    while len(events) < n_requests:
        n_sessions = max(8, (n_requests - len(events)) // 2)
        starts = np.sort(_arrival_times(rng, n_sessions, duration_s,
                                        periods, peak_to_trough))
        for t0 in starts:
            s += 1
            r = rng.random()
            pr = (Priority.INTERACTIVE if r < priority_mix[0]
                  else Priority.BATCH
                  if r < priority_mix[0] + priority_mix[1]
                  else Priority.BEST_EFFORT)
            adapter = None
            if adapter_p is not None and rng.random() < adapter_frac:
                adapter = adapters[int(rng.choice(len(adapter_p),
                                                  p=adapter_p))]
            sysp = sys_prompts[int(rng.integers(0, n_system_prompts))]
            convo: List[int] = []
            tt = float(t0)
            for _turn in range(int(rng.integers(1, max_turns + 1))):
                convo = convo + rng.integers(
                    0, vocab, size=int(rng.integers(6, 13))).tolist()
                prompt = np.concatenate(
                    [sysp, np.asarray(convo)]).astype(np.int32)
                new = int(min(new_tokens_base
                              + rng.pareto(new_tokens_tail)
                              * new_tokens_scale, new_tokens_cap))
                events.append(dict(
                    t=tt, session=f"s{s}",
                    prompt=prompt[:prompt_cap].tolist(),
                    new_tokens=new, priority=pr,
                    deadline_s=(interactive_deadline_s
                                if pr is Priority.INTERACTIVE else None),
                    adapter=adapter))
                tt += float(rng.exponential(think_time_s))
    # Down-sample the overshoot UNIFORMLY, not by truncating the sorted
    # tail: cutting the latest events would amputate the final
    # trough/peak and bend the diurnal shape the curve promises.
    if len(events) > n_requests:
        keep = rng.choice(len(events), size=n_requests, replace=False)
        events = [events[i] for i in sorted(keep)]
    events = sorted(events, key=lambda e: e["t"])
    mean_new = float(np.mean([e["new_tokens"] for e in events]))
    return events, mean_new
