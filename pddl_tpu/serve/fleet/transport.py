"""Framed replica transport: the wire stops being trusted.

The r11 pipe protocol between :class:`~pddl_tpu.serve.fleet.replica.
ProcessReplica` and `fleet/worker.py` was raw JSON lines — which
assumes the stdio pipe is a perfectly reliable, perfectly ordered
network. That is true of a kernel pipe on one box and false of every
transport the fleet will ever ride at pod scale (TCP through proxies,
RDMA with flaky links, a relay that re-chunks writes). Gray Failure
(Huang et al., HotOS '17) is explicit that the differential between
"dead" and "subtly corrupting/delaying" is what takes systems down, so
this module makes the wire UNTRUSTED and the failure modes injectable:

- **Framing.** Every payload travels as one line::

      PF1 <seq> <crc32-hex> <len> <payload-json>\\n

  Length-prefix (byte length of the payload), CRC32 over the payload
  bytes, and a per-direction monotone sequence number. A frame whose
  length or CRC disagrees is REJECTED, never parsed — zero corrupt
  frames accepted is a property of the codec, not of luck. The frame
  stays newline-terminated so the existing select()/readline pump
  loops keep working unchanged.
- **Sequencing.** The receiver delivers payloads in seq order:
  duplicates (seq already delivered) are dropped, gaps (a future seq
  arrives first) are buffered and trigger a bounded RESEND REQUEST for
  the missing range; the sender keeps a bounded replay buffer of
  recent frames to answer from. Retries are bounded with timeout
  backoff — an unrecoverable wire degrades to the typed
  :class:`~pddl_tpu.serve.fleet.replica.ReplicaDied` path the router
  already migrates around, it never wedges the router loop.
- **Bounded reads.** A single frame larger than ``max_frame_bytes``
  (default 8 MiB — a drain-snapshot or chain-pull base64 payload is
  MBs, a runaway line is not) is a TYPED reject
  (:class:`FrameTooLarge` recorded in the stats, the oversized bytes
  discarded up to the next newline), closing the unbounded
  single-line read the r11 loops had.
- **Injection.** :class:`WireFaultPlan` is the `utils/faults.py`
  discipline applied to frames: seeded Bernoulli rates plus scheduled
  ``(step, site)`` coordinates (step = the frame's sequence number,
  site = the pipe direction), firing CORRUPT / TRUNCATE / DUPLICATE /
  REORDER / DELAY / DROP mutations on the byte stream. The same seed
  against the same workload mangles the same frames, so every
  recovery path of the framed transport is testable in tier-1 on CPU.
"""

from __future__ import annotations

import dataclasses
import enum
import time
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

FRAME_MAGIC = b"PF1"
# Large enough for chain-pull/base64 snapshot payloads, small enough
# that a runaway writer cannot balloon the peer's line buffer without
# a typed reject. Both pipe ends enforce it.
MAX_FRAME_BYTES = 8 * 1024 * 1024


class FrameError(ValueError):
    """A frame failed validation (bad magic/length/CRC): the payload
    is untrusted and is NOT parsed. The receiver records it and asks
    for a resend; nothing raises across the pump loop."""


class FrameTooLarge(FrameError):
    """A single line exceeded ``max_frame_bytes`` — the typed reject
    for the unbounded single-line pipe read (a multi-MB payload must
    arrive as a VALID frame under the cap, or not at all)."""


CONTROL_MAGIC = b"PFC"


def encode_control(payload: Dict) -> bytes:
    """A transport-CONTROL line (resend requests): deliberately
    OUTSIDE the sequence space. A control message ordered behind the
    very gap it reports would deadlock the healing — each side waiting
    for the other's missing frame — so control lines are sequence-free,
    idempotent, and periodically re-sent; a corrupted one is simply
    dropped and the next period repeats it."""
    import json

    return CONTROL_MAGIC + b" " + json.dumps(
        payload, separators=(",", ":")).encode() + b"\n"


def decode_control(line: bytes) -> Optional[Dict]:
    """The control payload, or None if the line is not (or no longer)
    a well-formed control line."""
    import json

    if not line.startswith(CONTROL_MAGIC + b" "):
        return None
    try:
        payload = json.loads(line[len(CONTROL_MAGIC) + 1:])
    except ValueError:
        return None
    return payload if isinstance(payload, dict) else None


def encode_frame(seq: int, payload_json: bytes) -> bytes:
    """One framed line: magic, sequence, CRC32, length, payload."""
    crc = zlib.crc32(payload_json) & 0xFFFFFFFF
    return b" ".join([FRAME_MAGIC, str(int(seq)).encode(),
                      format(crc, "08x").encode(),
                      str(len(payload_json)).encode(),
                      payload_json]) + b"\n"


def decode_frame(line: bytes) -> Tuple[int, bytes]:
    """``(seq, payload_json)`` of a framed line (no trailing newline),
    raising :class:`FrameError` on any validation failure."""
    parts = line.split(b" ", 4)
    if len(parts) != 5 or parts[0] != FRAME_MAGIC:
        raise FrameError("not a PF1 frame")
    try:
        seq = int(parts[1])
        crc = int(parts[2], 16)
        length = int(parts[3])
    except ValueError as e:
        raise FrameError(f"malformed frame header: {e}") from e
    payload = parts[4]
    if len(payload) != length:
        raise FrameError(
            f"length mismatch: header says {length}, got {len(payload)}")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise FrameError("CRC32 mismatch")
    return seq, payload


class FrameSender:
    """Outbound framing: assigns the monotone sequence and keeps a
    bounded replay buffer so the peer's resend requests can be
    answered without re-deriving application state."""

    def __init__(self, *, resend_buffer: int = 512):
        self._next_seq = 1
        self._buffer: "OrderedDict[int, bytes]" = OrderedDict()
        self._resend_buffer = int(resend_buffer)
        self.frames_sent = 0
        self.frames_resent = 0

    def encode(self, payload_json: bytes) -> bytes:
        seq = self._next_seq
        self._next_seq += 1
        frame = encode_frame(seq, payload_json)
        self._buffer[seq] = frame
        while len(self._buffer) > self._resend_buffer:
            self._buffer.popitem(last=False)
        self.frames_sent += 1
        return frame

    @property
    def last_seq(self) -> int:
        """Sequence number of the most recently encoded frame (0 when
        none yet) — the fault plan's step coordinate on egress."""
        return self._next_seq - 1

    def resend_from(self, from_seq: int) -> List[bytes]:
        """Every buffered frame with ``seq >= from_seq``, in order.
        Frames that aged out of the buffer are gone — the requester's
        bounded retry then degrades to its typed failure path."""
        out = [frame for seq, frame in self._buffer.items()
               if seq >= int(from_seq)]
        self.frames_resent += len(out)
        return out


class FrameReceiver:
    """Inbound framing: validates, de-duplicates, re-orders, and
    reports the gap to ask a resend for. Feed it raw lines; read
    in-order payloads back.

    Stats keys (all monotone counters): ``frames_ok``, ``crc_rejects``
    (CRC/length/parse failures — frames the codec REFUSED), ``dups``
    (sequence already delivered), ``gaps`` (a future frame arrived
    first), ``too_large`` (the typed oversize reject).
    """

    def __init__(self, *, max_frame_bytes: int = MAX_FRAME_BYTES,
                 reorder_buffer: int = 256, first_seq: int = 1):
        # ``first_seq``: where the sequence space begins for THIS
        # receiver. A standby that joins an already-running WAL stream
        # (router HA, ISSUE 20) resumes from the shipper's next frame
        # after a disk catch-up — demanding history the sender's replay
        # buffer no longer holds would wedge the gap logic forever.
        self._expected = int(first_seq)
        self._pending: Dict[int, bytes] = {}
        self._max_frame = int(max_frame_bytes)
        self._reorder_buffer = int(reorder_buffer)
        self.stats: Dict[str, int] = {
            "frames_ok": 0, "crc_rejects": 0, "dups": 0, "gaps": 0,
            "too_large": 0}

    @property
    def expected_seq(self) -> int:
        return self._expected

    @property
    def has_gap(self) -> bool:
        return bool(self._pending)

    def feed(self, line: bytes) -> List[bytes]:
        """One raw line in; zero or more IN-ORDER payloads out (a
        gap-filling frame releases everything buffered behind it)."""
        if len(line) > self._max_frame:
            # Typed oversize reject. An oversized frame that VALIDATES
            # (correct CRC, just over policy) is refused terminally —
            # its sequence slot is consumed so the stream advances
            # (resending the same oversize would wedge the gap logic
            # forever); an oversized frame that fails validation takes
            # the corrupt path (resend may produce an intact one).
            self.stats["too_large"] += 1
            try:
                seq, _ = decode_frame(line)
            except FrameError:
                self.stats["crc_rejects"] += 1
                return []
            if seq != self._expected:
                return []
            out: List[bytes] = []
            self._expected += 1
            while self._expected in self._pending:
                out.append(self._pending.pop(self._expected))
                self._expected += 1
            self.stats["frames_ok"] += len(out)
            return out
        try:
            seq, payload = decode_frame(line)
        except FrameError:
            self.stats["crc_rejects"] += 1
            return []
        if seq < self._expected or seq in self._pending:
            self.stats["dups"] += 1
            return []
        if seq > self._expected:
            self.stats["gaps"] += 1
            if len(self._pending) < self._reorder_buffer:
                self._pending[seq] = payload
            return []
        out = [payload]
        self._expected += 1
        while self._expected in self._pending:
            out.append(self._pending.pop(self._expected))
            self._expected += 1
        self.stats["frames_ok"] += len(out)
        return out

    def drain_pending(self) -> List[Tuple[int, bytes]]:
        """Abandon in-order delivery: every buffered out-of-order
        frame, ``(seq, payload)`` sorted by seq, and the expectation
        jumps past them. The standby's catch-up path (router HA,
        ISSUE 20) calls this after refolding from disk — a gap on a
        one-way replication stream will never heal from the wire (the
        primary may be dead), and the disk fold already covers the
        missing range's durable prefix; whatever was buffered beyond
        it is the NON_DURABLE backlog, deduplicated downstream by the
        journal's own record sequence."""
        out = sorted(self._pending.items())
        self._pending.clear()
        if out:
            self._expected = max(self._expected, out[-1][0] + 1)
        return out


# ------------------------------------------------------ fault injection


class WireFaultKind(enum.Enum):
    CORRUPT = "corrupt"      # flip payload bytes: CRC must reject
    TRUNCATE = "truncate"    # cut the line short: length must reject
    DUPLICATE = "duplicate"  # deliver the frame twice: seq must dedup
    REORDER = "reorder"      # hold the frame, deliver after the next
    DELAY = "delay"          # tail-latency: sleep, frame intact
    DROP = "drop"            # lose the frame: gap + resend must heal


@dataclasses.dataclass(frozen=True)
class WireFaultSpec:
    """One scheduled wire fault: fire ``kind`` on the frame whose
    sequence number is ``step`` travelling in direction ``site``
    (``"cmd"`` parent->worker, ``"ev"`` worker->parent) — the
    `utils/faults.py` (step, site) coordinate discipline applied to
    the pipe."""

    step: int
    site: str
    kind: WireFaultKind


class WireFaultPlan:
    """Seeded wire-fault schedule over a framed pipe's two directions.

    The `utils/faults.py` shape: explicit :class:`WireFaultSpec`
    coordinates are the surgical tool, per-frame Bernoulli rates from
    one seeded generator are the chaos tool; the same seed against the
    same workload mangles the same frames. ``apply(site, seq, line)``
    returns the list of lines actually delivered in place of ``line``
    (possibly mutated, duplicated, reordered with a held frame, or
    empty for a drop).
    """

    SITES: Tuple[str, ...] = ("cmd", "ev")

    def __init__(self, seed: int = 0, *, corrupt_rate: float = 0.0,
                 truncate_rate: float = 0.0, duplicate_rate: float = 0.0,
                 reorder_rate: float = 0.0, delay_rate: float = 0.0,
                 drop_rate: float = 0.0, delay_s: float = 0.002,
                 sites: Optional[Sequence[str]] = None,
                 scheduled: Sequence[WireFaultSpec] = (),
                 max_random_injections: Optional[int] = None,
                 sleep_fn=time.sleep):
        rates = {"corrupt": corrupt_rate, "truncate": truncate_rate,
                 "duplicate": duplicate_rate, "reorder": reorder_rate,
                 "delay": delay_rate, "drop": drop_rate}
        for name, rate in rates.items():
            if not 0.0 <= rate <= 1.0:
                raise ValueError(
                    f"{name}_rate must be in [0, 1], got {rate}")
        if sum(rates.values()) > 1.0:
            raise ValueError("wire fault rates must sum to <= 1")
        if sites is not None:
            unknown = set(sites) - set(self.SITES)
            if unknown:
                raise ValueError(
                    f"unknown wire site(s) {sorted(unknown)}; valid "
                    f"sites are {self.SITES}")
        for spec in scheduled:
            if spec.site not in self.SITES:
                raise ValueError(
                    f"unknown scheduled wire site {spec.site!r}; valid "
                    f"sites are {self.SITES}")
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)
        self._rates = {WireFaultKind(k): float(v)
                       for k, v in rates.items()}
        self.delay_s = float(delay_s)
        self._sites = frozenset(sites) if sites is not None else None
        self._sched: Dict[Tuple[int, str], List[WireFaultKind]] = {}
        for spec in scheduled:
            self._sched.setdefault((spec.step, spec.site), []).append(
                spec.kind)
        self._max_random = max_random_injections
        self._random_fired = 0
        self._sleep = sleep_fn
        # One held frame per site (the REORDER mechanism): delivered
        # in front of the NEXT frame on the same direction.
        self._held: Dict[str, bytes] = {}
        self.injected: Dict[WireFaultKind, int] = {
            k: 0 for k in WireFaultKind}
        self.on_inject = None  # fn(seq, site, kind_value), tracer hook

    @property
    def total_injected(self) -> int:
        return sum(self.injected.values())

    def _draw(self, site: str, seq: int) -> Optional[WireFaultKind]:
        pending = self._sched.get((seq, site))
        if pending:
            kind = pending.pop(0)
            if not pending:
                del self._sched[(seq, site)]
            return kind
        if self._sites is not None and site not in self._sites:
            return None
        if (self._max_random is not None
                and self._random_fired >= self._max_random):
            return None
        total = sum(self._rates.values())
        if total <= 0.0:
            return None
        u = self._rng.random()
        acc = 0.0
        for kind, rate in self._rates.items():
            acc += rate
            if u < acc:
                self._random_fired += 1
                return kind
        return None

    def apply(self, site: str, seq: int, line: bytes) -> List[bytes]:
        """The lines to actually deliver in place of ``line``."""
        out: List[bytes] = []
        held = self._held.pop(site, None)
        kind = self._draw(site, seq)
        if kind is None:
            if held is not None:
                out.append(held)
            out.append(line)
            return out
        self.injected[kind] += 1
        if self.on_inject is not None:
            self.on_inject(seq, site, kind.value)
        if kind is WireFaultKind.CORRUPT:
            mangled = bytearray(line)
            # Flip a byte inside the payload region (past the header),
            # never the trailing newline — the line structure survives,
            # the CRC must not.
            idx = max(0, len(mangled) - 2 - int(
                self._rng.integers(0, max(1, len(mangled) // 2))))
            mangled[idx] ^= 0x5A
            out.extend([] if held is None else [held])
            out.append(bytes(mangled))
        elif kind is WireFaultKind.TRUNCATE:
            cut = max(len(FRAME_MAGIC) + 1, len(line) // 2)
            out.extend([] if held is None else [held])
            out.append(line[:cut] + b"\n")
        elif kind is WireFaultKind.DUPLICATE:
            out.extend([] if held is None else [held])
            out.extend([line, line])
        elif kind is WireFaultKind.REORDER:
            # Hold THIS frame; a previously held one flushes first so
            # at most one frame per site is ever in flight late.
            if held is not None:
                out.append(held)
            self._held[site] = line
        elif kind is WireFaultKind.DELAY:
            self._sleep(self.delay_s)
            out.extend([] if held is None else [held])
            out.append(line)
        else:  # DROP
            if held is not None:
                out.append(held)
        return out

    def flush(self, site: str) -> List[bytes]:
        """Release a held (REORDER) frame — call when the stream is
        ending so a reordered final frame is not lost forever."""
        held = self._held.pop(site, None)
        return [held] if held is not None else []
