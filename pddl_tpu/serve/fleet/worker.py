"""Fleet worker: one engine replica as a real OS process.

``python -m pddl_tpu.serve.fleet.worker --config-json '{...}'`` builds
a GPT + :class:`~pddl_tpu.serve.ServeEngine` from the config, warms it,
and then speaks the JSON-line protocol of
:class:`~pddl_tpu.serve.fleet.replica.ProcessReplica` over stdio:
commands (submit/cancel/ping/counts/restore/fence/shutdown) arrive on
stdin, events (ready/submit_ok/queue_full/tokens/finish/pong/counts/
snapshot/fenced/fence_ok) leave on stdout. stdout is PROTOCOL-ONLY — anything chatty (jax logs)
must go to stderr, which the parent leaves attached to its own.

Determinism contract: every worker of a fleet (and the oracle engine a
chaos test compares against) initializes parameters from the same
``param_seed``, so greedy streams are token-exact across replicas —
which is what makes live migration's "finish with the identical token
sequence" promise testable.

Death modes, matching r08's single-engine taxonomy:

- **SIGTERM** → drain: stop admission, encode every in-flight request
  (rid-tagged, `serve/drain.py` wire format), emit it as the final
  ``snapshot`` event, exit 0. The router restores these on survivors —
  live migration.
- **SIGKILL / crash** → nothing is emitted; the parent sees EOF and
  the router rebuilds the lost requests from its own prompt+token
  mirrors (replay fallback).
"""

from __future__ import annotations

import argparse
import json
import select
import signal
import sys
from typing import Dict

from pddl_tpu.serve.fleet.replica import HandleLedger, sampling_from_wire
from pddl_tpu.serve.fleet.transport import (
    MAX_FRAME_BYTES,
    FrameReceiver,
    FrameSender,
    decode_control,
    encode_control,
)
from pddl_tpu.serve.request import Priority, QueueFull

# Machine-checked role vocabulary (graftlint `role-vocab`): must stay
# set-equal to `fleet/disagg.py`'s ROLES — declared as a literal on
# BOTH sides of the process boundary on purpose, so the worker can
# refuse a role this build has never heard of even when spawned by a
# newer (or older) parent.
ROLES = ("prefill", "decode", "unified")

# Machine-checked fencing dispatch table (graftlint `epoch-vocab`):
# the command kinds whose ``epoch`` stamp this worker checks before
# dispatch — must stay tuple-equal to `fleet/replica.py`'s EPOCH_CMDS
# (the driver-side stamping manifest), both directions. Declared as a
# literal on BOTH sides of the process boundary on purpose, like
# ROLES: fencing is only as strong as the stalest binary's table.
FENCED_CMDS = ("submit", "cancel", "restore", "fence")


def build_engine(config: Dict[str, object]):
    """Engine from a flat config dict (the fleet's one model family for
    now: GPT with ``attention="reference"`` — the CPU-safe path)."""
    import jax
    import jax.numpy as jnp

    from pddl_tpu.models.gpt import GPT
    from pddl_tpu.serve import ServeEngine

    # Fleet determinism: every process deriving params from param_seed
    # must draw the SAME bits. Newer jax defaults this True; older
    # releases default False — pin it so a worker and the oracle
    # comparing against it can never disagree on initialization.
    try:
        jax.config.update("jax_threefry_partitionable", True)
    except Exception:  # noqa: BLE001 - flag gone once always-on
        pass

    model = GPT(vocab_size=int(config.get("vocab", 256)),
                max_len=int(config.get("max_len", 512)),
                embed_dim=int(config.get("embed_dim", 256)),
                depth=int(config.get("depth", 4)),
                num_heads=int(config.get("heads", 4)),
                attention="reference")
    dummy = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.key(int(config.get("param_seed", 0))),
                        dummy, train=False)["params"]
    aging = config.get("aging_s", 30.0)
    # Multi-tenant passthrough (ISSUE 9, mirroring the r13 `paged`
    # passthrough): a `tenant` sub-config builds the same registry on
    # every process replica — adapters are (name, seed[, rank, scale])
    # pairs materialized via the registry's deterministic
    # `register_random`, so every replica (and the chaos oracle) holds
    # bit-identical factors and migrated tenant streams stay
    # token-exact across processes. `token_strings` enables grammar
    # constraints; absent `tenant` keeps the plain engine so existing
    # fleet configs stay comparable.
    tenant_cfg = config.get("tenant")
    tenant = None
    if tenant_cfg:
        from pddl_tpu.serve.tenant import AdapterRegistry, TenantConfig

        registry = AdapterRegistry(
            model.embed_dim, model.vocab_size,
            rank=int(tenant_cfg.get("rank", 8)))
        for name, spec in (tenant_cfg.get("adapters") or {}).items():
            registry.register_random(
                name, int(spec["seed"]),
                scale=float(spec.get("scale", 0.05)),
                rank=spec.get("rank"))
        pool_slots = tenant_cfg.get("adapter_pool_slots")
        tenant = TenantConfig(
            registry=registry,
            adapter_pool_slots=(int(pool_slots)
                                if pool_slots is not None else None),
            token_strings=tenant_cfg.get("token_strings"),
            adapter_load_tokens=int(
                tenant_cfg.get("adapter_load_tokens", 8)))
    # Tiered KV cache (ISSUE 13, mirroring the paged/tenant/spec
    # passthroughs): a nonzero host_tier_bytes arms the host-RAM spill
    # tier on every process replica — which is also what makes the
    # router's chain pulls land somewhere. Absent keeps the untiered
    # engine so existing fleet configs stay comparable.
    host_tier = None
    if config.get("host_tier_bytes"):
        from pddl_tpu.serve.kvcache import HostTierConfig

        host_tier = HostTierConfig(
            byte_budget=int(config["host_tier_bytes"]),
            promote_tokens_per_block=int(
                config.get("host_promote_tokens_per_block", 2)),
            min_chain_blocks=int(
                config.get("host_min_chain_blocks", 1)))
    return ServeEngine(
        model, {"params": params},
        host_tier=host_tier,
        max_slots=int(config.get("slots", 8)),
        prefill_len=int(config.get("prefill_len", 64)),
        max_queue_depth=int(config.get("max_queue_depth", 64)),
        # SLO knobs (ISSUE 7): scheduler aging and chunked-prefill
        # slicing ride the same flat config.
        prefill_token_budget=config.get("prefill_token_budget"),
        aging_s=float(aging) if aging is not None else None,
        prefill_slice_tokens=config.get("prefill_slice_tokens"),
        # Engine-parity default: absent means the auto-sized prefix
        # pool, NOT off — the router's affinity shadow must point at
        # caches that exist. Pass 0 explicitly to disable.
        prefix_cache_blocks=config.get("prefix_cache_blocks"),
        # Paged attention (ISSUE 8): decode straight from the block
        # pool through per-slot block tables; absent keeps the copy
        # engine so existing bench configs stay comparable.
        paged=bool(config.get("paged", False)),
        tenant=tenant,
        # Speculative serving (ISSUE 12, mirroring the paged/tenant
        # passthroughs): every replica drafts with the same k/ngram, so
        # migrated speculative streams land on an engine that re-feeds
        # them through the identical verify machinery. Absent keeps the
        # classic tick so existing fleet configs stay comparable.
        spec_k=int(config.get("spec_k", 0)),
        spec_ngram=int(config.get("spec_ngram", 3)),
        rng=jax.random.key(int(config.get("engine_seed", 0))))


def _emit(record: Dict[str, object]) -> None:
    sys.stdout.write(json.dumps(record) + "\n")
    sys.stdout.flush()


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--config-json", required=True)
    args = p.parse_args(argv)
    config = json.loads(args.config_json)

    # Disaggregation role (ISSUE 17): validated BEFORE the engine
    # build — a misconfigured role is a config error the spawn should
    # surface (the parent sees a ready timeout + this stderr line),
    # not a replica that silently serves the wrong phase.
    role = str(config.get("role", "unified"))
    if role not in ROLES:
        print(f"invalid replica role {role!r}: must be one of {ROLES}",
              file=sys.stderr)
        return 2

    # Framed transport (ISSUE 14, `fleet/transport.py`): the parent
    # injects ``framed: true`` and both directions gain length+CRC+seq
    # framing, duplicate suppression, and bounded resend — stdout is
    # still PROTOCOL-ONLY, the frames are still one line each.
    framed = bool(config.get("framed", False))
    max_frame = int(config.get("max_frame_bytes", MAX_FRAME_BYTES))
    sender = FrameSender()
    receiver = FrameReceiver(max_frame_bytes=max_frame)

    if framed:
        def emit(record: Dict[str, object]) -> None:
            sys.stdout.buffer.write(sender.encode(
                json.dumps(record, separators=(",", ":")).encode()))
            sys.stdout.buffer.flush()
    else:
        emit = _emit

    engine = build_engine(config)
    engine.warmup()
    ledger = HandleLedger()

    # Distributed tracing (ISSUE 19): `dtrace` arms a RequestTracer on
    # the engine plus the bounded span shipper (records ride back on
    # the pipe); `flightrec_dir` arms the crash-durable flight
    # recorder (spans + per-tick records survive SIGKILL for the
    # router's postmortem harvest). Both default off — the tracing-off
    # worker is byte-identical to the pre-ISSUE-19 one.
    tracer = None
    shipper = None
    recorder = None
    trace_rids: Dict[int, int] = {}  # engine request_id -> router rid
    if config.get("dtrace"):
        from pddl_tpu.obs.propagate import SpanShipper
        from pddl_tpu.obs.trace import RequestTracer

        # Small decode-event budget: per-token events are cadence
        # detail the TTFT critical path never reads (it keys off
        # prefill/first_token events and the tokens_emitted field),
        # but they dominate shipped-span JSON volume — and on a
        # shared-core host, serialize/parse time is decode time.
        tracer = RequestTracer(
            max_decode_events_per_span=int(
                config.get("dtrace_decode_events", 8)))
        engine.set_tracer(tracer)
        shipper = SpanShipper(capacity=int(
            config.get("dtrace_buffer", 512)))
    if config.get("flightrec_dir"):
        from pddl_tpu.obs.flightrec import FlightRecorder

        recorder = FlightRecorder(
            str(config["flightrec_dir"]),
            max_segment_bytes=int(
                config.get("flightrec_segment_bytes", 262144)),
            max_segments=int(config.get("flightrec_segments", 4)),
            tracer=tracer)

    flags = {"drain": False, "shutdown": False}

    # Fencing epoch (router HA, ISSUE 20): the highest epoch any
    # command has carried. -1 = never fenced, so epoch-free callers
    # (every pre-HA fleet) are never refused. ``fence_path`` persists
    # the floor across a worker respawn — a deposed primary must not
    # regain the fleet by bouncing its workers.
    fence = {"epoch": -1}
    fence_path = config.get("fence_path")
    if fence_path:
        try:
            with open(str(fence_path)) as f:
                fence["epoch"] = max(fence["epoch"], int(f.read()))
        except (OSError, ValueError):
            pass  # no file yet / unreadable: the in-memory floor rules

    def raise_fence(epoch: int) -> None:
        fence["epoch"] = epoch
        if fence_path:
            try:
                with open(str(fence_path), "w") as f:
                    f.write(str(epoch))
            except OSError as e:  # keep serving: the in-memory floor
                print(f"fence persist failed: {e}", file=sys.stderr)

    def _on_sigterm(signum, frame):  # flag only: async-signal-safe
        flags["drain"] = True

    signal.signal(signal.SIGTERM, _on_sigterm)
    emit({"ev": "ready", "replica": config.get("replica_id"),
          "role": role, "compile_counts": engine.compile_counts()})

    import time

    def note_trace(rid: int, handle, ctx) -> None:
        """Stamp the router's wire trace context onto a fresh span and
        remember the engine-id -> rid mapping for shipping."""
        if tracer is None:
            return
        eng_rid = handle.request.request_id
        trace_rids[eng_rid] = rid
        if ctx:
            tracer.on_trace_context(eng_rid, str(ctx[0]), ctx[1])

    def pump_spans() -> None:
        """Finished engine spans -> flight recorder + shipper, then one
        ``spans`` event per batch so records reach the router in the
        same pipe write as the finishes they describe (no heartbeat
        lag for a test or a postmortem to wait out)."""
        if tracer is None:
            return
        moved = 0
        while True:
            try:
                rec = tracer.finished.popleft()
            except IndexError:
                break
            rec = dict(rec)
            rec["rid"] = trace_rids.pop(rec.get("request_id"), None)
            rec["replica"] = config.get("replica_id")
            rec["role"] = role
            if recorder is not None:
                recorder.append(rec)
            shipper.add(rec)
            moved += 1
        if moved:
            tracer.on_span_shipped(moved, shipper.dropped)
        while len(shipper):
            emit({"ev": "spans", "spans": shipper.drain(16),
                  "dropped": shipper.dropped})

    def handle_cmd(cmd: Dict[str, object]) -> None:
        kind = cmd.get("cmd")
        # Fencing gate, BEFORE dispatch (ISSUE 20): a command in the
        # FENCED_CMDS table carrying a STALE epoch is refused whole
        # with the typed reject — the deposed-but-alive primary
        # physically cannot drive this worker. Equal-or-higher epochs
        # are adopted (and persisted) first, so the promotion probe
        # and the new primary's first command both raise the floor.
        if kind in FENCED_CMDS and cmd.get("epoch") is not None:
            epoch = int(cmd["epoch"])
            if epoch < fence["epoch"]:
                emit({"ev": "fenced", "cmd": kind,
                      "rid": cmd.get("rid"), "epoch": epoch,
                      "highest": fence["epoch"]})
                return
            if epoch > fence["epoch"]:
                raise_fence(epoch)
        if kind == "fence":
            # The promotion probe: the gate above already adopted the
            # epoch (or refused the probe); ack with the floor held.
            emit({"ev": "fence_ok", "highest": fence["epoch"]})
        elif kind == "submit":
            rid = int(cmd["rid"])
            try:
                handle = engine.submit(
                    cmd["prompt"], int(cmd["max_new_tokens"]),
                    sampling=sampling_from_wire(cmd.get("sampling")),
                    deadline_s=cmd.get("deadline_s"),
                    priority=Priority(cmd.get(
                        "priority", Priority.INTERACTIVE.value)),
                    adapter=cmd.get("adapter"),
                    constraint=cmd.get("constraint"))
            except QueueFull as e:
                emit({"ev": "queue_full", "rid": rid,
                       "queue_depth": e.queue_depth,
                       "max_queue_depth": e.max_queue_depth,
                       "retry_after_s": e.retry_after_s})
                return
            except ValueError as e:  # bad request (too long, etc.):
                emit({"ev": "error", "rid": rid,  # reject it, not the
                       "message": str(e)})         # whole worker
                return
            ledger.add(rid, handle)
            note_trace(rid, handle, cmd.get("trace"))
            emit({"ev": "submit_ok", "rid": rid})
        elif kind == "cancel":
            h = ledger.get(int(cmd["rid"]))
            if h is not None:
                h.cancel()
        elif kind == "ping":
            # `tick_wall_s` (the worker's own last engine-step wall,
            # injected delay included) is the gray detector's latency
            # sample for PROCESS replicas: the parent's pipe-pump wall
            # cannot see a slow self-driving worker, so the worker
            # self-reports — gray failure is degradation, not
            # byzantine lying, and the number is measured where the
            # time is actually spent.
            # `echo_t_s`/`mono_s`: the parent's ping send time echoed
            # back with this process's own monotonic read — one clock-
            # offset sample per heartbeat (ISSUE 19 trace stitching).
            emit({"ev": "pong", "queue_depth": engine.scheduler.depth,
                  "live_slots": engine.live_slots,
                  "degraded": engine.degraded,
                  "tick_wall_s": wire["tick_wall_s"],
                  "echo_t_s": cmd.get("t_s"),
                  "mono_s": time.monotonic()})
            pump_spans()  # idle-path shipping: heartbeats flush spans
                          # even when no engine step is harvesting
        elif kind == "set_tick_delay":
            # Chaos knob (the gray-failure injector): every subsequent
            # engine step gains this much wall time — the process-
            # replica analogue of a LATENCY FaultPlan on every call.
            wire["tick_delay_s"] = float(cmd.get("delay_s", 0.0))
        elif kind == "counts":
            emit({"ev": "counts", "counts": engine.compile_counts()})
        elif kind == "restore":
            from pddl_tpu.serve.fleet.replica import snapshot_from_pairs
            from pddl_tpu.serve.request import FinishReason, RequestState

            # Entry-at-a-time with per-entry isolation (the submit
            # handler's discipline): one bad migrated entry — a
            # corrupted mirror, a prompt beyond THIS replica's max_len —
            # must fail that request terminally, not crash a healthy
            # survivor mid-failover and cascade the outage.
            tmap = {int(p[0]): p[1]
                    for p in (cmd.get("traces") or [])}
            for rid, entry in cmd["requests"]:
                rid = int(rid)
                try:
                    (h,) = engine.restore(snapshot_from_pairs(
                        [(rid, entry)]))
                except Exception as e:  # noqa: BLE001 - reject the entry
                    print(f"restore of rid={rid} rejected: {e}",
                          file=sys.stderr)
                    emit({"ev": "finish", "rid": rid,
                           "state": RequestState.FAILED.value,
                           "reason": FinishReason.ERROR.value,
                           "ttft_s": (entry.get("ttft_s")
                                      if isinstance(entry, dict) else None),
                           "n_tokens": 0})
                    continue
                ledger.add(rid, h)
                note_trace(rid, h, tmap.get(rid))
        elif kind == "export_chain":
            # Replica-to-replica prefix transfer OUT (ISSUE 13): the
            # chain wire entry (or null) as a synchronous ack, like
            # counts — the router routes on the answer. Per-command
            # isolation (the submit/restore discipline): the pull is
            # best-effort END TO END, so a failed export — tier off on
            # this engine, a device fault mid-read — answers null, it
            # never crashes a healthy replica serving live streams.
            t0 = time.monotonic()
            try:
                entry = engine.export_prefix_chain(
                    cmd["prompt"], max_blocks=cmd.get("max_blocks"))
            except Exception as e:  # noqa: BLE001 - reject the pull
                print(f"export_chain rejected: {e}", file=sys.stderr)
                entry = None
            t1 = time.monotonic()
            if entry is not None and tracer is not None:
                from pddl_tpu.obs.propagate import chain_export_span

                n_blocks = len(entry.get("blocks") or ())
                tracer.on_chain_export(n_blocks, t1 - t0)
                shipper.add(chain_export_span(
                    cmd.get("trace"), t0, t1, n_blocks,
                    replica=config.get("replica_id"), role=role))
            emit({"ev": "chain", "entry": entry})
        elif kind == "import_chain":
            # Same isolation inbound: a malformed wire entry (bad
            # base64, an invalid dtype string from a foreign build)
            # refuses the chain, not the worker.
            t0 = time.monotonic()
            try:
                n = engine.import_prefix_chain(cmd["entry"])
            except Exception as e:  # noqa: BLE001 - reject the entry
                print(f"import_chain rejected: {e}", file=sys.stderr)
                n = 0
            t1 = time.monotonic()
            if n and tracer is not None:
                from pddl_tpu.obs.propagate import chain_import_span

                tracer.on_chain_import(n, t1 - t0)
                shipper.add(chain_import_span(
                    cmd.get("trace"), t0, t1, n,
                    replica=config.get("replica_id"), role=role))
            emit({"ev": "chain_imported", "n": n})
        elif kind == "drain":
            flags["drain"] = True
        elif kind == "shutdown":
            flags["shutdown"] = True

    wire = {"next_resend_s": 0.0, "dropping": False,
            "tick_wall_s": None, "tick_delay_s": 0.0}

    def consume_cmd_line(line: bytes) -> None:
        """One stdin line -> command(s). Framed mode validates, dedups
        and re-orders through the receiver; a command the CRC refused
        heals via the resend request below. An oversized line is a
        TYPED reject in both modes — reported, counted, never a worker
        crash (the r11 loop would have ballooned or thrown)."""
        if not line.strip():
            return
        if not framed:
            if len(line) > max_frame:
                receiver.stats["too_large"] += 1
                emit({"ev": "wire_error", "kind": "frame_too_large",
                      "bytes": len(line)})
                return
            handle_cmd(json.loads(line))
            return
        ctl = decode_control(line)
        if ctl is not None:
            # Out-of-band control (sequence-free — see transport.py):
            # the parent lost event frames, replay them verbatim from
            # the send buffer (chaos never re-fires on resends —
            # recovery must terminate).
            if ctl.get("ctl") == "resend":
                for frame in sender.resend_from(int(ctl.get("from", 1))):
                    sys.stdout.buffer.write(frame)
                sys.stdout.buffer.flush()
            return
        if len(line) > max_frame:
            # Report the typed reject; the receiver still consumes the
            # frame's sequence slot (policy refusal, not corruption —
            # a resend of the same oversize could never heal it).
            emit({"ev": "wire_error", "kind": "frame_too_large",
                  "bytes": len(line)})
        for payload in receiver.feed(line):
            handle_cmd(json.loads(payload))

    stdin_fd = sys.stdin.fileno()
    buf = b""
    while not flags["shutdown"]:
        # Commands first (non-blocking; idle workers block briefly so a
        # quiet fleet costs ~no CPU), then one engine step if live.
        timeout = 0.0 if engine.has_work else 0.02
        ready, _, _ = select.select([stdin_fd], [], [], timeout)
        if ready:
            try:
                chunk = sys.stdin.buffer.raw.read(65536)
            except (BlockingIOError, OSError):
                chunk = None
            if chunk == b"":  # parent closed stdin: orphaned, exit
                break
            if chunk:
                buf += chunk
                # Unterminated-giant-line guard: discard through the
                # next newline instead of growing without bound (4x
                # headroom — a complete oversized frame must reach the
                # receiver's skip path, which consumes its seq slot).
                if wire["dropping"] or (b"\n" not in buf
                                        and len(buf) > 4 * max_frame):
                    if b"\n" in buf:
                        _, buf = buf.split(b"\n", 1)
                        if wire["dropping"]:
                            receiver.stats["too_large"] += 1
                            emit({"ev": "wire_error",
                                  "kind": "frame_too_large"})
                        wire["dropping"] = False
                    else:
                        buf = b""
                        wire["dropping"] = True
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    consume_cmd_line(line)
        if framed and receiver.has_gap:
            # A command went missing (corrupt/dropped frame): ask the
            # parent to resend from the first missing seq, at a
            # bounded cadence so a dead gap cannot spam the pipe.
            # Out-of-band (sequence-free) on purpose — see
            # transport.encode_control.
            now_s = time.monotonic()
            if now_s >= wire["next_resend_s"]:
                wire["next_resend_s"] = now_s + 0.02
                sys.stdout.buffer.write(encode_control(
                    {"ctl": "resend", "from": receiver.expected_seq}))
                sys.stdout.buffer.flush()
        if flags["drain"]:
            now = time.monotonic()
            entries = ledger.drain_entries(now)
            try:
                engine.drain()
            except Exception:  # noqa: BLE001 - snapshot already captured
                pass
            # engine.drain() flushed every in-flight span; ship them
            # BEFORE the snapshot so the migration's trace has no hole
            # where the source replica's records should be.
            pump_spans()
            emit({"ev": "snapshot",
                   "requests": [[rid, entry] for rid, entry in entries],
                   "compile_counts": engine.compile_counts()})
            if recorder is not None:
                recorder.close()
            return 0
        if engine.has_work:
            t0 = time.monotonic()
            engine.step()
            if wire["tick_delay_s"] > 0.0:
                time.sleep(wire["tick_delay_s"])
            wire["tick_wall_s"] = time.monotonic() - t0
            events = ledger.harvest()
            for ev in events:
                emit(ev)
            if recorder is not None:
                # The flight record of THIS tick: enough to reassemble
                # the worker's final moments after a SIGKILL (tokens
                # streamed per rid, wall, load) from the file alone.
                t_now = time.monotonic()
                recorder.append({"kind": "flight_tick", "t_s": t_now,
                                 "wall_s": wire["tick_wall_s"],
                                 "queue_depth": engine.scheduler.depth,
                                 "live_slots": engine.live_slots})
                for ev in events:
                    if ev.get("ev") == "tokens":
                        recorder.append({"kind": "flight_tokens",
                                         "t_s": t_now,
                                         "toks": ev["toks"]})
            pump_spans()
    return 0


if __name__ == "__main__":
    sys.exit(main())
