"""Prefix-aware KV reuse under the serving engine.

`block_pool.py` is the DEVICE half: a resident pool of fixed-size
token blocks per K/V cache leaf plus the tree-level gather (pool →
slot prefix) and donate (slot prompt → pool blocks) assembly over the
single-leaf primitives in :mod:`pddl_tpu.ops.attention`.
`radix.py` is the HOST half: a refcounted, LRU-evicted radix tree over
token ids mapping prompt prefixes to stored block chains.
`hosttier.py` is the SECOND tier under both (ISSUE 13): a
byte-budgeted pinned-host-memory pool where the radix index's LRU
victims spill instead of dying, and from which admission promotes
matched chains back H2D — see `docs/SERVING.md` § "Tiered KV cache".

See `docs/SERVING.md` § "Prefix caching" for the design and the
engine integration (`pddl_tpu/serve/engine.py`).
"""

from pddl_tpu.serve.kvcache.block_pool import (
    donate_prefix_blocks,
    gather_prefix_into_row,
    kv_block_pool,
    paged_decode_cache,
    pool_nbytes,
)
from pddl_tpu.serve.kvcache.hosttier import HostTierCache, HostTierConfig
from pddl_tpu.serve.kvcache.radix import RadixPrefixCache

__all__ = [
    "HostTierCache",
    "HostTierConfig",
    "RadixPrefixCache",
    "donate_prefix_blocks",
    "gather_prefix_into_row",
    "kv_block_pool",
    "paged_decode_cache",
    "pool_nbytes",
]
