"""Device-resident KV block pool: the storage half of the prefix cache.

The serving engine's pooled slot cache (`gpt.slot_decode_cache`) holds
each LIVE request's K/V at full request granularity; this module holds
SHARED prompt-prefix K/V at fixed-size token-block granularity, one
pool per K/V cache leaf:

    slot cache leaf   [S,  ..., max_len,     D]   (one row per request)
    block pool leaf   [N,  ..., block_size,  D]   (one row per block)

Block ``j`` of a cached prefix stores the K/V of tokens
``[j*block_size, (j+1)*block_size)`` at their ABSOLUTE positions — both
families' caches are position-absolute (GPT adds the learned position
embedding before the block stack; Llama caches post-RoPE keys rotated
at their global positions), so a prefix block computed by one request
is bit-valid for every later request sharing those prompt tokens.

Block id 0 is reserved as a WRITE SINK ("scratch"): fixed-shape gather
and scatter programs pad their runtime id vectors with 0, so one
compiled program serves every hit depth and donation width while the
radix index (`radix.py`) never hands out or references block 0. Data
flow is copy-only in both directions (gather copies pool → slot,
donation copies slot → pool), which is the copy-on-write guarantee: a
concurrent hit can never alias a live slot's storage, and eviction of
a pool block can never reach under a decoding request.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pddl_tpu.models.gpt import (
    BLOCK_TABLE_KEY,
    CACHE_INDEX_KEYS,
    _decode_cache_shapes,
    is_cache_index_path,
)
from pddl_tpu.ops.attention import cache_blocks_gather, cache_blocks_scatter

# The reserved write-sink block id (see module docstring).
SCRATCH_BLOCK = 0


def kv_block_pool(dec, num_blocks: int, block_size: int):
    """A zeros-initialized block pool tree for a decode module.

    Mirrors the row-cache structure (`gpt._decode_cache_shapes`) so the
    gather/donate tree maps below can walk pool and row together; K/V
    leaves become ``[num_blocks, ..., block_size, D]``, position
    counters become scalar placeholders (never read — the pool stores
    token K/V only, positions are implicit in the block index).
    """
    if num_blocks < 2:
        raise ValueError(
            f"num_blocks must be >= 2 (block 0 is the reserved scratch "
            f"sink), got {num_blocks}")
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    row = _decode_cache_shapes(dec, 1)

    def _leaf(path, sd):
        if is_cache_index_path(path):
            return jnp.zeros((), jnp.int32)
        return jnp.zeros(
            (num_blocks,) + sd.shape[1:-2] + (block_size, sd.shape[-1]),
            sd.dtype)

    return jax.tree_util.tree_map_with_path(_leaf, row)


def paged_decode_cache(dec, num_blocks: int, block_size: int):
    """The PAGED serving cache tree: the pool IS the cache.

    Where :func:`kv_block_pool` builds a pool that sits BESIDE the
    engine's resident slot cache (the copy-in/copy-out prefix cache),
    this builds the cache tree the paged engine hands straight to
    ``dec.apply``: every K/V leaf is a block pool
    ``[num_blocks, ..., block_size, D]``, position counters and
    per-slot block tables are CANONICAL PLACEHOLDERS (scalar 0 /
    ``[1, 1]``) that every paged program re-stamps from engine-owned
    host state on entry and restores on exit — one tree structure
    across the fused tick ([S] counters, [S, T] tables) and the
    batch-1 chunk prefill (scalar counter, [1, T] table), which is
    what keeps the donated resident buffers shape-stable and the
    program set at zero recompiles.

    Block 0 stays the reserved scratch sink: parked slots' table rows
    are all scratch, so their fixed-shape tick writes land on junk the
    radix index never references.
    """
    if num_blocks < 2:
        raise ValueError(
            f"num_blocks must be >= 2 (block 0 is the reserved scratch "
            f"sink), got {num_blocks}")
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    row = _decode_cache_shapes(dec, 1)

    def _build(tree):
        out = {}
        has_kv = False
        for key, val in tree.items():
            name = str(key)
            if hasattr(val, "items"):
                out[name] = _build(val)
            elif name in CACHE_INDEX_KEYS:
                out[name] = jnp.zeros((), jnp.int32)
            else:
                has_kv = True
                out[name] = jnp.zeros(
                    (num_blocks,) + val.shape[1:-2]
                    + (block_size, val.shape[-1]), val.dtype)
        if has_kv:
            out[BLOCK_TABLE_KEY] = jnp.zeros((1, 1), jnp.int32)
        return out

    return _build(row)


def pool_nbytes(pool) -> int:
    """Device bytes a block pool's leaves occupy — the HBM the engine's
    degraded mode can shed (the number the failure-modes runbook in
    `docs/OPERATIONS.md` reasons about when sizing pools against OOM
    headroom)."""
    return sum(int(leaf.size) * leaf.dtype.itemsize
               for leaf in jax.tree.leaves(pool))


def gather_prefix_into_row(pool, row_cache, block_ids):
    """Copy pool blocks ``block_ids [M]`` into positions
    ``[0, M*block_size)`` of every K/V leaf of a batch-1 row cache
    (counters untouched — the caller stamps them with the true cached
    length; junk from scratch-padded ids beyond it is overwritten by
    the suffix prefill or parked past the position counter)."""

    def _g(path, pool_leaf, row_leaf):
        if is_cache_index_path(path):
            return row_leaf
        pre = cache_blocks_gather(pool_leaf, block_ids)
        return jax.lax.dynamic_update_slice(
            row_leaf, pre.astype(row_leaf.dtype), (0,) * row_leaf.ndim)

    return jax.tree_util.tree_map_with_path(_g, pool, row_cache)


def donate_prefix_blocks(pool, row_cache, block_ids, start_block):
    """Write row-cache tokens ``[start_block*bs, (start_block+M)*bs)``
    into pool blocks ``block_ids [M]`` on every K/V leaf — a finished
    prefill donating its prompt's uncached full blocks. ``start_block``
    is traced; padded ids point at the scratch sink."""

    def _s(path, pool_leaf, row_leaf):
        if is_cache_index_path(path):
            return pool_leaf
        return cache_blocks_scatter(pool_leaf, row_leaf, block_ids,
                                    start_block)

    return jax.tree_util.tree_map_with_path(_s, pool, row_cache)
