"""Host-RAM spill tier under the device radix index (ISSUE 13).

The warm prefix working set of a production fleet exceeds device HBM
by orders of magnitude: the radix index's LRU reclaim used to FREE an
evicted chain, and the next request sharing that prefix paid the whole
prefill again. This module is the missing tier between "in HBM" and
"recompute" (the Mooncake / LMCache shape): eviction becomes a
DEMOTION — the evicted block's K/V is copied D2H into a byte-budgeted
host pool tracked by a second token-keyed index — and admission that
misses HBM but hits host memory PROMOTES the chain back with one H2D
scatter charged against the prefill budget (the r14
``adapter_load_tokens`` precedent) instead of re-running the model.

Division of labor (mirroring `radix.py` / `block_pool.py`):

- :class:`HostTierCache` here is host-side ONLY — numpy block payloads
  under a token-keyed tree with radix-style root-path refcounts and a
  byte-budgeted LRU. It never touches a device array.
- The ENGINE owns the transfers: demotion rides an eager
  ``ops.attention.cache_blocks_gather`` of the dying block (a D2H read
  of one small ``[1, ..., block_size, D]`` slice per leaf — the pool is
  never copied), promotion rides ONE jitted
  ``ops.attention.cache_blocks_scatter`` over the pool tree (the
  ``host_promote`` site, fixed padded shapes, donated pool — zero
  recompiles by construction). No new model-compute program exists in
  either direction.

Tree shape: like the device radix index, every node owns exactly one
``block_size``-token chunk, keyed by its tokens, so a root path spells
a prefix. Nodes are STRUCTURAL (``data is None``) when their block
lives elsewhere (still in HBM, or already re-evicted from the tier) —
device eviction is leaf-first, so chains spill tip-first while their
roots stay resident, and a structural ancestor is exactly how the tier
represents "the device still holds this part". A host match therefore
EXTENDS a device match: :meth:`HostTierCache.match_from` walks from the
device-matched depth and returns the deepest node reachable through
CONTIGUOUS data-bearing children (a hole ends the promotable chain).

Recency ordering survives demotion for free: the device reclaim evicts
least-recently-used leaves first, so they receive the earliest host LRU
stamps and are the first the byte budget sheds — the tier's eviction
order is the device's, one level colder.

Refcount discipline (the same contract `radix.py` holds, and the one
the graftlint ``pin-release`` rule machine-checks): a promotion PINS
the host chain (:meth:`pin_chain` — the acquire the rule's vocabulary
knows) for exactly the span of the H2D dispatch, and every fault/
cancel/unwind path releases it through :meth:`unpin`; the byte
budget's eviction can never free a pinned block out from under an
in-flight promotion.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class HostTierConfig:
    """Engine-facing host-tier knobs (``ServeEngine(host_tier=...)``).

    Args:
      byte_budget: host bytes the tier may hold resident; ``0`` disables
        the tier entirely — the engine is then bit-identical to one
        built without the argument (the cold-path contract).
      promote_tokens_per_block: prefill-budget charge per PROMOTED
        block, through the scheduler's tenancy-aware ``cost_fn`` — the
        H2D transfer is admission-path work like a cold adapter load,
        but far cheaper than prefilling ``block_size`` tokens, so the
        default prices one block of promotion well under one block of
        prefill (docs/OPERATIONS.md § "Host tier sizing" tunes it).
      min_chain_blocks: spill-worthiness floor — an evicted chain
        shorter than this many blocks is freed, not demoted (short
        chains repay a D2H+H2D round trip worst; recency needs no knob
        because LRU eviction order IS the recency score and it carries
        into the tier's own LRU, see the module docstring).
    """

    byte_budget: int
    promote_tokens_per_block: int = 2
    min_chain_blocks: int = 1

    def __post_init__(self):
        if self.byte_budget < 0:
            raise ValueError(
                f"byte_budget must be >= 0, got {self.byte_budget}")
        if self.promote_tokens_per_block < 0:
            raise ValueError(
                f"promote_tokens_per_block must be >= 0, got "
                f"{self.promote_tokens_per_block}")
        if self.min_chain_blocks < 1:
            raise ValueError(
                f"min_chain_blocks must be >= 1, got "
                f"{self.min_chain_blocks}")


class _HostNode:
    """One host-tier block: ``key`` its token tuple, ``data`` the
    per-leaf numpy payloads (``None`` = structural), ``depth`` its
    block count from the root (root = 0)."""

    __slots__ = ("key", "parent", "children", "ref", "last_access",
                 "depth", "data", "nbytes")

    def __init__(self, key: Optional[tuple], parent: Optional["_HostNode"],
                 depth: int):
        self.key = key
        self.parent = parent
        self.children: Dict[tuple, "_HostNode"] = {}
        self.ref = 0
        self.last_access = 0
        self.depth = depth
        self.data: Optional[Dict[str, np.ndarray]] = None
        self.nbytes = 0


class HostTierCache:
    """Byte-budgeted pinned-host-memory tier under the device radix
    index (module docstring).

    Args:
      block_size: tokens per block — must match the device index's.
      byte_budget: resident-payload cap; the LRU sheds beyond it.
      min_chain_blocks: see :class:`HostTierConfig`.
      leaf_spec: ``{leaf_key: (shape, dtype)}`` of one block's payload
        per KV leaf — the engine derives it from its pool tree, and
        :meth:`store` validates every payload against it, so a
        malformed replica-to-replica chain import is refused here
        instead of corrupting a later promotion.
    """

    def __init__(self, block_size: int, byte_budget: int, *,
                 min_chain_blocks: int = 1,
                 leaf_spec: Optional[Dict[str, Tuple[tuple, object]]]
                 = None):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if byte_budget < 1:
            raise ValueError(
                f"byte_budget must be >= 1 (0 disables the tier at the "
                f"engine, not here), got {byte_budget}")
        self.block_size = int(block_size)
        self.byte_budget = int(byte_budget)
        self.min_chain_blocks = int(min_chain_blocks)
        self.leaf_spec = dict(leaf_spec) if leaf_spec is not None else None
        self._root = _HostNode(None, None, 0)
        self._now = 0
        self.bytes_resident = 0
        self.blocks_resident = 0
        self.spills = 0      # blocks that entered the tier (ever)
        self.evictions = 0   # blocks the byte budget hard-freed
        self.pins_outstanding = 0  # live pin_chain/pin acquisitions

    # ------------------------------------------------------------ clock
    def _tick(self) -> int:
        self._now += 1
        return self._now

    # ------------------------------------------------------------ policy
    def spill_worthy(self, depth_blocks: int) -> bool:
        """The demotion policy's length score (recency is implicit:
        LRU eviction order carries into the tier's own LRU, so colder
        chains are shed first without a second knob)."""
        return depth_blocks >= self.min_chain_blocks

    # ------------------------------------------------------------- walk
    def _descend(self, tokens: Sequence[int], blocks: int,
                 create: bool) -> Optional[_HostNode]:
        """Walk (optionally creating structural nodes) ``blocks`` levels
        along ``tokens``; None when a level is missing and ``create``
        is off."""
        node = self._root
        bs = self.block_size
        for j in range(blocks):
            key = tuple(int(t) for t in tokens[j * bs:(j + 1) * bs])
            if len(key) != bs:
                return None
            child = node.children.get(key)
            if child is None:
                if not create:
                    return None
                child = _HostNode(key, node, j + 1)
                node.children[key] = child
            node = child
        return node

    def has_block(self, tokens: Sequence[int]) -> bool:
        """True when the tier already holds the payload of the chain's
        DEEPEST block (``len(tokens)`` must be a block multiple) — the
        engine's demotion hook checks this before paying a D2H gather
        for a block the tier kept across a promotion."""
        node = self._descend(tokens, len(tokens) // self.block_size,
                             create=False)
        return node is not None and node.data is not None

    # ------------------------------------------------------------- store
    def store(self, tokens: Sequence[int],
              data: Dict[str, np.ndarray]) -> bool:
        """Attach one demoted block's payload at the chain's deepest
        node (structural ancestors created as needed), LRU-evicting
        unpinned payloads past the byte budget. Returns False — and
        stores nothing — when the node is already populated, the
        payload fails the ``leaf_spec`` validation, or the budget
        cannot fit it even empty (demotion is opportunistic: a refused
        spill degrades to the old free-and-recompute path, never to an
        error)."""
        blocks = len(tokens) // self.block_size
        if blocks < 1 or len(tokens) % self.block_size != 0:
            return False
        if self.leaf_spec is not None:
            if set(data) != set(self.leaf_spec):
                return False
            for key, arr in data.items():
                shape, dtype = self.leaf_spec[key]
                if tuple(arr.shape) != tuple(shape) \
                        or arr.dtype != np.dtype(dtype):
                    return False
        nbytes = sum(int(arr.nbytes) for arr in data.values())
        if nbytes > self.byte_budget:
            return False
        existing = self._descend(tokens, blocks, create=False)
        if existing is not None and existing.data is not None:
            existing.last_access = self._tick()
            return False
        if self.bytes_resident + nbytes > self.byte_budget:
            self._evict_bytes(self.bytes_resident + nbytes
                              - self.byte_budget)
            if self.bytes_resident + nbytes > self.byte_budget:
                return False  # everything else is pinned
        # Create the target AFTER the eviction pass: ``_evict_bytes``
        # prunes empty structural nodes, so a node created first could
        # be deleted out of the tree mid-store (demotion is leaf-first,
        # so at a full budget the LRU victim is exactly the incoming
        # block's own descendant) — the payload would then attach to a
        # detached node: unreachable, unevictable, budget leaked.
        node = self._descend(tokens, blocks, create=True)
        node.data = {k: np.asarray(v) for k, v in data.items()}
        node.nbytes = nbytes
        node.last_access = self._tick()
        self.bytes_resident += nbytes
        self.blocks_resident += 1
        self.spills += 1
        return True

    def _evict_bytes(self, need: int) -> None:
        """Hard-free unpinned payloads, least recently used first,
        until ``need`` bytes are recovered or everything left is
        pinned. Data-less leaves prune so the structural skeleton
        cannot outgrow the payloads it once carried."""
        victims: List[_HostNode] = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.data is not None and node.ref == 0:
                victims.append(node)
        victims.sort(key=lambda v: v.last_access)
        for victim in victims:
            if need <= 0:
                break
            need -= victim.nbytes
            self.bytes_resident -= victim.nbytes
            self.blocks_resident -= 1
            self.evictions += 1
            victim.data = None
            victim.nbytes = 0
            self._prune(victim)

    def _prune(self, node: _HostNode) -> None:
        while (node is not self._root and node.data is None
               and not node.children and node.ref == 0):
            parent = node.parent
            del parent.children[node.key]
            node = parent

    # ------------------------------------------------------------- match
    def match_from(self, tokens: Sequence[int], start_block: int,
                   max_blocks: int) -> Optional[_HostNode]:
        """Deepest node reachable from depth ``start_block`` through
        consecutive DATA-bearing children (at most ``max_blocks`` of
        them), refreshing LRU stamps; None on a miss. Depths up to
        ``start_block`` need only exist structurally — those blocks are
        the device match the promotion extends."""
        if max_blocks < 1:
            return None
        anchor = self._descend(tokens, start_block, create=False)
        if anchor is None:
            return None
        now = self._tick()
        bs = self.block_size
        node, depth = anchor, start_block
        while depth - start_block < max_blocks:
            key = tuple(int(t) for t in tokens[depth * bs:
                                               (depth + 1) * bs])
            if len(key) != bs:
                break
            child = node.children.get(key)
            if child is None or child.data is None:
                break
            node = child
            node.last_access = now
            depth += 1
        return node if depth > start_block else None

    def match_depth(self, tokens: Sequence[int], start_block: int,
                    max_blocks: int) -> int:
        """Promotable block count (the scheduler cost estimator's view
        — no pin, no stamp mutation beyond the LRU refresh)."""
        node = self.match_from(tokens, start_block, max_blocks)
        return 0 if node is None else node.depth - start_block

    # --------------------------------------------------------- refcounts
    def pin_chain(self, tokens: Sequence[int], start_block: int,
                  max_blocks: int) -> Optional[_HostNode]:
        """Match AND pin in one step — THE host-tier acquire (the
        graftlint ``pin-release`` rule tracks this verb): the returned
        tip (``.depth`` tells the caller how far it reaches) must be
        :meth:`unpin`-ed exactly once on every path out of the
        promotion, fault-unwind included. None acquires nothing."""
        node = self.match_from(tokens, start_block, max_blocks)
        if node is not None:
            self.pin(node)
        return node

    def pin(self, node: _HostNode) -> None:
        """Protect ``node`` and its root path from the byte budget's
        eviction (one live user, radix-style)."""
        self.pins_outstanding += 1
        while node is not self._root:
            node.ref += 1
            node = node.parent

    def unpin(self, node: _HostNode) -> None:
        self.pins_outstanding -= 1
        while node is not self._root:
            if node.ref <= 0:
                raise RuntimeError(
                    "host-tier unpin without a matching pin (refcount "
                    "underflow) — a promotion released its chain twice")
            node.ref -= 1
            node = node.parent

    # --------------------------------------------------------- payloads
    def chain_data(self, tip: _HostNode,
                   n_blocks: int) -> List[Dict[str, np.ndarray]]:
        """Payloads of the ``n_blocks`` deepest blocks ending at
        ``tip``, root-first — what the promotion scatters H2D. Raises
        if any of them is structural (callers hold the pin from
        :meth:`pin_chain`, whose match guaranteed contiguous data)."""
        out: List[Dict[str, np.ndarray]] = []
        node = tip
        for _ in range(n_blocks):
            if node is None or node.data is None:
                raise RuntimeError(
                    "host-tier chain lost a payload under a pin "
                    "(tier bug)")
            out.append(node.data)
            node = node.parent
        out.reverse()
        return out
