"""Host-side radix index over prompt token ids → KV block chains.

The RadixAttention idea (SGLang), at block granularity (vLLM's paged
unit): a tree whose every node owns exactly ONE pool block — the K/V of
``block_size`` tokens — keyed by those tokens, so a root-to-node path
spells a prompt prefix and the path's block ids are the chain the
engine gathers into a new request's slot. Host-side only: the tree
holds ids and token tuples, never device arrays.

Invariants (property-tested in ``tests/test_prefix_cache.py``):

- **Accounting**: every non-scratch pool block is either on the free
  list or owned by exactly one live node; ``blocks_live + blocks_free
  == num_blocks - 1`` at all times.
- **Refcounts**: ``pin(node)`` increments every node on the root path,
  ``unpin`` decrements it; a request pins the deepest node it matched
  or extended for its whole slot residency, so every ancestor of an
  in-use chain is protected.
- **Eviction**: only LEAF nodes with ``ref == 0`` are evictable, least
  recently accessed first — an interior node always outlives its
  children, so a stored chain can never lose an ancestor block while a
  descendant (or a pinned user) remains.

Single-threaded by design, like the engine that drives it: the engine
is caller-driven (``step()``), so no locking — and because the device
GATHER copies blocks into the slot before admission returns, eviction
of an unpinned chain is always safe even if a past hit is still
decoding from its private copy.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from pddl_tpu.serve.kvcache.block_pool import SCRATCH_BLOCK


class _Node:
    """One cached block: ``key`` is its block's token tuple, ``block_id``
    its pool row. The root is a sentinel (no key, no block)."""

    __slots__ = ("key", "block_id", "parent", "children", "ref",
                 "last_access")

    def __init__(self, key: Optional[tuple], block_id: Optional[int],
                 parent: Optional["_Node"]):
        self.key = key
        self.block_id = block_id
        self.parent = parent
        self.children: Dict[tuple, "_Node"] = {}
        self.ref = 0
        self.last_access = 0


@dataclasses.dataclass
class PrefixMatch:
    """Longest stored chain for a prompt: ``node`` is the deepest match
    (the root for a full miss), ``block_ids`` its root path's blocks."""

    node: _Node
    block_ids: List[int]

    @property
    def n_blocks(self) -> int:
        return len(self.block_ids)


class RadixPrefixCache:
    """Refcounted, LRU-evicted radix index over a block pool.

    Args:
      block_size: tokens per block (the pool's token granularity).
      num_blocks: pool rows INCLUDING the reserved scratch sink (id 0),
        so ``num_blocks - 1`` blocks are allocatable.
    """

    def __init__(self, block_size: int, num_blocks: int):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block {SCRATCH_BLOCK} is the "
                f"scratch sink), got {num_blocks}")
        self.block_size = block_size
        self.num_blocks = num_blocks
        self._free: Deque[int] = deque(range(1, num_blocks))
        self._root = _Node(None, None, None)
        self._clock = itertools.count(1)
        self.evictions = 0
        # Demotion hook (ISSUE 13, `kvcache/hosttier.py`): called once
        # per reclaim pass with the LIST of victims BEFORE their block
        # ids are freed — each node still attached (parent chain
        # walkable, block id readable), so the engine can spill the
        # blocks' K/V D2H into the host tier in ONE batched gather
        # (per-victim calls measured ~7x slower on the admission
        # path). The callback must not touch this index. None
        # (default) keeps eviction a plain free; the degraded flush
        # NEVER calls it (`flush_unpinned` — spilling during an OOM
        # response would defeat the shedding).
        self.on_evict = None

    # ------------------------------------------------------------ stats
    @property
    def blocks_free(self) -> int:
        return len(self._free)

    @property
    def blocks_live(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    # ------------------------------------------------------------ match
    def match(self, tokens: Sequence[int],
              max_blocks: Optional[int] = None) -> PrefixMatch:
        """Walk the longest stored chain of full-block matches of
        ``tokens`` (optionally capped at ``max_blocks``), refreshing the
        chain's LRU stamps. Never pins — callers pin explicitly."""
        now = next(self._clock)
        node = self._root
        ids: List[int] = []
        limit = len(tokens) // self.block_size
        if max_blocks is not None:
            limit = min(limit, max_blocks)
        for j in range(limit):
            key = tuple(int(t) for t in
                        tokens[j * self.block_size:(j + 1) * self.block_size])
            child = node.children.get(key)
            if child is None:
                break
            node = child
            node.last_access = now
            ids.append(node.block_id)
        return PrefixMatch(node, ids)

    def descend(self, node: _Node, tokens: Sequence[int],
                start_block: int) -> Tuple[_Node, int]:
        """Walk already-stored children of ``node`` along ``tokens``
        from ``start_block`` on, refreshing LRU stamps; returns the
        deepest stored node and its block depth. The donation-side
        dedup: chunks the index already holds (e.g. beyond a capped
        gather match, or stored by an earlier identical prompt) must
        not have fresh blocks allocated — under a full pool that
        allocation would LRU-evict a USEFUL block to supply one that
        ``extend`` would immediately hand back."""
        now = next(self._clock)
        j = start_block
        while True:
            key = tuple(int(t) for t in
                        tokens[j * self.block_size:
                               (j + 1) * self.block_size])
            if len(key) != self.block_size:
                break
            child = node.children.get(key)
            if child is None:
                break
            node = child
            node.last_access = now
            j += 1
        return node, j

    def chain_tokens(self, node: _Node) -> List[int]:
        """Root-path token ids of ``node`` (``depth * block_size`` of
        them) — the chain identity the host tier (`hosttier.py`) keys a
        demoted block under, and the prompt slice a promotion re-keys
        it back from."""
        keys: List[tuple] = []
        while node is not self._root:
            keys.append(node.key)
            node = node.parent
        keys.reverse()
        return [int(t) for key in keys for t in key]

    def chain_depth(self, node: _Node) -> int:
        """Block count of ``node``'s root path (0 for the root)."""
        depth = 0
        while node is not self._root:
            depth += 1
            node = node.parent
        return depth

    def chain_ids(self, node: _Node) -> List[int]:
        """Root-path block ids of ``node``, root-first — the stored
        chain a paged slot's block table must point at after donation
        (the paged engine swaps duplicate private blocks onto the
        stored chain; token-identity implies bit-identical KV, so the
        swap is token-exact by the position-absolute cache contract)."""
        ids: List[int] = []
        while node is not self._root:
            ids.append(node.block_id)
            node = node.parent
        ids.reverse()
        return ids

    # -------------------------------------------------------- refcounts
    def pin(self, node: _Node) -> None:
        """Protect ``node`` and its whole root path from eviction (one
        live user). Pinning the root is a no-op chain of length 0."""
        while node is not self._root:
            node.ref += 1
            node = node.parent

    def unpin(self, node: _Node) -> None:
        while node is not self._root:
            if node.ref <= 0:
                raise RuntimeError(
                    "unpin without a matching pin (refcount underflow) — "
                    "an engine slot released its prefix chain twice")
            node.ref -= 1
            node = node.parent

    def flush_unpinned(self) -> int:
        """Degraded-mode flush: evict EVERY unpinned block (the chains
        live slots still pin stay — their gathered copies are already
        private, but their index entries must remain consistent until
        unpin). Returns the number of blocks freed. Used by the engine
        when a RESOURCE_EXHAUSTED surfaces: the prefix cache is the one
        large optional HBM consumer, so shedding it is the graceful
        response before any request has to fail.

        BYPASSES demotion deliberately (``demote=False`` below): this
        path runs inside the OOM response, where the point is to shed
        work, and a D2H spill per evicted block would spend transfers
        — and host memory — exactly when the engine is trying to
        survive. Degraded-mode eviction is a hard free, pinned
        discriminatively by ``tests/test_kv_tier.py``."""
        before = self.blocks_free
        self._reclaim(self.blocks_live, demote=False)
        return self.blocks_free - before

    # ------------------------------------------------------- allocation
    def release(self, block_ids: List[int]) -> None:
        """Return ids from :meth:`allocate` that were never attached via
        :meth:`extend` (a failed donation unwinding). Releasing an
        attached block this way would double-own it — that path must go
        through eviction instead."""
        for bid in block_ids:
            if bid == SCRATCH_BLOCK:
                raise ValueError("the scratch block is never allocated")
            self._free.append(bid)

    def allocate(self, n: int) -> List[int]:
        """Up to ``n`` free block ids, LRU-evicting unpinned leaves as
        needed. May return FEWER than asked (everything else is pinned)
        — the caller donates a shorter chain prefix, never fails."""
        if len(self._free) < n:
            self._reclaim(n - len(self._free))
        take = min(n, len(self._free))
        return [self._free.popleft() for _ in range(take)]

    def _reclaim(self, need: int, demote: bool = True) -> None:
        """Evict up to ``need`` unpinned LEAVES, least recently accessed
        first. One DFS collects the whole evictable set per pass (not
        one full-tree scan PER block — allocation bursts sit on the
        admission/TTFT path); evicting a leaf can expose its parent as
        a new evictable leaf, so passes repeat until satisfied or
        nothing is evictable.

        With a demotion hook installed (``on_evict``), eviction is a
        POLICY DECISION rather than a free: the WHOLE reclaim's victim
        set — all passes, eviction order — is offered to the hook in
        ONE call, still attached, block ids still valid, before any id
        returns to the free list, so reuse-worthy chains spill to the
        host tier instead of dying and the spill's D2H read is one
        batched transfer per allocation shortfall rather than one per
        pass (passes often take 1-2 leaves each, and the hook's
        device round trip sits on the admission path). Victims are
        marked, not freed, between passes, so exposing a parent as the
        next pass's leaf needs no tree mutation before the hook runs.
        ``demote=False`` (the degraded flush) skips the hook
        unconditionally."""
        call_hook = demote and self.on_evict is not None
        all_taken: List[_Node] = []
        marked = set()
        while need > 0:
            victims = []
            stack = [self._root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if (node is not self._root and node.ref == 0
                        and id(node) not in marked
                        and all(id(c) in marked
                                for c in node.children.values())):
                    victims.append(node)
            if not victims:
                break
            victims.sort(key=lambda v: v.last_access)
            taken = victims[:need]
            all_taken.extend(taken)
            marked.update(id(v) for v in taken)
            need -= min(need, len(victims))
        if not all_taken:
            return
        if call_hook:
            self.on_evict(all_taken)
        for victim in all_taken:
            del victim.parent.children[victim.key]
            self._free.append(victim.block_id)
            self.evictions += 1

    # --------------------------------------------------------- insertion
    def extend(self, node: _Node, tokens: Sequence[int],
               block_ids: Sequence[int]) -> _Node:
        """Attach ``len(block_ids)`` new child blocks under ``node``,
        one per consecutive ``block_size``-token chunk of ``tokens``
        (the donated suffix blocks, in chain order). Returns the new
        chain tip. ``tokens`` may cover more chunks than ids (a partial
        donation when the allocator ran dry); extra chunks are simply
        not stored."""
        now = next(self._clock)
        for j, bid in enumerate(block_ids):
            if bid == SCRATCH_BLOCK:
                raise ValueError("the scratch block cannot join the index")
            key = tuple(int(t) for t in
                        tokens[j * self.block_size:(j + 1) * self.block_size])
            if len(key) != self.block_size:
                raise ValueError(
                    f"chunk {j} has {len(key)} tokens, need a full "
                    f"{self.block_size}-token block")
            if key in node.children:
                # A concurrent admission in the same tick already stored
                # this chunk: keep the existing node, return the id to
                # the free list (ours was never written into the tree).
                self._free.append(bid)
                node = node.children[key]
            else:
                child = _Node(key, bid, node)
                node.children[key] = child
                node = child
            node.last_access = now
        return node
