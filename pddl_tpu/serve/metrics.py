"""Serving telemetry: the numbers an online engine is judged by.

Single-request serving is judged by tokens/s; ONLINE serving is judged
by the latency/throughput trade under load — so the engine records, per
tick and per request:

- **TTFT** (time to first token, queue wait included) — the user-felt
  responsiveness number; p50/p99 because the tail IS the product.
- **per-token latency** — inter-token gap once streaming.
- **queue depth / slot occupancy** — the load signals the admission
  knobs (`scheduler.py`) act on.
- **tokens/s** — aggregate decoded throughput over the engine's active
  window.

Exposed through the existing :mod:`pddl_tpu.utils.summary` plumbing
(:func:`~pddl_tpu.utils.summary.format_table`) for humans, and as a
plain dict (:meth:`ServeMetrics.snapshot`) for benches/dashboards —
`benchmarks/serve_bench.py` writes the snapshot into the repo's
standard JSON-artifact shape.
"""

from __future__ import annotations

import random
from collections import deque
from typing import Deque, Dict, Iterable, List, Optional

import numpy as np

from pddl_tpu.serve.request import Priority
from pddl_tpu.utils.summary import format_table

# Stable label vocabulary for the per-priority splits: every class is
# always present (zeros included) so the Prometheus exposition's label
# sets never appear/vanish with traffic.
PRIORITY_CLASSES = tuple(p.value for p in Priority)


def _pct(values, q: float) -> Optional[float]:
    vals = list(values)
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals, np.float64), q))


class Reservoir:
    """Fixed-capacity uniform sample of an unbounded stream (Vitter's
    algorithm R): after ``n`` observations every observation has
    ``cap/n`` probability of being in the buffer, so percentiles and
    means over the buffer estimate the WHOLE stream — which is what
    keeps ``ServeMetrics.snapshot()`` stable while memory stays capped
    under sustained load (the plain lists it replaces grew forever).

    List-enough for the recording paths (``append``/``extend``/
    ``len``/iteration/truthiness); seeded, so the same workload
    snapshots the same numbers.
    """

    __slots__ = ("cap", "count", "_buf", "_rng")

    def __init__(self, cap: int = 8192, seed: int = 0):
        if cap < 1:
            raise ValueError(f"reservoir cap must be >= 1, got {cap}")
        self.cap = int(cap)
        self.count = 0  # total observed (>= len once capped)
        self._buf: List[float] = []
        self._rng = random.Random(seed)

    def append(self, value) -> None:
        self.count += 1
        if len(self._buf) < self.cap:
            self._buf.append(value)
        else:
            j = self._rng.randrange(self.count)
            if j < self.cap:
                self._buf[j] = value

    def extend(self, values: Iterable) -> None:
        for v in values:
            self.append(v)

    def __len__(self) -> int:
        return len(self._buf)

    def __iter__(self):
        return iter(self._buf)

    def __bool__(self) -> bool:
        return bool(self._buf)


class ServeMetrics:
    """Accumulates engine telemetry; cheap enough to leave always-on
    (a few floats per tick — never a device sync of its own). The
    per-sample series (TTFT, token latency, queue depth, occupancy)
    live in capped :class:`Reservoir`\\ s — ``reservoir_cap`` samples
    each, default ~8k — so a week of sustained load holds the same
    memory as a minute while ``snapshot()`` percentiles keep estimating
    the full stream."""

    def __init__(self, reservoir_cap: int = 8192) -> None:
        self.reservoir_cap = int(reservoir_cap)
        self.ttft_s = Reservoir(self.reservoir_cap, seed=0)
        self.token_latency_s = Reservoir(self.reservoir_cap, seed=1)
        self.queue_depth = Reservoir(self.reservoir_cap, seed=2)
        self.occupancy = Reservoir(self.reservoir_cap, seed=3)
        # Per-priority splits (the SLO dashboard: is `interactive`
        # actually protected, is `best_effort` actually absorbing the
        # shedding?). TTFT reservoirs per class plus finish/shed/reject
        # counters; exported as labeled Prometheus series.
        self.ttft_by_priority: Dict[str, Reservoir] = {
            cls: Reservoir(self.reservoir_cap, seed=10 + i)
            for i, cls in enumerate(PRIORITY_CLASSES)}
        self.finished_by_priority: Dict[str, int] = dict.fromkeys(
            PRIORITY_CLASSES, 0)
        self.deadline_shed_by_priority: Dict[str, int] = dict.fromkeys(
            PRIORITY_CLASSES, 0)
        self.rejected_by_priority: Dict[str, int] = dict.fromkeys(
            PRIORITY_CLASSES, 0)
        self.tokens_emitted = 0
        self.requests_finished = 0
        self.requests_rejected = 0
        self.requests_timed_out = 0
        self.requests_cancelled = 0
        # Prefix-cache telemetry (all zero when the cache is disabled):
        # one lookup per admission, hits counted at block granularity —
        # `prefill_tokens_saved` is the cached-token total the engine
        # did NOT re-prefill, the cache's whole value in one number.
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefill_tokens_saved = 0
        self.prefix_evictions = 0
        self.prefix_blocks_live = 0  # gauge, engine-stamped per admission
        # Paged-attention telemetry (all zero on a copy-mode engine):
        # `copy_bytes_avoided` counts the pool->slot gather bytes a
        # prefix hit did NOT copy (matched tokens x per-token KV
        # bytes — the admission work paging deletes); `blocks_shared`
        # is the live gauge of pool blocks referenced by >1 slot
        # (each one a block the copy engine would hold once PER slot —
        # the capacity-doubling number); `block_table_fill` is the
        # mean occupied fraction of live slots' block tables.
        self.copy_bytes_avoided = 0
        self.blocks_shared = 0       # gauge, engine-stamped per tick
        self.block_table_fill = 0.0  # gauge, engine-stamped per tick
        # Tiered-KV-cache telemetry (`serve/kvcache/hosttier.py`; all
        # zero without a host tier): blocks demoted into the tier
        # (chain imports from replica pulls included), admissions whose
        # host match promoted >= 1 block, blocks promoted back H2D,
        # prefill-budget tokens those promotions were charged (the
        # adapter_load_tokens precedent), and the resident-byte gauge
        # the sizing runbook watches against the byte budget.
        self.host_tier_spills = 0
        self.host_tier_hits = 0
        self.host_tier_promotions = 0
        self.host_tier_promote_tokens_charged = 0
        self.host_tier_bytes_resident = 0  # gauge, engine-stamped
        # Multi-tenant telemetry (`serve/tenant/`; all zero on a plain
        # engine): adapter pool hits vs cold loads (the hit RATE is the
        # runbook's pool-sizing signal), LRU evictions under pressure,
        # a live residency gauge, per-adapter admission counts as a
        # labeled series, and the constrained-decoding counters.
        self.adapter_hits = 0        # admission found the adapter resident
        self.adapter_loads = 0       # cold host->device factor loads
        self.adapter_evictions = 0   # LRU evictions of unpinned rows
        self.adapter_pool_resident = 0  # gauge, engine-stamped
        self.requests_by_adapter: Dict[str, int] = {}
        self.constrained_requests = 0    # submissions carrying a spec
        self.requests_grammar_complete = 0  # FinishReason.GRAMMAR settles
        # Speculative-serving telemetry (engine ``spec_k > 0``; all
        # zero on a classic engine): verify windows dispatched, draft
        # tokens offered for acceptance (per-slot caps summed — sampled
        # rows and replay re-feeds offer none), and draft tokens the
        # verifier accepted. The acceptance RATE (accepted/drafted) is
        # the runbook's k-tuning signal: it falls as k grows past the
        # workload's self-similarity, and the throughput win follows it.
        self.spec_ticks = 0
        self.spec_drafted_tokens = 0
        self.spec_accepted_tokens = 0
        # Resilience telemetry (`serve/faults.py`, engine retry/replay/
        # degraded paths): all zero on a fault-free engine.
        self.retries = 0             # failed device calls retried
        self.retry_sites: Dict[str, int] = {}
        self.replays = 0             # slot-state rebuilds (KV recomputed)
        self.preemptions = 0         # best_effort slots parked for
        #                              queued interactive work
        self.requests_failed = 0     # terminal FinishReason.ERROR
        self.requests_deadline_shed = 0  # FinishReason.DEADLINE at pop
        self.degraded_entries = 0    # times the engine flipped degraded
        self.degraded_time_s = 0.0   # wall time spent degraded (closed
        #                              intervals; re-arm stamps them)
        # Recent admission timestamps: the QueueFull retry_after_s
        # estimator (a short window so the hint tracks CURRENT service
        # rate, not the all-time average).
        self._admission_times: Deque[float] = deque(maxlen=32)
        self._first_activity_s: Optional[float] = None
        self._last_activity_s: Optional[float] = None

    # ------------------------------------------------------ recording
    def record_tick(self, now_s: float, queue_depth: int, live_slots: int,
                    total_slots: int, new_tokens: int,
                    tick_seconds: float) -> None:
        self.queue_depth.append(queue_depth)
        self.occupancy.append(live_slots / max(total_slots, 1))
        self.tokens_emitted += new_tokens
        if new_tokens:
            # One fused tick serves every live slot, so the inter-token
            # gap each STREAM sees is the whole tick's wall time — one
            # sample per token emitted this tick.
            self.token_latency_s.extend([tick_seconds] * new_tokens)
        if self._first_activity_s is None:
            self._first_activity_s = now_s
        self._last_activity_s = now_s

    def record_first_token(self, ttft_s: float,
                           priority: Optional[str] = None) -> None:
        self.ttft_s.append(ttft_s)
        if priority in self.ttft_by_priority:
            self.ttft_by_priority[priority].append(ttft_s)
        self.tokens_emitted += 1

    def record_finish(self, reason_value: str,
                      priority: Optional[str] = None) -> None:
        """One request departed. ``requests_finished`` counts ONLY
        successful completions (length/eos); cancellations, timeouts,
        pop-time deadline sheds, and fault failures each go to their
        own counter — all disjoint, so a success rate is finished /
        (finished + cancelled + timed_out + deadline_shed + failed +
        rejected) with no hidden convention. ``priority`` (a
        :class:`~pddl_tpu.serve.request.Priority` value string) feeds
        the per-class finish/shed splits."""
        if reason_value == "timed_out":
            self.requests_timed_out += 1
        elif reason_value == "deadline":
            self.requests_deadline_shed += 1
            if priority in self.deadline_shed_by_priority:
                self.deadline_shed_by_priority[priority] += 1
        elif reason_value == "cancelled":
            self.requests_cancelled += 1
        elif reason_value == "error":
            self.requests_failed += 1
        else:
            self.requests_finished += 1
            if reason_value == "grammar":
                # A grammar-complete stream is a SUCCESS (the FSM ran
                # out of legal continuations because the output is a
                # complete document) — counted inside finished, plus
                # its own counter so the tenant dashboard can tell
                # grammar closure from eos/length.
                self.requests_grammar_complete += 1
            if priority in self.finished_by_priority:
                self.finished_by_priority[priority] += 1

    def record_rejected(self, priority: Optional[str] = None) -> None:
        self.requests_rejected += 1
        if priority in self.rejected_by_priority:
            self.rejected_by_priority[priority] += 1

    # ------------------------------------------------------- resilience
    def record_retry(self, site: str) -> None:
        self.retries += 1
        self.retry_sites[site] = self.retry_sites.get(site, 0) + 1

    def record_replay(self) -> None:
        self.replays += 1

    def record_preemption(self) -> None:
        self.preemptions += 1

    def record_degraded_entry(self) -> None:
        self.degraded_entries += 1

    def record_degraded_exit(self, seconds: float) -> None:
        self.degraded_time_s += max(0.0, float(seconds))

    def record_admission(self, now_s: float) -> None:
        """One FRESH request admitted (replays excluded — they consume
        admission work but represent no new queue progress, and the
        retry_after hint estimates how fast the queue drains)."""
        self._admission_times.append(float(now_s))

    def recent_admission_interval_s(self) -> Optional[float]:
        """Mean gap between recent admissions, or ``None`` before two
        were observed."""
        times = self._admission_times
        if len(times) < 2:
            return None
        return (times[-1] - times[0]) / (len(times) - 1)

    def estimate_retry_after_s(self, queue_depth: int) -> Optional[float]:
        """The QueueFull backpressure hint: the queue ahead of a new
        arrival times the recent per-admission interval — roughly when
        a queue slot frees up. An estimate from a sliding window, not a
        promise; ``None`` until the engine has admitted twice."""
        interval = self.recent_admission_interval_s()
        if interval is None:
            return None
        return max(interval, 0.0) * max(int(queue_depth), 1)

    def record_prefix_lookup(self, tokens_saved: int, *, blocks_live: int,
                             evictions: int) -> None:
        """One admission-time prefix-cache lookup: ``tokens_saved`` is
        the matched (not re-prefilled) token count, 0 for a miss;
        ``blocks_live``/``evictions`` snapshot the pool state so the
        gauges need no separate plumbing."""
        self.prefix_lookups += 1
        if tokens_saved > 0:
            self.prefix_hits += 1
            self.prefill_tokens_saved += int(tokens_saved)
        self.prefix_blocks_live = int(blocks_live)
        self.prefix_evictions = int(evictions)

    def record_copy_avoided(self, nbytes: int) -> None:
        """One paged prefix hit referenced ``nbytes`` of matched KV in
        place instead of gathering it into a slot row."""
        self.copy_bytes_avoided += int(nbytes)

    def record_paged_gauges(self, blocks_shared: int,
                            block_table_fill: float) -> None:
        """Per-tick paged sharing/occupancy gauges (engine-stamped)."""
        self.blocks_shared = int(blocks_shared)
        self.block_table_fill = float(block_table_fill)

    # ----------------------------------------------------- tiered cache
    def record_host_spill(self, bytes_resident: int) -> None:
        """One block entered the host tier — a demotion of an LRU
        victim, or a replica-to-replica chain import; ``bytes_resident``
        stamps the residency gauge in passing."""
        self.host_tier_spills += 1
        self.host_tier_bytes_resident = int(bytes_resident)

    def record_host_promotion(self, blocks: int, tokens_charged: int,
                              bytes_resident: int) -> None:
        """One admission promoted ``blocks`` host-tier blocks back into
        the device pool, charged ``tokens_charged`` against the prefill
        budget."""
        self.host_tier_hits += 1
        self.host_tier_promotions += int(blocks)
        self.host_tier_promote_tokens_charged += int(tokens_charged)
        self.host_tier_bytes_resident = int(bytes_resident)

    # ---------------------------------------------------------- tenancy
    def record_adapter_hit(self, name: str, resident: int, *,
                           fresh: bool = True) -> None:
        """One admission found its adapter already device-resident;
        ``resident`` stamps the pool-residency gauge in passing.
        ``fresh=False`` (a replay / preemption-resume re-admission)
        still counts pool traffic but NOT per-tenant request volume —
        ``requests_by_adapter`` is the capacity-planning series and
        must count each request once, however many times faults
        re-admit it."""
        self.adapter_hits += 1
        if fresh:
            self.requests_by_adapter[name] = \
                self.requests_by_adapter.get(name, 0) + 1
        self.adapter_pool_resident = int(resident)

    def record_adapter_load(self, name: str, resident: int,
                            evictions: int, *,
                            fresh: bool = True) -> None:
        """One COLD adapter load (host→device factor transfer on the
        admission path); ``evictions`` is the pool's cumulative LRU
        eviction count (stamped, like the prefix cache's). ``fresh``
        as in :meth:`record_adapter_hit` — a replay's reload is real
        pool traffic (it keeps the hit rate honest about thrash) but
        not new request volume."""
        self.adapter_loads += 1
        if fresh:
            self.requests_by_adapter[name] = \
                self.requests_by_adapter.get(name, 0) + 1
        self.adapter_pool_resident = int(resident)
        self.adapter_evictions = int(evictions)

    def record_constrained(self) -> None:
        """One submission carried a grammar/schema constraint."""
        self.constrained_requests += 1

    # ------------------------------------------------------ speculation
    def record_spec_tick(self, drafted: int, accepted: int) -> None:
        """One speculative verify window: ``drafted`` tokens offered
        for acceptance across the batch (sampled rows and forced replay
        re-feeds offer none), ``accepted`` of them taken."""
        self.spec_ticks += 1
        self.spec_drafted_tokens += int(drafted)
        self.spec_accepted_tokens += int(accepted)

    # ------------------------------------------------------ reporting
    def snapshot(self) -> Dict[str, object]:
        """The dashboard dict: counters plus latency percentiles (None
        where nothing was recorded yet)."""
        window = None
        if (self._first_activity_s is not None
                and self._last_activity_s is not None):
            window = self._last_activity_s - self._first_activity_s
        return {
            "requests_finished": self.requests_finished,
            "requests_rejected": self.requests_rejected,
            "requests_timed_out": self.requests_timed_out,
            "requests_cancelled": self.requests_cancelled,
            "tokens_emitted": self.tokens_emitted,
            "tokens_per_s": (self.tokens_emitted / window
                             if window else None),
            "ttft_p50_s": _pct(self.ttft_s, 50),
            "ttft_p99_s": _pct(self.ttft_s, 99),
            "token_latency_p50_s": _pct(self.token_latency_s, 50),
            "token_latency_p99_s": _pct(self.token_latency_s, 99),
            "mean_queue_depth": (float(np.mean(list(self.queue_depth)))
                                 if self.queue_depth else None),
            "mean_slot_occupancy": (float(np.mean(list(self.occupancy)))
                                    if self.occupancy else None),
            "prefix_lookups": self.prefix_lookups,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": (self.prefix_hits / self.prefix_lookups
                                if self.prefix_lookups else None),
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "prefix_blocks_live": self.prefix_blocks_live,
            "prefix_evictions": self.prefix_evictions,
            "copy_bytes_avoided": self.copy_bytes_avoided,
            "blocks_shared": self.blocks_shared,
            "block_table_fill": round(self.block_table_fill, 6),
            "host_tier_spills": self.host_tier_spills,
            "host_tier_hits": self.host_tier_hits,
            "host_tier_promotions": self.host_tier_promotions,
            "host_tier_promote_tokens_charged":
                self.host_tier_promote_tokens_charged,
            "host_tier_bytes_resident": self.host_tier_bytes_resident,
            "adapter_hits": self.adapter_hits,
            "adapter_loads": self.adapter_loads,
            "adapter_evictions": self.adapter_evictions,
            "adapter_hit_rate": (
                self.adapter_hits / (self.adapter_hits + self.adapter_loads)
                if (self.adapter_hits + self.adapter_loads) else None),
            "adapter_pool_resident": self.adapter_pool_resident,
            "constrained_requests": self.constrained_requests,
            "requests_grammar_complete": self.requests_grammar_complete,
            "spec_ticks": self.spec_ticks,
            "spec_drafted_tokens": self.spec_drafted_tokens,
            "spec_accepted_tokens": self.spec_accepted_tokens,
            "spec_acceptance_rate": (
                self.spec_accepted_tokens / self.spec_drafted_tokens
                if self.spec_drafted_tokens else None),
            # Labeled series: one sample per adapter NAME seen (unlike
            # the priority splits the label set is open — a tenant
            # appears on first admission and never vanishes).
            "requests_by_adapter": dict(self.requests_by_adapter),
            "retries": self.retries,
            # Per-site retry attribution (open label set, like
            # requests_by_adapter): WHERE the transient faults land —
            # recorded since r08 but only exported since the graftlint
            # exposition-parity rule caught it missing here.
            "retry_sites": dict(self.retry_sites),
            "replays": self.replays,
            "preemptions": self.preemptions,
            "requests_failed": self.requests_failed,
            "requests_deadline_shed": self.requests_deadline_shed,
            "degraded_entries": self.degraded_entries,
            "degraded_time_s": round(self.degraded_time_s, 6),
            # Per-priority splits: mappings render as labeled series
            # (one sample per class) through `obs/export.py`, so the
            # SLO runbook reads shed/finish/TTFT per class off one
            # scrape. Every class is always present — a silent zero is
            # a zero, not a vanished label.
            "requests_finished_by_priority": dict(
                self.finished_by_priority),
            "requests_deadline_shed_by_priority": dict(
                self.deadline_shed_by_priority),
            "requests_rejected_by_priority": dict(
                self.rejected_by_priority),
            "ttft_p50_s_by_priority": {
                cls: _pct(r, 50)
                for cls, r in self.ttft_by_priority.items()},
            "ttft_p99_s_by_priority": {
                cls: _pct(r, 99)
                for cls, r in self.ttft_by_priority.items()},
        }

    def summary(self) -> str:
        """Human-readable table via the shared summary plumbing (the
        per-priority mappings flatten to one ``key[class]`` row each)."""
        rows = {}
        for k, v in self.snapshot().items():
            if isinstance(v, dict):
                for cls, cv in v.items():
                    rows[f"{k}[{cls}]"] = "-" if cv is None else cv
            else:
                rows[k] = "-" if v is None else v
        return format_table("Serving metrics:", rows)
