"""Request lifecycle for the online serving engine.

The reference's endpoint is "save the model, then serve it"
(`/root/reference/imagenet-resnet50.py:72`); the batch serving story
(`docs/SERVING.md`) measured the single-request path. This module is
the per-request half of the ONLINE layer: what a caller submits, the
states a request moves through, and the handle it streams tokens from.

Design constraints, inherited from the engine:

- The engine is single-threaded and caller-driven (``engine.step()``),
  so handles need no locking — cancellation is a flag the engine
  honors at its next tick, not a cross-thread interrupt.
- Sampling parameters are PER-REQUEST runtime values (batched into
  ``[slots]`` arrays each tick), never compiled statics — hence the
  array sentinels on :class:`SamplingParams`.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
from typing import List, Optional, Sequence


class Priority(enum.Enum):
    """SLO class of a request — the scheduler's pop order and the
    fleet's admission/brownout ladder both key off it.

    - ``INTERACTIVE``: a human is waiting; protected under overload.
    - ``BATCH``: latency-tolerant but must eventually run (the
      scheduler's anti-starvation aging guarantees it).
    - ``BEST_EFFORT``: sheddable; the first thing a brownout drops.
    """

    INTERACTIVE = "interactive"
    BATCH = "batch"
    BEST_EFFORT = "best_effort"

    @property
    def rank(self) -> int:
        """0 = most urgent. The scheduler sorts ascending on this."""
        return _PRIORITY_RANK[self]


_PRIORITY_RANK = {Priority.INTERACTIVE: 0, Priority.BATCH: 1,
                  Priority.BEST_EFFORT: 2}


class QueueFull(RuntimeError):
    """Typed admission-control rejection: the engine's queue is at its
    ``max_queue_depth``. Carries the depth — and, when the engine has
    seen enough traffic to estimate one, a ``retry_after_s`` hint
    (queue depth x the recent per-admission interval from
    ``ServeMetrics``) — so upstream backpressure can be polite
    (honor the hint) instead of blind hammering, without parsing
    strings. ``retry_after_s`` is ``None`` before the estimator warms
    up (fewer than two admissions observed). The hint is
    PRIORITY-AWARE: a lower class waits behind every queued request of
    its own and all higher classes, so its hint counts that deeper
    effective queue — longer, and honest."""

    def __init__(self, queue_depth: int, max_queue_depth: int,
                 retry_after_s: Optional[float] = None,
                 priority: Optional["Priority"] = None):
        self.queue_depth = queue_depth
        self.max_queue_depth = max_queue_depth
        self.retry_after_s = retry_after_s
        self.priority = priority
        hint = (f"; retry after ~{retry_after_s:.3f}s"
                if retry_after_s is not None else "")
        super().__init__(
            f"serving queue full ({queue_depth}/{max_queue_depth}); "
            f"shed load upstream or raise max_queue_depth{hint}")


class AdmissionRejected(QueueFull):
    """Router-level admission-control rejection (a :class:`QueueFull`
    subclass so every existing backpressure path handles it): the fleet
    refused the request BEFORE any engine queue was consulted — a
    per-priority token bucket ran dry, or the brownout ladder is
    shedding this class (``reason`` says which). Carries the same
    honest ``retry_after_s`` contract; under brownout the hint covers
    the hysteretic recovery horizon, so a ``best_effort`` reject waits
    out the whole ladder unwind instead of hammering a browned-out
    fleet."""

    def __init__(self, reason: str, retry_after_s: Optional[float] = None,
                 priority: Optional["Priority"] = None,
                 queue_depth: int = 0, max_queue_depth: int = 0):
        super().__init__(queue_depth, max_queue_depth,
                         retry_after_s=retry_after_s, priority=priority)
        self.reason = reason
        hint = (f"; retry after ~{retry_after_s:.3f}s"
                if retry_after_s is not None else "")
        # Replace the queue-full message: no engine queue was involved.
        self.args = (
            f"fleet admission rejected ({reason}"
            f"{', ' + priority.value if priority is not None else ''})"
            f"{hint}",)


class RequestState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    CANCELLED = "cancelled"
    TIMED_OUT = "timed_out"
    FAILED = "failed"        # replay budget exhausted (see FinishReason.ERROR)


class FinishReason(enum.Enum):
    LENGTH = "length"        # emitted max_new_tokens
    EOS = "eos"              # hit the engine's eos token (included)
    GRAMMAR = "grammar"      # a constrained stream's FSM reached a state
    #                          with no legal continuation: the output is
    #                          COMPLETE per its grammar (a success, like
    #                          eos — e.g. a JSON document's closing brace)
    CANCELLED = "cancelled"  # handle.cancel()
    TIMED_OUT = "timed_out"  # deadline_s exceeded while running
    DEADLINE = "deadline"    # deadline already expired at pop time (shed
    #                          by the scheduler before any prefill work)
    ERROR = "error"          # device faults outlasted the retry + replay
    #                          budget: the request fails, the engine lives


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration (the ``generate()`` surface).

    ``temperature <= 0`` is greedy; ``top_k``/``top_p`` then must be
    unset (mirroring ``generate()``'s loud error — greedy would
    silently ignore them)."""

    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None

    def __post_init__(self):
        if self.top_k is not None and int(self.top_k) < 1:
            raise ValueError(f"top_k must be >= 1, got {self.top_k}")
        if self.top_p is not None and not 0.0 < float(self.top_p) <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.temperature <= 0 and (self.top_k is not None
                                      or self.top_p is not None):
            raise ValueError(
                "top_k/top_p require temperature > 0 (greedy decoding "
                "would silently ignore them)")

    # Array-side sentinels (arrays can't carry None): see
    # gpt.batched_filtered_logits.
    def as_arrays(self) -> tuple:
        return (float(self.temperature),
                int(self.top_k) if self.top_k is not None else 0,
                float(self.top_p) if self.top_p is not None else 2.0)


_ids = itertools.count()


@dataclasses.dataclass
class Request:
    """One generate request as the scheduler sees it.

    ``adapter``/``constraint`` are the multi-tenant fields (ISSUE 9;
    `serve/tenant/`): the NAME of a registered LoRA adapter (``None`` =
    base model) and a JSON-able constraint spec dict
    (``{"kind": "regex"|"json_schema", ...}`` —
    :func:`pddl_tpu.serve.tenant.compile_constraint`'s input; ``None``
    = unconstrained). Both are plain wire-serializable values, so the
    drain snapshot (v4) and the fleet's submit/migration protocol carry
    them without new encode/decode pairs, and a replayed or migrated
    stream resumes under the identical adapter + automaton."""

    prompt: Sequence[int]
    max_new_tokens: int
    sampling: SamplingParams = SamplingParams()
    deadline_s: Optional[float] = None  # wall budget from submit()
    priority: Priority = Priority.INTERACTIVE
    adapter: Optional[str] = None       # registered LoRA adapter name
    constraint: Optional[dict] = None   # grammar/schema spec dict
    request_id: int = dataclasses.field(default_factory=lambda: next(_ids))


class RequestHandle:
    """The caller's view of a submitted request.

    ``tokens`` grows as the engine streams (generated tokens only, eos
    included when hit); ``state``/``finish_reason`` settle when the
    request leaves its slot. ``cancel()`` is honored at the engine's
    next step — a queued request never runs, a running one is evicted
    mid-decode with the tokens emitted so far intact.

    ``replays`` counts how many times the engine rebuilt this request's
    slot state after a device fault (each rebuild re-prefills the
    prompt and re-feeds ``tokens`` through the tick — the stream the
    caller sees never repeats or loses a token); past the engine's
    ``max_replays`` the request settles FAILED/ERROR instead of
    crash-looping. ``replay_pending`` is engine-internal: the
    already-emitted tokens still to re-feed during a replay.
    ``preemptions`` counts slot evictions in favor of more urgent
    queued work (the stream pauses and later resumes token-exactly
    through the same replay machinery); the engine stops preempting a
    handle past its preemption cap, so a stream can stall briefly but
    never thrash forever.
    """

    def __init__(self, request: Request, arrival_s: float):
        self.request = request
        self.arrival_s = arrival_s
        self.tokens: List[int] = []
        self.state = RequestState.QUEUED
        self.finish_reason: Optional[FinishReason] = None
        self.ttft_s: Optional[float] = None  # submit → first token
        self.finish_s: Optional[float] = None
        self.replays = 0
        self.replay_pending: List[int] = []
        self.preemptions = 0
        # Speculative-serving telemetry (engine ``spec_k > 0``): how
        # many draft tokens this stream was offered and how many the
        # verifier accepted — carried through drain/migration (snapshot
        # v5) so a stream's lifetime acceptance accounting survives a
        # replica move. Zero on non-speculative engines.
        self.spec_drafted = 0
        self.spec_accepted = 0
        self._cancel = False

    def cancel(self) -> None:
        self._cancel = True

    @property
    def cancelled(self) -> bool:
        return self._cancel

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.CANCELLED,
                              RequestState.TIMED_OUT, RequestState.FAILED)

    def __repr__(self) -> str:  # debugging aid, not an API
        return (f"RequestHandle(id={self.request.request_id}, "
                f"state={self.state.value}, tokens={len(self.tokens)})")
