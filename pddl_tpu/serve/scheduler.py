"""Admission control for the serving engine: FCFS with two knobs.

Orca (OSDI '22) separates the SCHEDULING policy from the iteration-level
execution engine; this module is the policy half, deliberately small:

- **max_queue_depth** — the load-shedding knob. A full queue rejects at
  ``submit()`` with a typed :class:`~pddl_tpu.serve.request.QueueFull`
  so upstream can backpressure instead of building unbounded latency.
- **prefill_token_budget** — the head-of-line-blocking knob. Admission
  each tick is FCFS but stops once the admitted prompts' combined
  length would exceed the budget: prefill work is O(prompt), and an
  unbounded admission burst would stall every RUNNING request's next
  token behind it. At least one request is always admitted when a slot
  is free (a single over-budget prompt must not deadlock).

The queue holds handles, not raw requests, so cancellation of a QUEUED
request is just a skip at pop time.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional

from pddl_tpu.serve.request import (
    FinishReason,
    QueueFull,
    RequestHandle,
    RequestState,
)


class FCFSScheduler:
    """First-come-first-served admission with load shedding and a
    per-tick prefill budget."""

    def __init__(self, *, max_queue_depth: int = 64,
                 prefill_token_budget: Optional[int] = None):
        if max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {max_queue_depth}")
        if prefill_token_budget is not None and prefill_token_budget < 1:
            raise ValueError(
                f"prefill_token_budget must be >= 1, got "
                f"{prefill_token_budget}")
        self.max_queue_depth = max_queue_depth
        self.prefill_token_budget = prefill_token_budget
        self._queue: Deque[RequestHandle] = deque()

    @property
    def depth(self) -> int:
        return len(self._queue)

    def submit(self, handle: RequestHandle) -> None:
        """Enqueue, or shed load with a typed rejection."""
        if len(self._queue) >= self.max_queue_depth:
            raise QueueFull(len(self._queue), self.max_queue_depth)
        self._queue.append(handle)

    def admit(self, free_slots: int,
              on_cancelled=None, on_expired=None, now_fn=None,
              cost_fn=None) -> List[RequestHandle]:
        """Pop up to ``free_slots`` admissible handles FCFS, bounded by
        the prefill token budget; cancelled queued handles are dropped
        (marked CANCELLED) in passing — ``on_cancelled(handle)`` lets
        the engine account them in its metrics.

        Deadline-aware shedding: with ``now_fn`` supplied, a queued
        handle whose deadline already expired is skipped-and-failed at
        pop time (state TIMED_OUT, reason DEADLINE, ``on_expired``
        called) BEFORE it can burn prefill budget or a slot — under
        sustained overload the queue wait is exactly where deadlines
        die, and paying a full prefill to emit zero useful tokens would
        steal the budget from requests that can still make theirs.

        ``cost_fn(handle) -> int`` overrides the budget charge per
        request (default: full prompt length). The prefix-cache engine
        charges the UNCACHED SUFFIX length — a cached prefix costs no
        prefill work, so it must not consume admission budget either.
        The charge is a pop-time ESTIMATE: same-tick donations usually
        shrink the real work below it, but under pool pressure an
        earlier admission's eviction pass can reclaim a later request's
        matched (not-yet-pinned) chain, in which case that request
        re-prefills more than it was charged — a bounded latency
        wobble, never a correctness issue (the second match at prefill
        time is authoritative)."""
        admitted: List[RequestHandle] = []
        budget = self.prefill_token_budget
        spent = 0
        while self._queue and len(admitted) < free_slots:
            head = self._queue[0]
            if head.cancelled:
                self._queue.popleft()
                head.state = RequestState.CANCELLED
                head.finish_reason = FinishReason.CANCELLED
                if on_cancelled is not None:
                    on_cancelled(head)
                continue
            if (now_fn is not None
                    and head.request.deadline_s is not None
                    and now_fn() - head.arrival_s > head.request.deadline_s):
                self._queue.popleft()
                head.state = RequestState.TIMED_OUT
                head.finish_reason = FinishReason.DEADLINE
                if on_expired is not None:
                    on_expired(head)
                continue
            cost = (cost_fn(head) if cost_fn is not None
                    else len(head.request.prompt))
            if budget is not None and admitted and spent + cost > budget:
                break  # FCFS: never skip the head for a cheaper request
            self._queue.popleft()
            head.state = RequestState.RUNNING
            admitted.append(head)
            spent += cost
        return admitted

    # ------------------------------------------------- resilience hooks
    def requeue_front(self, handles: List[RequestHandle]) -> None:
        """Put replayed handles back at the queue HEAD in the given
        order (they were admitted before anything currently queued, so
        FCFS owes them the next free slots). Bypasses
        ``max_queue_depth`` deliberately: these requests were already
        accepted once — shedding them now would turn a transient device
        fault into a visible rejection."""
        for handle in reversed(handles):
            handle.state = RequestState.QUEUED
            self._queue.appendleft(handle)

    def drain(self) -> List[RequestHandle]:
        """Pop every queued handle (FCFS order) for a drain snapshot;
        the queue is left empty so a post-drain step admits nothing."""
        out = list(self._queue)
        self._queue.clear()
        return out

    def restore(self, handles: List[RequestHandle]) -> None:
        """Re-enqueue restored handles in snapshot order. Like
        :meth:`requeue_front`, depth limits do not apply — every one of
        these was admitted by the drained engine."""
        self._queue.extend(handles)
