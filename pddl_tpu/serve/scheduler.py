"""Admission control for the serving engine: SLO-aware pop order.

Orca (OSDI '22) separates the SCHEDULING policy from the iteration-level
execution engine; this module is the policy half. It grew from pure
FCFS to the overload-robust order DistServe's SLO-goodput framing asks
for, while keeping the two original knobs:

- **max_queue_depth** — the load-shedding knob. A full queue rejects at
  ``submit()`` with a typed :class:`~pddl_tpu.serve.request.QueueFull`
  so upstream can backpressure instead of building unbounded latency.
- **prefill_token_budget** — the head-of-line-blocking knob. Admission
  each tick stops once the admitted prompts' combined length would
  exceed the budget: prefill work is O(prompt), and an unbounded
  admission burst would stall every RUNNING request's next token behind
  it. At least one request is always admitted when a slot is free (a
  single over-budget prompt must not deadlock).

Pop order (the SLO layer):

- **Priority classes** (:class:`~pddl_tpu.serve.request.Priority`):
  ``interactive`` pops before ``batch`` pops before ``best_effort`` —
  under overload the queue wait lands on the work that can afford it.
- **EDF within a class**: requests carrying a ``deadline_s`` pop
  earliest-deadline-first (deadline shedding already kills expired
  ones at pop time — EDF is what stops deadlines from dying in the
  first place); deadline-less requests follow, FIFO.
- **Anti-starvation aging**: a queued request's effective class rises
  one rank per ``aging_s`` waited, so a sustained ``interactive``
  flood cannot starve a ``batch`` request forever — after ``aging_s``
  it competes at interactive rank and its older arrival wins the
  tie-break. Plain EDF/priority without aging starves; the
  ``overload`` test suite pins the bound discriminatively.

The queue holds handles, not raw requests, so cancellation of a QUEUED
request is just a skip at pop time. Replayed/restored handles bypass
the ordering entirely (a separate front lane): they were admitted once
already and are owed the next free slots regardless of class.
"""

from __future__ import annotations

import math
from collections import deque
from typing import Deque, List, Optional, Tuple

from pddl_tpu.serve.request import (
    FinishReason,
    Priority,
    QueueFull,
    RequestHandle,
    RequestState,
)


class SLOScheduler:
    """Priority + EDF + aging admission with load shedding and a
    per-tick prefill budget.

    Args:
      max_queue_depth: queue cap; beyond it ``submit()`` raises
        :class:`~pddl_tpu.serve.request.QueueFull`.
      prefill_token_budget: per-``admit()`` cap on the admitted
        prompts' combined (cost_fn-priced) length.
      aging_s: seconds of queue wait per effective-rank promotion
        (the anti-starvation bound: a ``batch`` request waits at most
        ``aging_s`` before competing at ``interactive`` rank, a
        ``best_effort`` one at most ``2*aging_s``). ``None`` disables
        aging — pure priority+EDF, which CAN starve; only tests use it.
    """

    def __init__(self, *, max_queue_depth: int = 64,
                 prefill_token_budget: Optional[int] = None,
                 aging_s: Optional[float] = 30.0):
        if max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {max_queue_depth}")
        if prefill_token_budget is not None and prefill_token_budget < 1:
            raise ValueError(
                f"prefill_token_budget must be >= 1, got "
                f"{prefill_token_budget}")
        if aging_s is not None and aging_s <= 0:
            raise ValueError(f"aging_s must be > 0 or None, got {aging_s}")
        self.max_queue_depth = max_queue_depth
        self.prefill_token_budget = prefill_token_budget
        self.aging_s = float(aging_s) if aging_s is not None else None
        # (seq, handle): seq is the FIFO tie-break inside an equal
        # (effective rank, deadline) key — stable, so an all-default
        # workload pops in exact submit order (the FCFS it grew from).
        self._queue: List[Tuple[int, RequestHandle]] = []
        self._seq = 0
        # The bypass lane for replayed/restored handles: popped before
        # any key is even computed (they were admitted once already).
        self._front: Deque[RequestHandle] = deque()

    @property
    def depth(self) -> int:
        return len(self._queue) + len(self._front)

    def depth_at_or_above(self, priority: Priority) -> int:
        """Queued handles an arrival of ``priority`` would wait behind:
        everything at its own or a more urgent class (the bypass lane
        outranks every class). The PRIORITY-AWARE retry_after_s hint is
        this depth times the recent admission interval — honest,
        because a ``best_effort`` arrival really does queue behind all
        interactive and batch work."""
        rank = priority.rank
        return len(self._front) + sum(
            1 for _, h in self._queue if h.request.priority.rank <= rank)

    def submit(self, handle: RequestHandle) -> None:
        """Enqueue, or shed load with a typed rejection."""
        if self.depth >= self.max_queue_depth:
            raise QueueFull(self.depth, self.max_queue_depth,
                            priority=handle.request.priority)
        self._queue.append((self._seq, handle))
        self._seq += 1

    # ------------------------------------------------------- pop order
    def _key(self, seq: int, handle: RequestHandle,
             now: Optional[float]) -> Tuple[int, float, int]:
        """(effective rank, absolute deadline, seq) — ascending pop.

        Aging lowers the effective rank one class per ``aging_s``
        waited (floored at the most urgent class), so the tie-break
        seq — older first — then finishes the starvation argument.
        A deadline-less request sorts on a SYNTHETIC horizon of
        ``4*aging_s`` past its arrival (``inf`` with aging off): urgent
        deadlines still jump it, but a stream of freshly-deadlined
        arrivals cannot starve it inside its own class forever."""
        req = handle.request
        rank = req.priority.rank
        if self.aging_s is not None and now is not None:
            rank = max(0, rank - int((now - handle.arrival_s)
                                     / self.aging_s))
        if req.deadline_s is not None:
            deadline = handle.arrival_s + req.deadline_s
        elif self.aging_s is not None:
            deadline = handle.arrival_s + 4.0 * self.aging_s
        else:
            deadline = math.inf
        return rank, deadline, seq

    def _peek_best(self, now: Optional[float]) -> Tuple[int, RequestHandle]:
        """Index (into the main queue; -1 = front lane) and handle of
        the next pop, WITHOUT removing it — the budget check must be
        able to leave an over-budget head exactly where it is (popping
        it into the bypass lane would promote it past every class next
        tick, inverting the SLO order)."""
        if self._front:
            return -1, self._front[0]
        best_i = 0
        best_key = self._key(*self._queue[0], now)
        for i in range(1, len(self._queue)):
            key = self._key(*self._queue[i], now)
            if key < best_key:
                best_i, best_key = i, key
        return best_i, self._queue[best_i][1]

    def _pop_at(self, index: int) -> RequestHandle:
        if index < 0:
            return self._front.popleft()
        return self._queue.pop(index)[1]

    def admit(self, free_slots: int,
              on_cancelled=None, on_expired=None, now_fn=None,
              cost_fn=None) -> List[RequestHandle]:
        """Pop up to ``free_slots`` admissible handles in SLO order,
        bounded by the prefill token budget; cancelled queued handles
        are dropped (marked CANCELLED) in passing —
        ``on_cancelled(handle)`` lets the engine account them in its
        metrics.

        Deadline-aware shedding: with ``now_fn`` supplied, a queued
        handle whose deadline already expired is skipped-and-failed at
        pop time (state TIMED_OUT, reason DEADLINE, ``on_expired``
        called) BEFORE it can burn prefill budget or a slot — under
        sustained overload the queue wait is exactly where deadlines
        die, and paying a full prefill to emit zero useful tokens would
        steal the budget from requests that can still make theirs.
        (EDF pop order makes the sweep cheap: expired deadlines are by
        construction at the head of their class.)

        ``cost_fn(handle) -> int`` overrides the budget charge per
        request (default: full prompt length). The prefix-cache engine
        charges the UNCACHED SUFFIX length — a cached prefix costs no
        prefill work, so it must not consume admission budget either.
        The tenant engine additionally charges a COLD adapter load
        (``TenantConfig.adapter_load_tokens``) through the same
        cost_fn: a host→device factor transfer is admission-path work
        exactly like an uncached suffix, and a resident adapter — like
        a cached prefix — charges nothing.
        The TIERED engine (ISSUE 13) prices host-tier promotions the
        same way: blocks the host tier will promote charge
        ``HostTierConfig.promote_tokens_per_block`` each instead of
        ``block_size`` prefill tokens — an H2D block transfer is real
        admission work but much cheaper than recomputing the block, so
        the budget admits more behind a promotion than behind the
        prefill it replaced while still throttling promotion storms
        (the runbook's "when promotion charges starve cold admissions"
        lever works by raising this price).
        The SPECULATIVE engine's contract (ISSUE 12): token-budget
        accounting charges ACCEPTED, never DRAFTED, tokens. A replayed
        stream's catch-up re-feed is charged at its emitted token
        count (the tokens that really re-enter the cache), not the
        ``(spec_k+1)``-wide verify compute spent reaching them; fresh
        admissions charge exactly what a non-speculative engine
        charges — drafting must never inflate an admission's price or
        shrink the batch the budget admits (pinned by
        ``tests/test_serve_spec.py``).
        The charge is a pop-time ESTIMATE: same-tick donations usually
        shrink the real work below it, but under pool pressure an
        earlier admission's eviction pass can reclaim a later request's
        matched (not-yet-pinned) chain, in which case that request
        re-prefills more than it was charged — a bounded latency
        wobble, never a correctness issue (the second match at prefill
        time is authoritative)."""
        admitted: List[RequestHandle] = []
        budget = self.prefill_token_budget
        spent = 0
        now = now_fn() if now_fn is not None else None
        while self.depth and len(admitted) < free_slots:
            idx, head = self._peek_best(now)
            if head.cancelled:
                self._pop_at(idx)
                head.state = RequestState.CANCELLED
                head.finish_reason = FinishReason.CANCELLED
                if on_cancelled is not None:
                    on_cancelled(head)
                continue
            if (now is not None
                    and head.request.deadline_s is not None
                    and now - head.arrival_s > head.request.deadline_s):
                self._pop_at(idx)
                head.state = RequestState.TIMED_OUT
                head.finish_reason = FinishReason.DEADLINE
                if on_expired is not None:
                    on_expired(head)
                continue
            cost = (cost_fn(head) if cost_fn is not None
                    else len(head.request.prompt))
            if budget is not None and admitted and spent + cost > budget:
                # Never skip the chosen head for a cheaper lower-ranked
                # request — that would invert the SLO order — but leave
                # it IN PLACE: next tick re-ranks it against whatever
                # arrived meanwhile.
                break
            self._pop_at(idx)
            head.state = RequestState.RUNNING
            admitted.append(head)
            spent += cost
        return admitted

    def queued_of_class(self, priority: Priority) -> int:
        """Main-queue handles whose ACTUAL class is ``priority`` (the
        bypass lane and aging promotions excluded) — the engine's
        preemption trigger reads this, so a replayed best_effort
        handle in the bypass lane cannot preempt its own class."""
        return sum(1 for _, h in self._queue
                   if h.request.priority is priority)

    def requeue(self, handle: RequestHandle) -> None:
        """Re-enter a PREEMPTED running handle through the NORMAL
        queue (not the bypass lane — a preempted ``best_effort``
        stream must not outrank the interactive work it was parked
        for). Depth limits do not apply: it was admitted once, and
        shedding it now would turn a scheduling decision into a
        visible failure."""
        handle.state = RequestState.QUEUED
        self._queue.append((self._seq, handle))
        self._seq += 1

    # ------------------------------------------------- resilience hooks
    def requeue_front(self, handles: List[RequestHandle]) -> None:
        """Put replayed handles back in the bypass lane in the given
        order (they were admitted before anything currently queued, so
        the scheduler owes them the next free slots regardless of
        class). Bypasses ``max_queue_depth`` deliberately: these
        requests were already accepted once — shedding them now would
        turn a transient device fault into a visible rejection."""
        for handle in reversed(handles):
            handle.state = RequestState.QUEUED
            self._front.appendleft(handle)

    def drain(self) -> List[RequestHandle]:
        """Pop every queued handle (bypass lane first, then submit
        order) for a drain snapshot; the queue is left empty so a
        post-drain step admits nothing."""
        out = list(self._front)
        out.extend(h for _, h in self._queue)
        self._front.clear()
        self._queue.clear()
        return out

    def restore(self, handles: List[RequestHandle]) -> None:
        """Re-enqueue restored handles in snapshot order, ahead of any
        new traffic (the bypass lane). Like :meth:`requeue_front`,
        depth limits do not apply — every one of these was admitted by
        the drained engine."""
        self._front.extend(handles)


# The name the engine (and older tests) grew up with: the SLO scheduler
# with every request at the default class and no deadlines IS FCFS.
FCFSScheduler = SLOScheduler
