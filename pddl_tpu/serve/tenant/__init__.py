"""Multi-tenant serving: per-request LoRA adapters + constrained decoding.

The "model server → platform" jump (ROADMAP item 3): two subsystems
sharing one admission path, both built on the engine's core invariant —
all per-slot variation lives in RUNTIME arrays, never in
compiled-program shape:

- **Paged LoRA** (`adapters.py` + `ops/lora.py`): a registry of host-
  resident adapters, a fixed-shape device pool with pin-on-admit
  refcounts and LRU eviction (the KV block pool's discipline applied to
  weights), per-slot int32 adapter ids gathered inside the fused tick —
  ONE compiled program serves every tenant mix, and admission charges a
  cold load against the prefill budget like an uncached prompt suffix.
- **Constrained decoding** (`grammar.py`): regex / JSON-schema →
  Brzozowski-derivative DFA → token FSM whose per-state allow mask is
  stamped as a runtime ``[S, V]`` array ahead of the batched sampler;
  FSM state is a pure function of emitted tokens, so replay, drain/
  restore and fleet migration re-derive it exactly like KV.

Enable with ``ServeEngine(..., tenant=TenantConfig(...))``; see
docs/SERVING.md § "Multi-tenant serving" and docs/OPERATIONS.md
§ "Adapter pool sizing".
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from pddl_tpu.serve.tenant.adapters import (
    AdapterPool,
    AdapterPoolExhausted,
    AdapterRegistry,
    LoRAAdapter,
)
from pddl_tpu.serve.tenant.grammar import (
    TokenFSM,
    compile_constraint,
    constraint_key,
    decode_tokens,
    encode_text,
    json_schema_to_regex,
    token_fsm_from_regex,
)


@dataclasses.dataclass
class TenantConfig:
    """Multi-tenancy knobs for :class:`~pddl_tpu.serve.ServeEngine`.

    Args:
      registry: the deployment's :class:`AdapterRegistry`; ``None``
        builds an empty one sized to the model (adapters can be
        registered before traffic). Its ``embed_dim``/``vocab_size``
        must match the engine's model — validated loudly at engine
        construction.
      adapter_pool_slots: device pool rows INCLUDING the reserved
        identity row 0 — how many distinct adapters can be resident at
        once. ``None`` (default) auto-sizes to the engine's
        ``max_slots + 4`` (the live-mix floor plus a little hit-rate
        headroom). An EXPLICIT size must cover the floor
        ``max_slots + 1`` (every slot on a distinct adapter plus the
        identity row) — the engine validates it loudly; the headroom
        above the floor is the hit-rate knob (docs/OPERATIONS.md
        § "Adapter pool sizing").
      token_strings: token-id → string vocabulary for grammar
        compilation (index = token id; empty/missing strings make a
        token never-legal under any constraint). Required before a
        constrained ``submit()`` — adapters-only tenancy may leave it
        ``None``.
      adapter_load_tokens: prefill-budget tokens a COLD adapter load is
        charged at admission (a resident adapter charges nothing). The
        default prices the host→device factor transfer roughly like a
        short prompt chunk.
    """

    registry: Optional[AdapterRegistry] = None
    adapter_pool_slots: Optional[int] = None
    token_strings: Optional[Sequence[str]] = None
    adapter_load_tokens: int = 8


__all__ = [
    "AdapterPool",
    "AdapterPoolExhausted",
    "AdapterRegistry",
    "LoRAAdapter",
    "TenantConfig",
    "TokenFSM",
    "compile_constraint",
    "constraint_key",
    "decode_tokens",
    "encode_text",
    "json_schema_to_regex",
    "token_fsm_from_regex",
]
