"""Per-request LoRA adapters: registry + paged device pool (host side).

The S-LoRA serving model (Sheng et al., 2023), built on the machinery
this repo already trusts for KV blocks (`serve/kvcache/`):

- :class:`AdapterRegistry` — every adapter the deployment knows, HOST
  resident (numpy factors, rank zero-padded to the registry's fixed
  ``rank`` so one pool shape serves heterogeneous ranks; per-adapter
  ``scale`` pre-folded into the up factor at registration so the device
  apply is a pure two-matmul chain).
- :class:`AdapterPool` — the bookkeeping of a fixed-shape DEVICE pool
  (``[P, d, r]`` / ``[P, r, V]``, `ops/lora.py`), mirroring the KV
  block pool's discipline exactly: row 0 is the reserved IDENTITY row
  (all zeros = base model — the "scratch block" of adapters), rows are
  pin-on-admit refcounted for their whole slot residency, and a cold
  load under a full pool LRU-evicts the least recently used UNPINNED
  row. The engine owns the device arrays and the one compiled load
  program; this class only decides WHICH row.

Admission economics (the ISSUE's "admission charges adapter pin/load
against the prefill budget"): a cold adapter load is a host→device
transfer on the admission path, so the engine's ``cost_fn`` charges
``adapter_load_tokens`` extra for non-resident adapters — a warm
adapter costs nothing, exactly like a cached prefix.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from pddl_tpu.ops.lora import IDENTITY_ROW


@dataclasses.dataclass(frozen=True)
class LoRAAdapter:
    """One registered adapter: rank-padded factors, scale pre-folded.

    ``a`` is ``[d, rank]``, ``b`` is ``[rank, V]`` (already multiplied
    by the adapter's scale), both float32 numpy — the exact tensors a
    pool load ships."""

    name: str
    a: np.ndarray
    b: np.ndarray

    @property
    def nbytes(self) -> int:
        return int(self.a.nbytes + self.b.nbytes)


class AdapterRegistry:
    """Host-side catalogue of every servable adapter.

    Args:
      embed_dim: the model's feature width ``d`` (validated by the
        engine against its model).
      vocab_size: the adapted head's output width ``V``.
      rank: the POOL rank ``r`` — the fixed-shape ceiling every
        registered adapter is zero-padded to (a smaller true rank pads
        with zero columns/rows, which is a mathematical no-op).
    """

    def __init__(self, embed_dim: int, vocab_size: int, rank: int = 8):
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        self.embed_dim = int(embed_dim)
        self.vocab_size = int(vocab_size)
        self.rank = int(rank)
        self._adapters: Dict[str, LoRAAdapter] = {}

    def register(self, name: str, a, b, *, scale: float = 1.0) -> LoRAAdapter:
        """Register factors ``a [d, r]`` / ``b [r, V]`` (``r <= rank``;
        zero-padded up). Re-registering a name replaces it — already-
        RESIDENT copies in a pool keep serving the old weights until
        reloaded (document, don't surprise: live slots pinned an
        adapter version, like a pinned prefix chain)."""
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        if a.ndim != 2 or a.shape[0] != self.embed_dim:
            raise ValueError(
                f"adapter {name!r}: a must be [{self.embed_dim}, r], "
                f"got {a.shape}")
        if b.ndim != 2 or b.shape != (a.shape[1], self.vocab_size):
            raise ValueError(
                f"adapter {name!r}: b must be [{a.shape[1]}, "
                f"{self.vocab_size}], got {b.shape}")
        r = a.shape[1]
        if r > self.rank:
            raise ValueError(
                f"adapter {name!r}: rank {r} exceeds the registry's "
                f"pool rank {self.rank}")
        pa = np.zeros((self.embed_dim, self.rank), np.float32)
        pb = np.zeros((self.rank, self.vocab_size), np.float32)
        pa[:, :r] = a
        pb[:r] = b * float(scale)
        adapter = LoRAAdapter(str(name), pa, pb)
        self._adapters[adapter.name] = adapter
        return adapter

    def register_random(self, name: str, seed: int, *,
                        scale: float = 0.05,
                        rank: Optional[int] = None) -> LoRAAdapter:
        """Deterministic random adapter from ``seed`` — the fleet's
        determinism contract (`fleet/worker.py` builds each process
        replica's registry from (name, seed) config pairs, so every
        replica and the chaos oracle hold bit-identical factors)."""
        r = int(rank) if rank is not None else self.rank
        rng = np.random.RandomState(int(seed))
        a = rng.randn(self.embed_dim, r).astype(np.float32)
        b = rng.randn(r, self.vocab_size).astype(np.float32)
        return self.register(name, a, b, scale=scale)

    def get(self, name: str) -> LoRAAdapter:
        try:
            return self._adapters[name]
        except KeyError:
            raise KeyError(
                f"adapter {name!r} is not registered "
                f"(known: {sorted(self._adapters)})") from None

    def __contains__(self, name: str) -> bool:
        return name in self._adapters

    def __len__(self) -> int:
        return len(self._adapters)

    @property
    def names(self) -> List[str]:
        return sorted(self._adapters)

    @property
    def adapter_nbytes(self) -> int:
        """Bytes ONE pool row holds (the pool-sizing unit in the
        OPERATIONS runbook)."""
        return 4 * self.rank * (self.embed_dim + self.vocab_size)


class AdapterPoolExhausted(RuntimeError):
    """Every pool row is pinned by a live slot and a cold adapter needs
    one: the admission escalates (replay-charged) exactly like a block
    pool shortfall. The runbook's sizing floor — ``pool_slots >=
    max_slots + 1`` — makes this impossible for a live mix."""


class AdapterPool:
    """Row bookkeeping of the device adapter pool (row 0 = identity).

    The engine consults :meth:`lookup` (resident?) → :meth:`assign`
    (reserve a row) → dispatches the device load → :meth:`pin`; a
    faulted load :meth:`unassign`\\ s the reservation.
    :meth:`pin`/:meth:`unpin` bracket slot residency; assignment under
    pressure LRU-evicts unpinned resident rows."""

    def __init__(self, slots: int):
        if slots < 2:
            raise ValueError(
                f"adapter pool needs >= 2 rows (row {IDENTITY_ROW} is "
                f"the reserved identity), got {slots}")
        self.slots = int(slots)
        self._row_of: Dict[str, int] = {}
        self._name_of: Dict[int, str] = {}
        self._refs = [0] * self.slots
        self._free: List[int] = list(range(1, self.slots))
        self._stamp = 0
        self._last_access = [0] * self.slots
        self.evictions = 0

    # ------------------------------------------------------------ stats
    @property
    def resident(self) -> int:
        return len(self._row_of)

    def row_of(self, name: str) -> Optional[int]:
        return self._row_of.get(name)

    # --------------------------------------------------------- assign
    def lookup(self, name: str) -> Optional[int]:
        """Resident row for ``name`` (LRU-touched), or None (cold)."""
        row = self._row_of.get(name)
        if row is not None:
            self._stamp += 1
            self._last_access[row] = self._stamp
        return row

    def assign(self, name: str) -> int:
        """Reserve a row for a cold load: a free row, else LRU-evict an
        unpinned resident one. The mapping is recorded immediately so a
        same-tick second admission of ``name`` finds it resident (the
        device load the engine dispatches right after is what makes the
        row's CONTENT real — a load fault must :meth:`unassign`)."""
        if name in self._row_of:
            raise ValueError(f"adapter {name!r} is already resident")
        if self._free:
            row = self._free.pop(0)
        else:
            victims = [r for r in range(1, self.slots)
                       if self._refs[r] == 0 and r in self._name_of]
            if not victims:
                raise AdapterPoolExhausted(
                    f"all {self.slots - 1} adapter pool rows are pinned "
                    "by live slots (size the pool >= max_slots + 1; see "
                    "docs/OPERATIONS.md 'Adapter pool sizing')")
            row = min(victims, key=lambda r: self._last_access[r])
            del self._row_of[self._name_of.pop(row)]
            self.evictions += 1
        self._row_of[name] = row
        self._name_of[row] = name
        self._stamp += 1
        self._last_access[row] = self._stamp
        return row

    def unassign(self, row: int) -> None:
        """Unwind a reservation whose device load never landed."""
        name = self._name_of.pop(row, None)
        if name is not None:
            del self._row_of[name]
        self._free.append(row)

    # ------------------------------------------------------- refcounts
    def pin(self, row: int) -> None:
        """One live slot depends on this row (identity row: no-op —
        it is structurally unevictable)."""
        if row != IDENTITY_ROW:
            self._refs[row] += 1

    def unpin(self, row: int) -> None:
        if row == IDENTITY_ROW:
            return
        if self._refs[row] <= 0:
            raise RuntimeError(
                "adapter unpin without a matching pin (refcount "
                "underflow) — an engine slot released its adapter twice")
        self._refs[row] -= 1

    def pinned_rows(self) -> List[int]:
        return [r for r in range(1, self.slots) if self._refs[r] > 0]
