"""Grammar-constrained decoding: regex/JSON-schema → per-step token masks.

The Outlines observation (Willard & Louf, 2023): constraining an LM to
a regular language reduces to a FINITE STATE MACHINE over the token
vocabulary — at every step the set of legal next tokens is a pure
function of the FSM state, so the whole constraint apparatus the engine
needs is a precomputed boolean mask table ``[n_states, V]`` and an
integer state per request. The mask is stamped into the fused tick as a
RUNTIME ``[S, V]`` array ahead of
:func:`~pddl_tpu.models.gpt.sample_logits_batched` (disallowed logits →
``-inf``), which is why mixed constrained/unconstrained batches cost
zero recompiles: an unconstrained slot's row is all-True and
``where(mask, logits, -inf)`` is then bit-identical to the unmasked
logits.

Pipeline, all host-side and all at ADMISSION time (never per tick):

1. ``regex`` (a self-contained subset: literals, ``.``, escapes,
   ``[...]`` classes with ranges/negation, ``( )`` groups, ``|``,
   ``* + ?``) → character DFA via **Brzozowski derivatives** with
   ACI-normalized smart constructors (finite state set guaranteed).
2. JSON Schema (restricted subset: string/integer/number/boolean,
   ``enum``, fixed-property objects, homogeneous arrays) → a regex of
   the schema's canonical serialization → the same DFA.
3. DFA → **token FSM**: each vocabulary token's STRING is run through
   the character transitions from every live state; the result is the
   dense transition table ``[n_states, V]`` (-1 = illegal) whose
   ``>= 0`` mask is the per-state allow mask. Dead states (no path to
   acceptance) are trimmed first, so a masked greedy decode can never
   wander into a cul-de-sac it cannot finish from.

EOS handling is the ENGINE's: the mask table never mentions the eos
token — the engine ORs eos into a state's row iff the state is
accepting, and a state with NO legal tokens and no eos escape finishes
the stream with ``FinishReason.GRAMMAR`` (the output is complete by
construction — e.g. a JSON object's closing ``}`` is a no-out-edge
accepting state).

Replay/fault/migration: FSM state is NEVER snapshotted — it is a pure
function of the emitted tokens (``TokenFSM.advance_many``), re-derived
at replay admission exactly like KV is re-derived from the prompt. The
constraint SPEC (a plain JSON-able dict, see :func:`compile_constraint`)
rides the drain/fleet wire format so a migrated constrained stream
resumes under the identical automaton.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# ------------------------------------------------------------ regex AST
# Hash-consed tuple ASTs with ACI-normalizing smart constructors: the
# Brzozowski derivative state space is finite only modulo associativity/
# commutativity/idempotence of alternation — frozenset alternatives and
# the absorption rules below are what bound the DFA.

EMPTY = ("empty",)  # ∅ — matches nothing
EPS = ("eps",)      # ε — matches the empty string


def _rclass(chars, negated: bool = False):
    return ("class", bool(negated), frozenset(chars))


ANY = _rclass((), negated=True)  # `.` — any character


def _cat(a, b):
    if a == EMPTY or b == EMPTY:
        return EMPTY
    if a == EPS:
        return b
    if b == EPS:
        return a
    return ("cat", a, b)


def _alt(a, b):
    if a == EMPTY:
        return b
    if b == EMPTY:
        return a
    xs = set()
    for x in (a, b):
        if x[0] == "alt":
            xs.update(x[1])
        else:
            xs.add(x)
    if len(xs) == 1:
        return next(iter(xs))
    return ("alt", frozenset(xs))


def _star(a):
    if a in (EMPTY, EPS):
        return EPS
    if a[0] == "star":
        return a
    return ("star", a)


def _nullable(r) -> bool:
    t = r[0]
    if t == "eps" or t == "star":
        return True
    if t == "empty" or t == "class":
        return False
    if t == "cat":
        return _nullable(r[1]) and _nullable(r[2])
    return any(_nullable(x) for x in r[1])  # alt


def _deriv(r, c: str):
    """Brzozowski derivative: the language of suffixes of ``r`` after
    consuming character ``c``."""
    t = r[0]
    if t == "empty" or t == "eps":
        return EMPTY
    if t == "class":
        return EPS if ((c in r[2]) != r[1]) else EMPTY
    if t == "cat":
        d = _cat(_deriv(r[1], c), r[2])
        if _nullable(r[1]):
            d = _alt(d, _deriv(r[2], c))
        return d
    if t == "alt":
        out = EMPTY
        for x in r[1]:
            out = _alt(out, _deriv(x, c))
        return out
    return _cat(_deriv(r[1], c), r)  # star


# --------------------------------------------------------- regex parser

_METACHARS = set("\\.[]()|*+?")


class RegexError(ValueError):
    """Malformed pattern (or a construct outside the supported subset —
    loud, never silently mis-parsed as literals)."""


def _regex_escape(literal: str) -> str:
    """Escape ``literal`` so the parser treats every character verbatim
    (the JSON-schema lowering escapes its serialized literals with
    this)."""
    return "".join("\\" + ch if ch in _METACHARS or ch in "^-"
                   else ch for ch in literal)


_DIGITS = frozenset("0123456789")
_WORD = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t\n\r\f\v")


def _parse(pattern: str):
    """Recursive-descent parse of the supported subset → AST."""
    pos = [0]
    n = len(pattern)

    def peek() -> Optional[str]:
        return pattern[pos[0]] if pos[0] < n else None

    def take() -> str:
        c = pattern[pos[0]]
        pos[0] += 1
        return c

    def parse_escape():
        if pos[0] >= n:
            raise RegexError(f"dangling backslash in {pattern!r}")
        c = take()
        if c == "d":
            return _rclass(_DIGITS)
        if c == "w":
            return _rclass(_WORD)
        if c == "s":
            return _rclass(_SPACE)
        if c == "n":
            return _rclass("\n")
        if c == "t":
            return _rclass("\t")
        return _rclass(c)  # escaped literal (incl. metachars)

    def parse_class():
        negated = peek() == "^"
        if negated:
            take()
        chars = set()
        if peek() == "]":  # a leading ] is a literal (POSIX convention)
            chars.add(take())
        while True:
            c = peek()
            if c is None:
                raise RegexError(f"unterminated [ class in {pattern!r}")
            if c == "]":
                take()
                return _rclass(chars, negated)
            take()
            if c == "\\":
                if pos[0] >= n:
                    raise RegexError(f"dangling backslash in {pattern!r}")
                e = take()
                sub = {"d": _DIGITS, "w": _WORD, "s": _SPACE,
                       "n": "\n", "t": "\t"}.get(e, e)
                chars.update(sub)
                continue
            if peek() == "-" and pos[0] + 1 < n \
                    and pattern[pos[0] + 1] != "]":
                take()  # the dash
                hi = take()
                if ord(hi) < ord(c):
                    raise RegexError(
                        f"inverted range {c}-{hi} in {pattern!r}")
                chars.update(chr(o) for o in range(ord(c), ord(hi) + 1))
            else:
                chars.add(c)

    def parse_atom():
        c = peek()
        if c is None or c in "|)":
            return None
        take()
        if c == "(":
            inner = parse_alt()
            if peek() != ")":
                raise RegexError(f"unbalanced ( in {pattern!r}")
            take()
            return inner
        if c == "[":
            return parse_class()
        if c == ".":
            return ANY
        if c == "\\":
            return parse_escape()
        if c in "*+?":
            raise RegexError(
                f"quantifier {c!r} with nothing to repeat in {pattern!r}")
        return _rclass(c)  # literal (incl. { } — no brace quantifiers)

    def parse_post():
        atom = parse_atom()
        if atom is None:
            return None
        while True:
            c = peek()
            if c == "*":
                take()
                atom = _star(atom)
            elif c == "+":
                take()
                atom = _cat(atom, _star(atom))
            elif c == "?":
                take()
                atom = _alt(atom, EPS)
            else:
                return atom

    def parse_cat():
        out = EPS
        while True:
            atom = parse_post()
            if atom is None:
                return out
            out = _cat(out, atom)

    def parse_alt():
        out = parse_cat()
        while peek() == "|":
            take()
            out = _alt(out, parse_cat())
        return out

    ast = parse_alt()
    if pos[0] != n:
        raise RegexError(f"unexpected {pattern[pos[0]]!r} at "
                         f"{pos[0]} in {pattern!r}")
    return ast


# ----------------------------------------------------------- DFA (char)

# A runaway derivative expansion is a bug in the pattern or the
# normalizer, not a workload to serve — fail loudly, bounded.
MAX_DFA_STATES = 4096


class CharDFA:
    """Deterministic character automaton from derivative construction.

    ``trans[s]`` maps char → next state id; ``accepting`` is the
    nullable set; ``live`` marks states from which acceptance is
    reachable (the trim that keeps masked decoding out of dead ends).
    """

    def __init__(self, trans: List[Dict[str, int]],
                 accepting: List[bool], live: List[bool]):
        self.trans = trans
        self.accepting = accepting
        self.live = live

    def run(self, state: int, text: str) -> int:
        """Advance ``state`` through ``text``; -1 = rejected (or lands
        in a trimmed dead state)."""
        for c in text:
            state = self.trans[state].get(c, -1)
            if state < 0 or not self.live[state]:
                return -1
        return state


def _mentioned_chars(r, acc: set) -> None:
    """Characters a regex AST names explicitly (class members — negated
    classes included: their MEMBERS are the boundary). Every alphabet
    character outside this set behaves identically under derivation,
    which is the standard equivalence-class trick: derive once for one
    representative instead of once per character (a 256-char vocabulary
    with a 10-char grammar costs 11 derivative columns, not 256)."""
    t = r[0]
    if t == "class":
        acc.update(r[2])
    elif t == "cat":
        _mentioned_chars(r[1], acc)
        _mentioned_chars(r[2], acc)
    elif t == "alt":
        for x in r[1]:
            _mentioned_chars(x, acc)
    elif t == "star":
        _mentioned_chars(r[1], acc)


def build_char_dfa(pattern: str, alphabet: Sequence[str]) -> CharDFA:
    """Compile ``pattern`` over the FINITE ``alphabet`` (the set of
    characters appearing in the token vocabulary — a constraint can only
    ever emit those). Negated classes / ``.`` quantify over it."""
    root = _parse(pattern)
    alphabet = sorted(set(alphabet))
    mentioned: set = set()
    _mentioned_chars(root, mentioned)
    probe = [c for c in alphabet if c in mentioned]
    rest = [c for c in alphabet if c not in mentioned]
    if rest:
        probe.append(rest[0])  # one representative for the whole class
    ids = {root: 0}
    order = [root]
    trans: List[Dict[str, int]] = []
    i = 0
    while i < len(order):
        r = order[i]
        row: Dict[str, int] = {}
        for c in probe:
            d = _deriv(r, c)
            if d == EMPTY:
                continue
            if d not in ids:
                if len(ids) >= MAX_DFA_STATES:
                    raise RegexError(
                        f"pattern {pattern!r} exceeds {MAX_DFA_STATES} "
                        "DFA states")
                ids[d] = len(order)
                order.append(d)
            row[c] = ids[d]
        if rest and rest[0] in row:
            # The representative advanced: every unmentioned character
            # derives identically — share the target.
            tgt = row[rest[0]]
            for c in rest[1:]:
                row[c] = tgt
        trans.append(row)
        i += 1
    accepting = [_nullable(r) for r in order]
    # Trim: live = can reach an accepting state (reverse reachability).
    live = list(accepting)
    changed = True
    while changed:
        changed = False
        for s, row in enumerate(trans):
            if not live[s] and any(live[t] for t in row.values()):
                live[s] = True
                changed = True
    if not live[0]:
        raise RegexError(f"pattern {pattern!r} matches nothing over the "
                         "vocabulary's alphabet")
    return CharDFA(trans, accepting, live)


# ---------------------------------------------------------- token lift


class TokenFSM:
    """The engine-facing automaton: dense token transitions + the
    precomputed per-state allow-mask table.

    ``next_state`` is int32 ``[n_states, V]`` (-1 = illegal);
    ``mask = next_state >= 0`` is the ``[n_states, V]`` token-mask
    table the engine stamps per slot; ``accepting`` is bool
    ``[n_states]`` (the engine ORs the eos column in for these).
    State 0 is the start state. Host-side only — the device ever sees
    one ``[S, V]`` bool array per tick.
    """

    def __init__(self, next_state: np.ndarray, accepting: np.ndarray,
                 spec_key: str):
        self.next_state = next_state
        self.accepting = accepting
        self.spec_key = spec_key
        self.n_states = int(next_state.shape[0])
        self.vocab_size = int(next_state.shape[1])
        self.start = 0
        self._mask = next_state >= 0

    def allow_row(self, state: int,
                  eos_token: Optional[int] = None) -> np.ndarray:
        """The ``[V]`` bool allow mask at ``state`` (a fresh copy — the
        engine stamps it into its per-slot array), with eos allowed iff
        the state is accepting."""
        row = self._mask[state].copy()
        if eos_token is not None and self.accepting[state] \
                and 0 <= eos_token < self.vocab_size:
            row[eos_token] = True
        return row

    def advance(self, state: int, token: int) -> int:
        """Next state after emitting ``token``; -1 = not a legal
        transition (an accepting-state eos, or a corrupted stream)."""
        if not 0 <= token < self.vocab_size:
            return -1
        return int(self.next_state[state, token])

    def advance_many(self, tokens: Sequence[int],
                     eos_token: Optional[int] = None) -> int:
        """Re-derive the state for an already-emitted stream (replay /
        drain-restore / fleet migration — FSM state is never
        snapshotted, exactly like KV). A trailing eos that closed an
        accepting state is consumed without a transition; any other
        illegal token means the stream does not belong to this grammar
        and raises."""
        state = self.start
        toks = list(tokens)
        for i, t in enumerate(toks):
            nxt = self.advance(state, int(t))
            if nxt < 0:
                if (eos_token is not None and int(t) == eos_token
                        and i == len(toks) - 1
                        and self.accepting[state]):
                    return state
                raise ValueError(
                    f"token {t} at position {i} is not accepted by the "
                    "constraint (corrupted replay stream?)")
            state = nxt
        return state

    def is_dead_end(self, state: int,
                    eos_token: Optional[int] = None) -> bool:
        """No legal token and no eos escape: the stream is COMPLETE
        (trimming guarantees a dead-end state is accepting — the engine
        settles it with ``FinishReason.GRAMMAR``)."""
        if self._mask[state].any():
            return False
        return not (eos_token is not None and self.accepting[state]
                    and 0 <= eos_token < self.vocab_size)

    def accepts(self, tokens: Sequence[int],
                eos_token: Optional[int] = None) -> bool:
        """Full-sequence membership test (the tests' referee: every
        constrained stream's output must satisfy this)."""
        try:
            state = self.advance_many(tokens, eos_token=eos_token)
        except ValueError:
            return False
        return bool(self.accepting[state])


def token_fsm_from_regex(pattern: str,
                         token_strings: Sequence[str],
                         spec_key: str = "") -> TokenFSM:
    """Lift a character DFA to the token vocabulary: token ``t`` is
    legal at state ``s`` iff running its string through the DFA from
    ``s`` survives into a live state. Tokens with empty strings (pads,
    specials outside the grammar's alphabet) are never legal."""
    alphabet = set()
    for s in token_strings:
        alphabet.update(s or "")
    alphabet.update(c for c in pattern if c not in _METACHARS)
    dfa = build_char_dfa(pattern, alphabet)
    n = len(dfa.trans)
    v = len(token_strings)
    next_state = np.full((n, v), -1, np.int32)
    for s in range(n):
        if not dfa.live[s]:
            continue
        for t, text in enumerate(token_strings):
            if not text:
                continue
            tgt = dfa.run(s, text)
            if tgt >= 0:
                next_state[s, t] = tgt
    accepting = np.array(dfa.accepting, bool)
    # TOKEN-level trim on top of the character-level one: the DFA may
    # have states reachable only through character paths no token
    # tiling can complete (e.g. the grammar needs a character the
    # vocabulary lacks mid-pattern). Masks must never steer a stream
    # into such a state — a "complete" (dead-end) state must imply the
    # output is ACCEPTED. Fixpoint: a state is token-live iff accepting
    # or some token transition reaches a token-live state; transitions
    # into non-live states are erased.
    live_t = accepting.copy()
    changed = True
    while changed:
        changed = False
        for s in range(n):
            if live_t[s]:
                continue
            tgts = next_state[s]
            if np.any((tgts >= 0) & live_t[np.clip(tgts, 0, n - 1)]):
                live_t[s] = True
                changed = True
    dead_tgt = (next_state >= 0) \
        & ~live_t[np.clip(next_state, 0, n - 1)]
    next_state[dead_tgt] = -1
    fsm = TokenFSM(next_state, accepting, spec_key)
    if not live_t[0]:
        raise RegexError(
            f"pattern {pattern!r}: no vocabulary token path can "
            "complete a match (token strings don't tile the language)")
    return fsm


# ---------------------------------------------------- JSON Schema lower

_JSON_STRING_RE = '"[^"\\\\]*"'  # no escapes inside — the v1 subset
_JSON_INT_RE = "(-?(0|[1-9][0-9]*))"
_JSON_NUM_RE = "(-?(0|[1-9][0-9]*)(\\.[0-9]+)?)"


def json_schema_to_regex(schema: Dict[str, object]) -> str:
    """A (restricted) JSON Schema → the regex of its canonical
    serialization: objects serialize their ``properties`` in DECLARED
    order with every property required and no whitespace (the canonical
    form the mask FORCES the model to emit — that determinism is the
    feature, not a bug: the closing ``}`` is a no-out-edge accepting
    state, so generation terminates exactly at a complete document).

    Supported: ``type`` string/integer/number/boolean, ``enum`` (JSON
    scalars), ``object`` with ``properties``, ``array`` with ``items``
    (optionally ``minItems`` 0/1). Anything else raises — silently
    approximating a schema would defeat the "output always validates"
    contract."""
    if "enum" in schema:
        opts = [_regex_escape(json.dumps(v, separators=(",", ":")))
                for v in schema["enum"]]  # type: ignore[index]
        if not opts:
            raise ValueError("enum must be non-empty")
        return "(" + "|".join(opts) + ")"
    t = schema.get("type")
    if t == "string":
        return _JSON_STRING_RE
    if t == "integer":
        return _JSON_INT_RE
    if t == "number":
        return _JSON_NUM_RE
    if t == "boolean":
        return "(true|false)"
    if t == "object":
        props = schema.get("properties", {})
        if not isinstance(props, dict) or not props:
            raise ValueError(
                "object schemas need non-empty 'properties' (the v1 "
                "subset serializes every property, in declared order)")
        parts = []
        for key, sub in props.items():
            parts.append(_regex_escape(json.dumps(str(key))) + ":"
                         + json_schema_to_regex(sub))
        return "{" + ",".join(parts) + "}"
    if t == "array":
        if "items" not in schema:
            raise ValueError("array schemas need 'items'")
        item = json_schema_to_regex(schema["items"])  # type: ignore[arg-type]
        body = f"({item}(,{item})*)"
        if int(schema.get("minItems", 0)) < 1:  # type: ignore[arg-type]
            body += "?"
        return "\\[" + body + "\\]"
    raise ValueError(f"unsupported schema for constrained decoding: "
                     f"{schema!r}")


# ------------------------------------------------------------ spec API


def constraint_key(spec: Dict[str, object]) -> str:
    """Canonical cache/wire key of a constraint spec dict."""
    return json.dumps(spec, sort_keys=True, separators=(",", ":"))


# Module-level compile cache: TokenFSMs are immutable (per-request
# state lives in the engine as a plain int), so one compiled automaton
# can serve every engine in the process — N replicas/restarts of the
# same deployment pay one compile per (spec, vocabulary), not one per
# engine. Bounded FIFO: a runaway spec generator cannot grow it
# forever.
_FSM_CACHE: Dict[tuple, TokenFSM] = {}
_FSM_CACHE_CAP = 256


def compile_constraint(spec: Dict[str, object],
                       token_strings: Sequence[str]) -> TokenFSM:
    """The one entry point the engine uses: a JSON-able spec dict —
    ``{"kind": "regex", "pattern": ...}`` or ``{"kind": "json_schema",
    "schema": {...}}`` — plus the engine's token-id → string vocabulary,
    to a :class:`TokenFSM` (process-wide cached). Raises ``ValueError``
    on malformed specs (the engine validates at ``submit()`` so bad
    constraints reject the REQUEST, never fault a tick)."""
    cache_key = (constraint_key(spec) if isinstance(spec, dict) else None,
                 tuple(token_strings))
    cached = _FSM_CACHE.get(cache_key)
    if cached is not None:
        return cached
    fsm = _compile_constraint_uncached(spec, token_strings)
    if len(_FSM_CACHE) >= _FSM_CACHE_CAP:
        _FSM_CACHE.pop(next(iter(_FSM_CACHE)))
    _FSM_CACHE[cache_key] = fsm
    return fsm


def _compile_constraint_uncached(spec, token_strings) -> TokenFSM:
    if not isinstance(spec, dict):
        raise ValueError(f"constraint spec must be a dict, got "
                         f"{type(spec).__name__}")
    kind = spec.get("kind")
    if kind == "regex":
        pattern = spec.get("pattern")
        if not isinstance(pattern, str) or not pattern:
            raise ValueError("regex constraint needs a non-empty "
                             "'pattern' string")
    elif kind == "json_schema":
        schema = spec.get("schema")
        if not isinstance(schema, dict):
            raise ValueError("json_schema constraint needs a 'schema' "
                             "dict")
        pattern = json_schema_to_regex(schema)
    else:
        raise ValueError(
            f"unknown constraint kind {kind!r} (expected 'regex' or "
            "'json_schema')")
    return token_fsm_from_regex(pattern, token_strings,
                                spec_key=constraint_key(spec))


def encode_text(text: str, token_strings: Sequence[str]) -> List[int]:
    """Greedy longest-match tokenizer over ``token_strings`` (test/
    bench convenience for building prompts in grammar vocabularies;
    raises when ``text`` cannot be tiled)."""
    by_len = sorted(((s, i) for i, s in enumerate(token_strings) if s),
                    key=lambda p: -len(p[0]))
    out: List[int] = []
    pos = 0
    while pos < len(text):
        for s, i in by_len:
            if text.startswith(s, pos):
                out.append(i)
                pos += len(s)
                break
        else:
            raise ValueError(f"cannot tokenize {text[pos:pos+8]!r} with "
                             "the given token strings")
    return out


def decode_tokens(tokens: Sequence[int],
                  token_strings: Sequence[str],
                  eos_token: Optional[int] = None) -> str:
    """Token ids → text (dropping a trailing eos) — the referee-side
    inverse of :func:`encode_text`."""
    toks = list(tokens)
    if eos_token is not None and toks and toks[-1] == eos_token:
        toks = toks[:-1]
    return "".join(token_strings[t] for t in toks)
