"""Training orchestration: the reference's ``compile``/``fit`` layer.

The reference drives everything through ``keras.Model.compile`` + ``fit``
(``/root/reference/imagenet-resnet50.py:62,67``) with callbacks. Here that
surface is a custom SPMD loop: a jitted ``train_step``/``eval_step`` over a
mesh, an epoch driver, and a Keras-compatible callback engine.
"""

from pddl_tpu.train.state import (
    TrainState,
    make_optimizer,
    make_schedule,
    get_learning_rate,
    set_learning_rate,
)
from pddl_tpu.train.loop import Trainer
from pddl_tpu.train.history import History
from pddl_tpu.train import callbacks
from pddl_tpu.train import metrics
from pddl_tpu.train.faults import (
    FaultKind,
    FaultSpec,
    TrainFaultPlan,
    TrainStateLost,
)

__all__ = [
    "TrainState",
    "Trainer",
    "History",
    "callbacks",
    "metrics",
    "FaultKind",
    "FaultSpec",
    "TrainFaultPlan",
    "TrainStateLost",
    "make_optimizer",
    "make_schedule",
    "get_learning_rate",
    "set_learning_rate",
]
